"""Multi-host distributed bootstrap: rendezvous + jax.distributed init.

Rebuild of the reference's driver-rendezvous control plane
(ref: lightgbm/src/main/scala/com/microsoft/ml/spark/lightgbm/LightGBMBase.scala:394-432
``createDriverNodesThread`` — driver ServerSocket collects each task's
``host:port``, broadcasts the full node list; TrainUtils.scala:236-295
``getNetworkInitNodes``/``networkInit`` with exponential-backoff retries;
vw/.../VowpalWabbitBase.scala:434-462 spanning-tree rendezvous).

TPU-native difference: the exchanged roster does not seed a native socket
ring — it seeds ``jax.distributed.initialize``, after which the data plane
is XLA collectives over ICI/DCN. The rendezvous only runs once per job to
agree on (coordinator_address, num_processes, process_id); per-iteration
traffic never touches these sockets.

Typical multi-host flow (one process per TPU host):
    roster = rendezvous(driver_addr, my_host, num_workers)   # all hosts
    initialize_from_roster(roster)                           # jax.distributed
    mesh = build_mesh(jax.devices(), want={"dp": ...})       # global mesh
Single-host (or driverless) use: ``initialize()`` no-ops when jax is
already initialized or when num_processes == 1.
"""
from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
from typing import Dict, List, Optional, Sequence

import jax

from synapseml_tpu.utils.fault import retry_with_backoff

# -- shard_map compat shim --------------------------------------------------
# The pinned jax (0.4.37) ships shard_map at jax.experimental.shard_map
# with a ``check_rep=`` kwarg; newer jax promotes it to ``jax.shard_map``
# and renames the kwarg ``check_vma=``. Every module (and test) imports
# the symbol from HERE so the package runs on either side of the move —
# `from jax import shard_map` at module scope is what broke the
# distributed test collection on the pinned jax.
try:  # pinned jax: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_impl
except ImportError:  # post-0.4.37: promoted into the jax namespace
    from jax import shard_map as _shard_map_impl  # type: ignore

try:
    import inspect as _inspect

    _SHARD_MAP_KWARGS = frozenset(
        _inspect.signature(_shard_map_impl).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _SHARD_MAP_KWARGS = frozenset()


def shard_map(f, *args, **kwargs):
    """``shard_map`` resolved against the installed jax, with the
    ``check_vma``/``check_rep`` rename translated in whichever direction
    the implementation needs — callers write either spelling."""
    for ours, theirs in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if ours in kwargs and ours not in _SHARD_MAP_KWARGS \
                and theirs in _SHARD_MAP_KWARGS:
            kwargs[theirs] = kwargs.pop(ours)
    return _shard_map_impl(f, *args, **kwargs)


_COORD_PORT_DEFAULT = 12421  # near the reference's DefaultLocalListenPort
_state = {"initialized": False}


@dataclasses.dataclass(frozen=True)
class WorkerInfo:
    """One process's identity in the rendezvous roster."""
    host: str
    rank_hint: int = -1

    def to_json(self) -> str:
        return json.dumps({"host": self.host, "rank_hint": self.rank_hint})

    @staticmethod
    def from_json(s: str) -> "WorkerInfo":
        d = json.loads(s)
        return WorkerInfo(host=d["host"], rank_hint=d.get("rank_hint", -1))


class DriverRendezvous:
    """Driver-side roster collector (createDriverNodesThread analogue).

    Accepts ``num_workers`` connections; each worker sends one JSON line
    (its :class:`WorkerInfo`), the driver replies to every worker with the
    full ordered roster plus the worker's assigned process index.
    """

    def __init__(self, num_workers: int, host: str = "0.0.0.0",
                 port: int = 0, timeout: float = 120.0):
        self.num_workers = num_workers
        self.timeout = timeout
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(num_workers)
        self.port = self._srv.getsockname()[1]
        self._thread: Optional[threading.Thread] = None
        self.roster: List[WorkerInfo] = []
        self.error: Optional[BaseException] = None

    def run(self):
        """Block until all workers announced and were answered."""
        conns = []
        self._srv.settimeout(self.timeout)
        try:
            while len(conns) < self.num_workers:
                conn, _ = self._srv.accept()
                # per-connection deadline: a connected-but-silent worker
                # must not hang the whole rendezvous
                conn.settimeout(self.timeout)
                line = conn.makefile("r").readline()
                conns.append((conn, WorkerInfo.from_json(line)))
            # deterministic order: by rank hint, then host, then arrival
            order = sorted(range(len(conns)),
                           key=lambda i: (conns[i][1].rank_hint,
                                          conns[i][1].host, i))
            self.roster = [conns[i][1] for i in order]
            ranks = {i: r for r, i in enumerate(order)}
            payload_base = [dataclasses.asdict(w) for w in self.roster]
            for i, (conn, _) in enumerate(conns):
                msg = json.dumps({"roster": payload_base,
                                  "process_id": ranks[i]}) + "\n"
                conn.sendall(msg.encode())
        except Exception as e:
            self.error = e  # surfaced via wait()
        except BaseException as e:
            # record for wait(), then re-raise: an injected
            # faults.ThreadKilled (or KeyboardInterrupt) must terminate
            # the collector thread, not vanish into self.error
            self.error = e
            raise
        finally:
            for conn, _ in conns:
                conn.close()
            self._srv.close()

    def start(self) -> "DriverRendezvous":
        # synlint: disable=RL001 - one-shot collector, not a serving
        # loop: errors are recorded above and re-raised by wait()
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def wait(self):
        """Join the collector; raises if rendezvous failed or is incomplete
        (a silent empty roster must not look like success)."""
        if self._thread is not None:
            # run() legitimately takes up to one timeout per worker (accept
            # + readline each reset the clock); joining for less would
            # declare failure while the thread later hands out ranks
            self._thread.join(self.timeout * (self.num_workers + 1))
        if self.error is not None:
            raise RuntimeError(
                f"rendezvous failed after collecting "
                f"{len(self.roster)}/{self.num_workers} workers"
            ) from self.error
        if len(self.roster) != self.num_workers:
            raise RuntimeError(
                f"rendezvous incomplete: {len(self.roster)}/"
                f"{self.num_workers} workers announced")


def announce(driver_host: str, driver_port: int, info: WorkerInfo,
             timeout: float = 120.0) -> Dict:
    """Worker side (getNetworkInitNodes analogue): send identity, receive
    ``{"roster": [...], "process_id": int}``. Retries with backoff — the
    driver may not be listening yet (TrainUtils.scala:279-295)."""

    def attempt():
        with socket.create_connection((driver_host, driver_port),
                                      timeout=timeout) as s:
            s.sendall((info.to_json() + "\n").encode())
            data = s.makefile("r").readline()
            return json.loads(data)

    # ~2-minute ladder: worker pods routinely start before the driver binds
    # its port (ref: TrainUtils.networkInit's long retry window)
    return retry_with_backoff(
        attempt,
        backoffs_ms=(100, 500, 1000, 2000, 5000, 10000, 15000, 30000, 60000),
        retryable=(ConnectionError, OSError, json.JSONDecodeError))


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> bool:
    """Join the jax distributed runtime (DCN control plane).

    Falls back to env (JAX's own vars, then ``SYNAPSEML_COORDINATOR`` /
    ``SYNAPSEML_NUM_PROCESSES`` / ``SYNAPSEML_PROCESS_ID``). No-op (returns
    False) for single-process jobs or when already initialized; retries
    with backoff otherwise, mirroring the reference's networkInit ladder.
    """
    if _state["initialized"]:
        return False
    already = getattr(jax.distributed, "is_initialized", None)
    if already is not None and already():
        # initialized outside this module (auto-init on a pod, another
        # library): adopt it, don't retry into a permanent error
        _state["initialized"] = True
        return False
    coordinator_address = coordinator_address or os.environ.get(
        "SYNAPSEML_COORDINATOR")
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("SYNAPSEML_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("SYNAPSEML_PROCESS_ID", "0"))
    if num_processes <= 1 and coordinator_address is None:
        return False

    def attempt():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids)
        return True

    retry_with_backoff(attempt, backoffs_ms=(500, 1000, 5000, 10000))
    _state["initialized"] = True
    return True


def initialize_from_roster(reply: Dict,
                           coordinator_port: int = _COORD_PORT_DEFAULT
                           ) -> bool:
    """Turn a rendezvous reply into a jax.distributed join: roster[0] hosts
    the coordination service."""
    roster = reply["roster"]
    return initialize(
        coordinator_address=f"{roster[0]['host']}:{coordinator_port}",
        num_processes=len(roster),
        process_id=int(reply["process_id"]))


def rendezvous_and_initialize(driver_host: str, driver_port: int,
                              my_host: Optional[str] = None,
                              rank_hint: int = -1,
                              coordinator_port: int = _COORD_PORT_DEFAULT
                              ) -> Dict:
    """One-call worker bootstrap: announce to the driver, then join the
    distributed runtime with the agreed roster. Returns the reply dict."""
    info = WorkerInfo(host=my_host or socket.gethostname(),
                      rank_hint=rank_hint)
    reply = announce(driver_host, driver_port, info)
    initialize_from_roster(reply, coordinator_port)
    return reply


def global_mesh(want: Optional[Dict[str, int]] = None):
    """All-process mesh over every device in the (initialized) job."""
    from synapseml_tpu.parallel.mesh import build_mesh

    return build_mesh(jax.devices(), want=want)


def host_allgather_rows(a):
    """Bit-exact allgather of per-host row blocks (ragged first dim).

    Hosts contribute different row counts: pad to the global max, gather,
    trim. Any 8-byte dtype (float64/int64) rides as uint32 words — jax
    would canonicalize 64-bit values to 32-bit with x64 disabled, and a
    rounding that crosses a bin quantile (or merges two query ids) would
    silently break fit identities. Returns the concatenation in process
    order. Single-process: returns ``a`` unchanged (already contiguous).
    """
    import numpy as np
    from jax.experimental import multihost_utils

    a = np.ascontiguousarray(a)
    if jax.process_count() == 1:
        return a
    n_all = np.asarray(multihost_utils.process_allgather(
        np.asarray([a.shape[0]]))).reshape(-1)
    # keep the collective well-shaped even when every host is empty
    n_max = max(int(n_all.max()), 1)
    dt = a.dtype
    if dt.itemsize % 4:
        raise TypeError(f"host_allgather_rows needs 4/8-byte dtypes, got {dt}")
    a = np.ascontiguousarray(
        np.pad(a, [(0, n_max - a.shape[0])] + [(0, 0)] * (a.ndim - 1)))
    words = a.view(np.uint32).reshape(n_max, -1)
    out = np.asarray(multihost_utils.process_allgather(words))
    out = out.reshape(len(n_all), n_max, -1)
    return np.concatenate([
        out[i, :n_all[i]].reshape(-1).view(dt).reshape(
            (n_all[i],) + a.shape[1:])
        for i in range(len(n_all))])
