"""Mixture-of-Experts layer with expert parallelism over the ``ep`` mesh axis.

Absent in the reference (SURVEY.md §2.10) but first-class here. Round-1
implementation is dense-dispatch: every expert's FFN is evaluated for every
token as one big einsum with the expert dimension sharded over ``ep`` (GSPMD
turns the final combine into a reduce over ICI). This keeps shapes static
(XLA-friendly, no capacity-overflow dynamic shapes); a capacity-based
all-to-all dispatch is the planned optimization for large expert counts.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _wsc(x, spec):
    return jax.lax.with_sharding_constraint(x, spec)


def topk_gating(gate_logits: jnp.ndarray, top_k: int):
    """Top-k softmax gating with renormalization. gate_logits: [..., E]."""
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_vals, _ = jax.lax.top_k(probs, top_k)
    threshold = top_vals[..., -1:]
    masked = jnp.where(probs >= threshold, probs, 0.0)
    return masked / (masked.sum(axis=-1, keepdims=True) + 1e-9)


def moe_ffn(
    x: jnp.ndarray,          # [B, S, D]
    gate_w: jnp.ndarray,     # [D, E]
    w1: jnp.ndarray,         # [E, D, F]
    w2: jnp.ndarray,         # [E, F, D]
    *,
    top_k: int = 2,
    activation=jax.nn.gelu,
    expert_spec: Optional[P] = None,
):
    """Dense-dispatch MoE feed-forward. Returns ([B,S,D], aux_loss)."""
    gates = topk_gating(jnp.einsum("bsd,de->bse", x, gate_w), top_k)  # [B,S,E]
    h = jnp.einsum("bsd,edf->bsef", x, w1)
    if expert_spec is not None:
        h = _wsc(h, expert_spec)
    h = activation(h)
    y = jnp.einsum("bsef,efd->bsed", h, w2)
    out = jnp.einsum("bse,bsed->bsd", gates.astype(y.dtype), y)
    # load-balancing auxiliary loss (Switch-style): E * sum_e f_e * p_e
    e = gate_w.shape[-1]
    frac = (gates > 0).astype(jnp.float32).mean(axis=(0, 1))
    prob = gates.mean(axis=(0, 1))
    aux = e * jnp.sum(frac * prob)
    return out, aux
