"""Tensor-parallel inference for imported ONNX graphs.

The reference's ONNX path is single-GPU-per-partition (one ORT session
per executor, deep-learning/.../onnx/ONNXModel.scala:497-508); model
parallelism is out of its reach. Here an imported graph's ``apply`` is a
pure jax function, so sharding the PARAMETERS over a mesh axis is enough:
GSPMD propagates the layouts through every matmul and inserts the
all-reduces — no per-op rules, no graph surgery, any exporter's file.

Placement is decided by the rule registry in
:mod:`synapseml_tpu.parallel.partition_rules` (default: the Megatron
column layout — 2-D weights shard their last dim over ``axis``,
projection biases ride their weight's column sharding, anything that
does not divide replicates). ``rules=`` takes per-model overrides, and
every call can hand back a coverage report naming which rule claimed
each param. For a transformer this puts each rank's slice of every
projection in HBM — the model no longer needs to fit on one chip
(``param_bytes_per_device`` makes that claim checkable, and the test
suite asserts it).

Activations are replicated by default (right for classifier-shaped
outputs); ``batch_axis`` keeps inputs/outputs batch-sharded instead so
activation-heavy graphs don't re-materialize full tensors per device.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from synapseml_tpu.parallel.mesh import replicated
from synapseml_tpu.parallel.partition_rules import (
    CoverageReport, match_partition_rules)


def tp_shard_params(params: Dict[str, np.ndarray], mesh: Mesh,
                    axis: str = "tp",
                    rules: Optional[Sequence[Tuple[str, Any]]] = None,
                    with_report: bool = False):
    """Place a params dict on ``mesh`` by the partition-rule registry.

    ``rules`` prepends per-model overrides ahead of the default Megatron
    column layout; anything unmatched takes the divisibility fallback
    (column-shard 2-D float weights, replicate the rest). With
    ``with_report=True`` returns ``(placed, CoverageReport)``.
    """
    specs, report = match_partition_rules(
        params, mesh, axis=axis, overrides=rules)
    out: Dict[str, Any] = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()}
    return (out, report) if with_report else out


def param_bytes_per_device(params: Dict[str, Any]) -> Dict[Any, int]:
    """Actual parameter bytes resident on each device — the tested form
    of the "model no longer needs to fit on one chip" claim."""
    per_dev: Dict[Any, int] = {}
    for v in jax.tree_util.tree_leaves(params):
        if not hasattr(v, "addressable_shards"):
            continue
        for s in v.addressable_shards:
            per_dev[s.device] = per_dev.get(s.device, 0) + s.data.nbytes
    return per_dev


def tp_jit(graph, mesh: Mesh, axis: str = "tp",
           batch_axis: Optional[str] = None,
           rules: Optional[Sequence[Tuple[str, Any]]] = None,
           with_report: bool = False):
    """(sharded_params, jitted_fn): run ``graph`` tensor-parallel.

    ``jitted_fn(params, *inputs)`` lets GSPMD carry the registry-placed
    weights through the graph — numerically identical to single-device
    ``graph.apply``. ``rules`` forwards per-model overrides to the
    registry; ``with_report=True`` appends the coverage report to the
    return tuple.

    With ``batch_axis=None`` (default) inputs and outputs replicate —
    right for classifiers, where activations are small next to weights.
    With ``batch_axis="dp"`` (or any mesh axis) inputs/outputs stay
    sharded over their leading batch dimension, so an activation-heavy
    graph never materializes a full-batch tensor on any one device;
    the leading dim of every array input must divide the axis size.
    """
    params, report = tp_shard_params(
        graph.params, mesh, axis, rules=rules, with_report=True)
    rep = replicated(mesh)
    n_b = mesh.shape[batch_axis] if batch_axis is not None else 1
    io_sh = NamedSharding(mesh, P(batch_axis)) if batch_axis else rep

    def fn(p, *inputs):
        return graph.apply(p, *inputs)

    jitted = jax.jit(fn, out_shardings=io_sh)

    checked_out = []

    def run(p, *inputs):
        # device-resident inputs (a previous stage's output) re-shard
        # without the D2H round trip np.asarray would force
        placed = []
        for x in inputs:
            x = x if isinstance(x, jax.Array) else np.asarray(x)
            if batch_axis is not None and x.ndim:
                if x.shape[0] % n_b:
                    raise ValueError(
                        f"batch_axis={batch_axis!r}: leading dim "
                        f"{x.shape[0]} does not divide axis size {n_b}")
                placed.append(jax.device_put(x, io_sh))
            else:
                placed.append(jax.device_put(x, rep))
        if batch_axis is not None and not checked_out:
            # validate every OUTPUT is batch-shardable before GSPMD
            # fails compilation with an error naming no tensor
            outs = jax.eval_shape(fn, p, *placed)
            for i, o in enumerate(jax.tree_util.tree_leaves(outs)):
                if not o.shape or o.shape[0] % n_b:
                    raise ValueError(
                        f"batch_axis={batch_axis!r}: graph output {i} has "
                        f"shape {o.shape}, whose leading dim cannot shard "
                        f"over axis size {n_b} — use batch_axis=None for "
                        "graphs with reduced/batchless outputs")
            checked_out.append(True)
        return jitted(p, *placed)

    return (params, run, report) if with_report else (params, run)
