"""Tensor-parallel inference for imported ONNX graphs.

The reference's ONNX path is single-GPU-per-partition (one ORT session
per executor, deep-learning/.../onnx/ONNXModel.scala:497-508); model
parallelism is out of its reach. Here an imported graph's ``apply`` is a
pure jax function, so sharding the PARAMETERS over a mesh axis is enough:
GSPMD propagates the layouts through every matmul and inserts the
all-reduces — no per-op rules, no graph surgery, any exporter's file.

Heuristic (the Megatron column layout): 2-D float weights shard their
LAST dim over ``axis``; 1-D biases that feed the same activations
replicate (GSPMD re-shards them as needed). Weights whose dims don't
divide the axis size stay replicated. For a transformer this puts each
rank's slice of every projection in HBM — the model no longer needs to
fit on one chip.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from synapseml_tpu.parallel.mesh import replicated


def tp_shard_params(params: Dict[str, np.ndarray], mesh: Mesh,
                    axis: str = "tp") -> Dict[str, Any]:
    """Place a params dict on ``mesh`` with 2-D weights column-sharded
    over ``axis`` (replicating anything that does not divide)."""
    n = mesh.shape[axis]
    rep = replicated(mesh)
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if (v.ndim == 2 and np.issubdtype(v.dtype, np.floating)
                and v.shape[-1] % n == 0 and v.shape[-1] >= n):
            out[k] = jax.device_put(
                v, NamedSharding(mesh, P(None, axis)))
        else:
            out[k] = jax.device_put(v, rep)
    return out


def tp_jit(graph, mesh: Mesh, axis: str = "tp"):
    """(sharded_params, jitted_fn): run ``graph`` tensor-parallel.

    ``jitted_fn(params, *inputs)`` replicates inputs, lets GSPMD carry
    the column-sharded weights through the graph, and returns replicated
    outputs — numerically identical to single-device ``graph.apply``.
    """
    params = tp_shard_params(graph.params, mesh, axis)
    rep = replicated(mesh)

    def fn(p, *inputs):
        return graph.apply(p, *inputs)

    jitted = jax.jit(fn, out_shardings=rep)

    def run(p, *inputs):
        # device-resident inputs (a previous stage's output) re-shard
        # without the D2H round trip np.asarray would force
        placed = [jax.device_put(
            x if isinstance(x, jax.Array) else np.asarray(x), rep)
            for x in inputs]
        return jitted(p, *placed)

    return params, run
