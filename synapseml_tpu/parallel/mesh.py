"""Device-mesh bootstrap — the TPU-native control/data plane.

Replaces the reference's socket rendezvous + native ring topologies
(ref: lightgbm/.../LightGBMBase.scala:394-432 createDriverNodesThread,
lightgbm/.../TrainUtils.scala:236-295 getNetworkInitNodes/networkInit,
vw/.../VowpalWabbitBase.scala:434-462 spanning tree): instead of exchanging
``host:port`` lists over TCP and letting the native engine build its own
collectives, we build a named :class:`jax.sharding.Mesh` over the slice and
let XLA insert ICI collectives (psum / all_gather / reduce_scatter /
ppermute). Multi-host joins the mesh via ``jax.distributed.initialize`` —
see :mod:`synapseml_tpu.parallel.distributed`.

Mesh axes (the framework's canonical names):
  dp — data parallel (batch)          sp — sequence/context parallel
  pp — pipeline parallel (stages)     tp — tensor parallel (heads / ffn)
  ep — expert parallel (MoE)
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "sp", "tp", "ep")


def _prime_factors(n: int) -> List[int]:
    out, d = [], 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return sorted(out, reverse=True)


def factor_axes(
    n_devices: int,
    want: Optional[Dict[str, int]] = None,
) -> Dict[str, int]:
    """Factor ``n_devices`` into the five canonical axes.

    Explicit sizes in ``want`` are honored (their product must divide
    n_devices); the remainder is distributed round-robin over the unpinned
    model axes (tp, sp, pp) before spilling into dp, so a pure power-of-two
    slice exercises every parallelism style.
    """
    want = dict(want or {})
    sizes = {a: want.get(a, 0) for a in AXES}
    pinned = int(np.prod([v for v in sizes.values() if v > 0])) if any(
        v > 0 for v in sizes.values()) else 1
    if n_devices % pinned != 0:
        raise ValueError(
            f"pinned axes product {pinned} does not divide {n_devices}")
    rest = n_devices // pinned
    free = [a for a in ("tp", "sp", "pp") if sizes[a] == 0]
    for a in AXES:
        if sizes[a] == 0:
            sizes[a] = 1
    for p in _prime_factors(rest):
        # spread model-parallel factors first, then pile the rest onto dp
        target = None
        for a in free:
            if sizes[a] == 1:
                target = a
                break
        if target is None:
            target = "dp"
        sizes[target] *= p
    assert int(np.prod(list(sizes.values()))) == n_devices
    return sizes


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    want: Optional[Dict[str, int]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = factor_axes(len(devices), want)
    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape((1,) * len(AXES)), AXES)


# -- sharding helpers -------------------------------------------------------

def data_sharding(mesh: Mesh, *trailing: Optional[str]) -> NamedSharding:
    """Batch axis sharded over dp (and sp if free); trailing dims as given."""
    return NamedSharding(mesh, P("dp", *trailing))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, arr, batch_axes: Tuple[str, ...] = ("dp",)):
    return jax.device_put(arr, NamedSharding(mesh, P(batch_axes)))


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name]


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0) -> Tuple[np.ndarray, int]:
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths), n
