"""Regex-path → PartitionSpec rule registry for imported param pytrees.

``tp_shard_params`` used to decide placement with one hardcoded
heuristic — "2-D float weight whose last dim divides the axis" — which
replicates every bias (even ones feeding column-sharded activations) and
gives a model author no way to steer placement for an unusual layer. This
module replaces that heuristic with the registry pattern used by the big
JAX LLM codebases: an ORDERED list of ``(regex, PartitionSpec)`` rules
matched against each param's path, first match wins, with per-model
overrides simply prepended ahead of the defaults.

Matching never raises and never produces an uncompilable layout:

* a param no rule matches falls back to the divisibility heuristic
  (column-shard a 2-D float weight when its last dim divides the axis,
  else replicate);
* a rule that DOES match but names an axis the param's dim cannot divide
  degrades to replicate — logged, and recorded in the coverage report —
  instead of letting GSPMD fail compilation with an error naming no
  tensor;
* scalars always replicate.

Bias pairing is the one stateful rule: :data:`BIAS_PAIR` is a sentinel
rule value meaning "shard this 1-D param over ``axis`` IFF a weight its
name pairs with (``l0_q_b`` ↔ ``l0_q_w``, ``foo.bias`` ↔ ``foo.weight``)
resolved to a column-sharded layout with a matching output dim". That is
the registry form of the old 1-D bug fix: projection biases ride their
weight's column sharding, while layernorm betas (whose pair is a 1-D
scale, never column-sharded) stay replicated.

The **coverage report** names which rule claimed each param and why the
fallbacks fired, so ``/debug/memory`` and the tests can prove the layout
rather than trust it.
"""
from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

log = logging.getLogger("synapseml_tpu.parallel.partition_rules")

#: Sentinel rule value: shard a 1-D param over the axis iff its paired
#: weight is column-sharded (see module docstring). Usable in overrides.
BIAS_PAIR = "bias-pair"

_BIAS_TOKEN = re.compile(r"(?:^|[._])(?P<tok>bias|beta|b)(?P<suf>_\w+)?$")
_WEIGHT_TOKENS = ("w", "W", "weight", "kernel")


def as_spec(spec: Any) -> P:
    """Normalize a PartitionSpec-or-axes-sequence into a PartitionSpec.

    Accepts ``P(None, "tp")``, ``(None, "tp")``, ``[None, "tp"]`` or
    ``None`` (replicate) so rules survive a JSON round trip through the
    serving entry's ``partition_rules`` Param.
    """
    if spec is None:
        return P()
    if isinstance(spec, P):
        return spec
    if isinstance(spec, (list, tuple)):
        return P(*spec)
    raise TypeError(f"cannot convert {spec!r} to a PartitionSpec")


def default_rules(axis: str = "tp") -> List[Tuple[str, Any]]:
    """The REDUCTION-FREE column layout — deterministic across
    reshardings, the serving default.

    Only weights whose matmul stays free of cross-shard reductions are
    sharded: the attention input projections and the MLP expand half
    (column-parallel — each output feature is computed whole on one
    rank, the only collective is an all-gather, i.e. concatenation).
    The row-parallel halves (attention output / MLP contract) and the
    embedding tables replicate explicitly: sharding them makes GSPMD
    psum partial products, and a float sum re-associated across tp
    ranks is NOT the single-device sum — measured ~1e-6 wobble on the
    forced-8-device platform, which breaks the capture/replay digest
    contract (docs/serving.md). With these rules a model served at
    tp=1, tp=2 and tp=4 produces byte-identical replies; trade
    determinism for the extra memory with :func:`megatron_rules`.
    """
    col = (r"(^|[._])(q|k|v|query|key|value|wq|wk|wv|q_proj|k_proj"
           r"|v_proj|ff1|fc1|up_proj|gate_proj|wi|w1)"
           r"([._](w|weight|kernel))?$")
    row = (r"(^|[._])(o|out|attn_out|o_proj|out_proj|wo|dense|ff2|fc2"
           r"|down_proj|w2)([._](w|weight|kernel))?$")
    return [
        # BERT-style compound names: the ffn expand half
        (r"(^|[._])intermediate[._]dense[._](w|weight|kernel)$",
         P(None, axis)),
        (col, P(None, axis)),
        # row-parallel halves need a psum: replicate for bit-stability
        (row, P()),
        # feature-sharded embeddings put a layernorm reduction across
        # ranks; vocab-sharded ones need a masked psum — replicate
        (r"(emb|embed|embedding|wte|wpe)\w*$", P()),
        # biases shard iff their paired weight is column-sharded
        (_BIAS_TOKEN.pattern, BIAS_PAIR),
    ]


def megatron_rules(axis: str = "tp") -> List[Tuple[str, Any]]:
    """The full Megatron column layout: EVERY 2-D weight (embeddings
    included) shards its last dim over ``axis`` — maximum per-device
    memory savings, at the cost of cross-shard psums whose float
    re-association makes outputs differ from tp=1 at the ~1e-6 level
    (so capture digests do NOT survive a resharding). Pass as
    ``rules=``/overrides where HBM is the binding constraint."""
    return [
        # embedding/vocab tables: shard the embedding dim, not vocab
        (r"(emb|embed|embedding|wte|wpe)\w*$", P(None, axis)),
        # 2-D projection weights (importer names: <node>_w, .weight)
        (r"(\.|_|^)(w|weight|kernel)(_\w+)?$", P(None, axis)),
        # biases shard iff their paired weight is column-sharded
        (_BIAS_TOKEN.pattern, BIAS_PAIR),
    ]


@dataclass
class Claim:
    """Why one param got the layout it got — a coverage-report row."""
    param: str
    spec: P
    rule: Optional[str]          # regex text, or None for a fallback
    reason: str                  # "rule" | "bias_pair" | "degraded" | ...
    shape: Tuple[int, ...] = ()

    def as_dict(self) -> Dict[str, Any]:
        return {"param": self.param, "spec": str(self.spec),
                "rule": self.rule, "reason": self.reason,
                "shape": list(self.shape)}


@dataclass
class CoverageReport:
    """Per-param placement provenance, queryable and log-friendly."""
    claims: List[Claim] = field(default_factory=list)

    def add(self, claim: Claim) -> None:
        self.claims.append(claim)

    def by_reason(self, reason: str) -> List[Claim]:
        return [c for c in self.claims if c.reason == reason]

    def claims_by_name(self) -> Dict[str, Claim]:
        return {c.param: c for c in self.claims}

    def rule_for(self, param: str) -> Optional[str]:
        for c in self.claims:
            if c.param == param:
                return c.rule
        return None

    def spec_for(self, param: str) -> Optional[P]:
        for c in self.claims:
            if c.param == param:
                return c.spec
        return None

    def sharded(self) -> List[Claim]:
        return [c for c in self.claims if tuple(c.spec) != ()]

    def summary(self) -> Dict[str, Any]:
        reasons: Dict[str, int] = {}
        for c in self.claims:
            reasons[c.reason] = reasons.get(c.reason, 0) + 1
        return {"params": len(self.claims),
                "sharded": len(self.sharded()),
                "reasons": reasons}

    def as_dict(self) -> Dict[str, Any]:
        return {"summary": self.summary(),
                "claims": [c.as_dict() for c in self.claims]}


def _divisible(shape: Tuple[int, ...], spec: P,
               mesh_axes: Dict[str, int]) -> bool:
    """Every sharded dim must divide its axis-product; the spec must not
    name more dims than the param has."""
    if len(spec) > len(shape):
        return False
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        names = axes if isinstance(axes, tuple) else (axes,)
        size = 1
        for a in names:
            if a not in mesh_axes:
                return False
            size *= mesh_axes[a]
        if size > 1 and (dim < size or dim % size):
            return False
    return True


def _fallback_spec(shape: Tuple[int, ...], dtype: Any, axis: str,
                   n: int) -> Tuple[P, str]:
    """The pre-registry heuristic, kept as the miss path: column-shard a
    2-D float weight when its last dim divides, else replicate."""
    floating = dtype is not None and np.issubdtype(
        np.dtype(dtype), np.floating)
    if (len(shape) == 2 and floating and shape[-1] >= n
            and shape[-1] % n == 0):
        return P(None, axis), "fallback"
    return P(), "fallback_replicate"


def paired_weight_names(bias_name: str) -> List[str]:
    """Candidate weight names for a bias: the bias token swapped for
    each weight token (``l0_q_b`` → ``l0_q_w`` … ``l0_q_kernel``)."""
    m = _BIAS_TOKEN.search(bias_name)
    if not m:
        return []
    suf = m.group("suf") or ""
    return [bias_name[:m.start("tok")] + tok + suf
            for tok in _WEIGHT_TOKENS]


def _column_sharded(spec: Optional[P], axis: str) -> bool:
    if spec is None or not tuple(spec):
        return False
    last = tuple(spec)[-1]
    names = last if isinstance(last, tuple) else (last,)
    return axis in names


def match_partition_rules(
    params: Dict[str, Any],
    mesh: Mesh,
    rules: Optional[Sequence[Tuple[str, Any]]] = None,
    axis: str = "tp",
    overrides: Optional[Sequence[Tuple[str, Any]]] = None,
) -> Tuple[Dict[str, P], CoverageReport]:
    """Resolve a spec for every param: ``(specs, coverage)``.

    ``rules`` defaults to :func:`default_rules`; ``overrides`` (the
    per-model escape hatch) are prepended so they win over any default.
    First ``re.search`` match claims the param. A claimed param whose
    dims cannot divide the named axes degrades to replicate with a
    logged coverage warning; a missed param takes the divisibility
    fallback; scalars always replicate. :data:`BIAS_PAIR` claims resolve
    in a second pass once every weight's layout is known.
    """
    base = list(rules) if rules is not None else default_rules(axis)
    ordered: List[Tuple[str, Any]] = [
        (pat, s if (isinstance(s, str) and s == BIAS_PAIR) else as_spec(s))
        for pat, s in list(overrides or []) + base]
    mesh_axes = dict(mesh.shape)
    n = mesh_axes.get(axis, 1)
    specs: Dict[str, P] = {}
    report = CoverageReport()
    deferred: List[Tuple[str, str, Tuple[int, ...]]] = []  # name, pat, shape

    for name, v in params.items():
        shape = tuple(getattr(v, "shape", ()))
        dtype = getattr(v, "dtype", None)
        if len(shape) == 0:
            specs[name] = P()
            report.add(Claim(name, P(), None, "scalar", shape))
            continue
        claimed = None
        for pat, spec in ordered:
            if re.search(pat, name):
                claimed = (pat, spec)
                break
        if claimed is None:
            spec, reason = _fallback_spec(shape, dtype, axis, n)
            specs[name] = spec
            report.add(Claim(name, spec, None, reason, shape))
            continue
        pat, spec = claimed
        if isinstance(spec, str):  # BIAS_PAIR sentinel
            deferred.append((name, pat, shape))
            continue
        if tuple(spec) and not _divisible(shape, spec, mesh_axes):
            log.warning(
                "partition rule %r claimed %s%s but %s does not divide "
                "the mesh — degrading to replicate", pat, name,
                list(shape), str(spec))
            specs[name] = P()
            report.add(Claim(name, P(), pat, "degraded", shape))
            continue
        specs[name] = spec
        report.add(Claim(name, spec, pat, "rule", shape))

    # second pass: bias pairing against the now-resolved weight layouts
    for name, pat, shape in deferred:
        paired = None
        for cand in paired_weight_names(name):
            w = params.get(cand)
            if w is None:
                continue
            w_shape = tuple(getattr(w, "shape", ()))
            if (_column_sharded(specs.get(cand), axis)
                    and len(shape) == 1 and w_shape
                    and w_shape[-1] == shape[0]):
                paired = cand
                break
        if paired is None:
            specs[name] = P()
            report.add(Claim(name, P(), pat, "unpaired_bias", shape))
            continue
        spec = P(axis)
        if not _divisible(shape, spec, mesh_axes):
            log.warning(
                "bias %s pairs with column-sharded %s but %s does not "
                "divide axis %r — degrading to replicate", name, paired,
                list(shape), axis)
            specs[name] = P()
            report.add(Claim(name, P(), pat, "degraded", shape))
            continue
        specs[name] = spec
        report.add(Claim(name, spec, pat, "bias_pair", shape))
    return specs, report
