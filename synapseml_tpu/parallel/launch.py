"""Multi-host launcher: ``python -m synapseml_tpu.parallel.launch [script]``.

The container entry the k8s train-job chart runs (tools/k8s/chart/
templates/train-job.yaml): joins the jax distributed runtime from the
``SYNAPSEML_COORDINATOR`` / ``SYNAPSEML_NUM_PROCESSES`` /
``SYNAPSEML_PROCESS_ID`` environment (parallel/distributed.py — the
DCN control-plane analogue of the reference's NetworkInit socket
rendezvous, lightgbm/.../TrainUtils.scala networkInit), then either

- executes a user training script with the runtime live (torchrun-style:
  ``... launch my_train.py --epochs 3``), or
- with no script, runs a built-in smoke fit: a dp-sharded GBDT over the
  global mesh, proving every host joined and ICI/DCN collectives work.
"""
from __future__ import annotations

import os
import runpy
import sys


def _smoke_fit() -> int:
    import jax
    import numpy as np

    from jax.sharding import Mesh

    from synapseml_tpu.gbdt.boosting import BoostParams, train

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    rng = np.random.default_rng(jax.process_index())
    n = 4096
    x = rng.normal(size=(n, 8))
    y = (x[:, 0] + x[:, 1] * x[:, 2] > 0).astype(np.float64)
    booster = train(BoostParams(objective="binary", num_iterations=10,
                                num_leaves=15), x, y, mesh=mesh)
    auc_proxy = float(np.mean((booster.predict(x) > 0.5) == (y > 0.5)))
    print(f"[launch] process {jax.process_index()}/{jax.process_count()} "
          f"devices={len(devs)} smoke-fit acc={auc_proxy:.3f}", flush=True)
    return 0 if auc_proxy > 0.7 else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from synapseml_tpu.parallel import distributed

    joined = distributed.initialize()
    import jax

    print(f"[launch] distributed={'joined' if joined else 'single-process'} "
          f"process={jax.process_index()}/{jax.process_count()} "
          f"local_devices={jax.local_device_count()}", flush=True)
    ckpt = os.environ.get("SYNAPSEML_CHECKPOINT_DIR")
    if ckpt:
        os.makedirs(ckpt, exist_ok=True)
    if argv:
        script, sys.argv = argv[0], argv
        runpy.run_path(script, run_name="__main__")
        return 0
    return _smoke_fit()


if __name__ == "__main__":
    raise SystemExit(main())
