"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has no sequence parallelism (SURVEY.md §2.10: "absent in
reference"); it is first-class here because long-context models shard the
sequence dimension across chips. Design: blockwise attention with an online
softmax accumulator; K/V blocks rotate around the ``sp`` ring via
``lax.ppermute`` so each device only ever holds one sequence block of K/V
while computing attention for its local Q block. Communication overlaps the
per-block matmuls and total memory is O(S/sp) per chip.

Also provides Ulysses-style all-to-all sequence parallelism
(head-scatter/seq-gather) as an alternative when head count ≥ sp size.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from synapseml_tpu.parallel.collectives import axis_size
from synapseml_tpu.parallel.distributed import shard_map

NEG_INF = -1e30


def dense_attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None):
    """Reference (single-device) attention. q,k,v: [B, S, H, D]."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q * s, k)
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        logits = jnp.where((ki > qi)[None, None], NEG_INF, logits)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _block_step(q, k, v, m, l, o, mask):
    """One blockwise-attention accumulation step with online softmax.

    q: [B, Sq, H, D]; k,v: [B, Sk, H, D]; m,l: [B, H, Sq]; o: [B, Sq, H, D];
    mask: [Sq, Sk] boolean (True = attend) or None.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, axis: str, causal: bool, scale: float):
    """Body run per-device inside shard_map. q,k,v are local blocks."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n = axis_size(axis)
    rank = lax.axis_index(axis)

    q = (q * scale).astype(q.dtype)
    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    o = jnp.zeros((b, sq, h, d), jnp.float32)

    def compute(step, k_blk, v_blk, m, l, o):
        # K/V block currently held arrived from rank (rank - step) % n
        src = (rank - step) % n
        if causal:
            q_pos = rank * sq + jnp.arange(sq)[:, None]
            k_pos = src * sk + jnp.arange(sk)[None, :]
            mask = k_pos <= q_pos
        else:
            mask = None
        return _block_step(q, k_blk, v_blk, m, l, o, mask)

    def body(step, carry):
        k_blk, v_blk, m, l, o = carry
        m, l, o = compute(step, k_blk, v_blk, m, l, o)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return k_blk, v_blk, m, l, o

    # n-1 (compute, rotate) steps, then a final compute with no wasted rotate
    k, v, m, l, o = lax.fori_loop(0, n - 1, body, (k, v, m, l, o))
    m, l, o = compute(n - 1, k, v, m, l, o)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    head_axis: Optional[str] = "tp",
    batch_axis: str = "dp",
    causal: bool = False,
):
    """Build a ring-attention callable over ``mesh``.

    Inputs q,k,v are GLOBAL [B, S, H, D] arrays (jit-traced values); shard_map
    splits B over dp, S over sp, H over tp. Differentiable (ppermute has a
    transpose rule), so it drops into training steps.
    """
    spec = P(batch_axis, seq_axis, head_axis, None)

    def fn(q, k, v):
        scale = 1.0 / math.sqrt(q.shape[-1])
        local = partial(_ring_attention_local, axis=seq_axis,
                        causal=causal, scale=scale)
        return shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return fn


def make_serving_ring_attention(mesh: Mesh, *, causal: bool = False):
    """Ring attention over the SERVING ``dp×tp`` mesh.

    The serving runtime's tensor-parallel lane builds a 2-axis
    ``("dp", "tp")`` mesh (runtime/executor.py) — there is no dedicated
    ``sp`` axis in a scoring pod. For long-sequence transformer graphs
    the same ``tp`` ranks double as the K/V ring: the sequence shards
    over ``tp`` (heads stay local), the batch stays on ``dp``, and each
    chip holds O(S/tp) of K/V while blocks rotate via ``ppermute`` —
    context length scales with the tp degree using the mesh the
    partition-rule registry already placed the weights on.

    ``mesh`` must carry ``dp`` and ``tp`` axes; global q,k,v are
    [B, S, H, D] with B divisible by dp and S by tp."""
    names = tuple(mesh.axis_names)
    if "dp" not in names or "tp" not in names:
        raise ValueError(
            f"serving ring attention needs a dp×tp mesh, got axes {names}")
    return make_ring_attention(mesh, seq_axis="tp", head_axis=None,
                               batch_axis="dp", causal=causal)


def make_ulysses_attention(
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_axis: str = "dp",
    head_axis: Optional[str] = "tp",
    causal: bool = False,
):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Each device trades its sequence shard of all heads for all sequence of a
    head shard (all_to_all over sp), runs dense attention on whole sequences
    of its local heads, then trades back.  Requires H % (sp*tp) == 0.
    """
    spec = P(batch_axis, seq_axis, head_axis, None)

    def local(q, k, v):
        def a2a(x, split_head=True):
            # [B, S_loc, H_loc, D] -> [B, S, H_loc/sp, D] (or inverse)
            if split_head:
                return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1,
                                      tiled=True)
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qg, kg, vg = a2a(q), a2a(k), a2a(v)
        out = dense_attention(qg, kg, vg, causal=causal)
        return a2a(out, split_head=False)

    def fn(q, k, v):
        return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)

    return fn
