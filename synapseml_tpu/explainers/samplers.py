"""Perturbation samplers for LIME and KernelSHAP.

Re-design of the reference's sampler hierarchy
(ref: core/.../explainers/Sampler.scala:16-237, LIMESampler.scala:11-46,
KernelSHAPSampler.scala:14-162) as vectorized numpy sampling: a whole
[rows, samples, features] mask block is drawn in one call instead of per-row
iterators, so the downstream model scores one large contiguous batch.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


def lime_state_samples(rng: np.random.Generator, n_rows: int, n_samples: int,
                       d: int, on_prob: float = 0.7) -> np.ndarray:
    """Binary on/off states in interpretable space, [R, S, D]
    (ref: LIMESampler.scala:11-46 — Bernoulli feature-state draws)."""
    return (rng.random((n_rows, n_samples, d)) < on_prob).astype(np.float32)


def lime_kernel_weights(states: np.ndarray, kernel_width: float) -> np.ndarray:
    """exp(-dist^2 / width^2) over cosine-ish distance from the all-on vector
    (ref: LIMEBase.transform:67-115 kernel weighting)."""
    d = states.shape[-1]
    frac_off = 1.0 - states.sum(axis=-1) / max(d, 1)
    return np.exp(-(frac_off ** 2) / (kernel_width ** 2)).astype(np.float32)


def shap_kernel_weight(d: int, k: int) -> float:
    """Shapley kernel pi(k) = (D-1) / (C(D,k) * k * (D-k))
    (ref: KernelSHAPSamplerSupport.scala:24 — binomial-coefficient weighting)."""
    if k <= 0 or k >= d:
        # full/empty coalitions enter the solve via the exact efficiency
        # constraint (see surrogate.shap_weighted_fit), not via weights
        return 0.0
    return (d - 1) / (math.comb(d, k) * k * (d - k))


def kernel_shap_samples(rng: np.random.Generator, n_rows: int, n_samples: int,
                        d: int) -> Tuple[np.ndarray, np.ndarray]:
    """Coalition vectors + shapley kernel weights, ([R, S, D], [R, S]).

    Coalition sizes are drawn proportionally to the shapley kernel mass per
    size, mirroring the reference's sampler which enumerates small coalitions
    first then samples (ref: KernelSHAPSampler.scala:14-162). The first sample
    of every row is the all-on coalition so the surrogate always sees f(x).
    """
    sizes = np.arange(1, d)
    if len(sizes) == 0:
        states = np.ones((n_rows, n_samples, d), np.float32)
        return states, np.ones((n_rows, n_samples), np.float32)
    size_w = np.array([shap_kernel_weight(d, int(k)) * math.comb(d, int(k))
                       for k in sizes])
    size_p = size_w / size_w.sum()
    states = np.empty((n_rows, n_samples, d), dtype=np.float32)
    weights = np.empty((n_rows, n_samples), dtype=np.float32)
    for r in range(n_rows):
        states[r, 0] = 1.0  # sample 0 is always the all-on row -> f(x)
        weights[r, 0] = 0.0
        ks = rng.choice(sizes, size=n_samples - 1, p=size_p)
        for s, k in enumerate(ks, start=1):
            idx = rng.choice(d, size=int(k), replace=False)
            row = np.zeros(d, np.float32)
            row[idx] = 1.0
            states[r, s] = row
            weights[r, s] = shap_kernel_weight(d, int(k))
    return states, weights


def apply_mask_background(x: np.ndarray, states: np.ndarray,
                          background: np.ndarray) -> np.ndarray:
    """Numeric perturbation: masked features -> background values.

    x: [R, D] originals, states: [R, S, D], background: [D] or [R, D].
    Returns [R, S, D].
    """
    bg = np.broadcast_to(background, x.shape)
    return states * x[:, None, :] + (1.0 - states) * bg[:, None, :]


def tabular_value_samples(rng: np.random.Generator, states: np.ndarray,
                          x: np.ndarray, feature_means: np.ndarray,
                          feature_stds: np.ndarray) -> np.ndarray:
    """TabularLIME perturbation: off-features are resampled from the
    background distribution N(mean, std) instead of a fixed value
    (ref: TabularLIME.scala — background-df feature stats)."""
    r, s, d = states.shape
    noise = rng.standard_normal((r, s, d)) * feature_stds + feature_means
    return states * x[:, None, :] + (1.0 - states) * noise.astype(np.float32)
