"""SLIC-style superpixel clustering on device.

Re-design of the reference's Superpixel
(ref: core/.../lime/Superpixel.scala:42-267 — grid-seeded iterative
color-distance clustering, `cellSize`/`modifier` params) as a jitted jax
k-means-style loop: all pixel→center distances compute as one [HW, P] block
per iteration (MXU-friendly), centers update via ``segment_sum``. No
per-pixel Python.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class SuperpixelData:
    """Cluster assignment for one image (ref: SuperpixelData.scala:25)."""
    assignment: np.ndarray  # [H, W] int32 cluster ids
    num_clusters: int

    def masked_image(self, image: np.ndarray, state: np.ndarray,
                     background: float = 0.0) -> np.ndarray:
        """Apply an on/off superpixel state vector to the image."""
        on = np.asarray(state)[self.assignment].astype(image.dtype)
        if image.ndim == 3:
            on = on[..., None]
        return image * on + background * (1 - on)


@partial(jax.jit, static_argnames=("grid_h", "grid_w", "iters"))
def _slic(pix, yx, grid_h: int, grid_w: int, spatial_w, iters: int):
    h, w, _ = pix.shape
    p = grid_h * grid_w
    flat = pix.reshape(-1, pix.shape[-1])
    pos = yx.reshape(-1, 2)
    cy = (jnp.arange(grid_h) + 0.5) * (h / grid_h)
    cx = (jnp.arange(grid_w) + 0.5) * (w / grid_w)
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1).reshape(-1, 2)
    c_idx = (jnp.clip(cyx[:, 0].astype(jnp.int32), 0, h - 1) * w
             + jnp.clip(cyx[:, 1].astype(jnp.int32), 0, w - 1))
    centers = jnp.concatenate([flat[c_idx], cyx], axis=1)  # [P, C+2]

    def body(_, centers):
        cd = jnp.sum((flat[:, None, :] - centers[None, :, :-2]) ** 2, -1)
        sd = jnp.sum((pos[:, None, :] - centers[None, :, -2:]) ** 2, -1)
        assign = jnp.argmin(cd + spatial_w * sd, axis=1)
        feat = jnp.concatenate([flat, pos], axis=1)
        sums = jax.ops.segment_sum(feat, assign, num_segments=p)
        cnts = jax.ops.segment_sum(jnp.ones((flat.shape[0],)), assign,
                                   num_segments=p)
        new = sums / jnp.maximum(cnts, 1.0)[:, None]
        return jnp.where(cnts[:, None] > 0, new, centers)

    centers = jax.lax.fori_loop(0, iters, body, centers)
    cd = jnp.sum((flat[:, None, :] - centers[None, :, :-2]) ** 2, -1)
    sd = jnp.sum((pos[:, None, :] - centers[None, :, -2:]) ** 2, -1)
    return jnp.argmin(cd + spatial_w * sd, axis=1).astype(jnp.int32)


def superpixels(image: np.ndarray, cell_size: float = 16.0,
                modifier: float = 130.0, iters: int = 10) -> SuperpixelData:
    """Cluster an [H, W, C] (or [H, W]) image into ~(H/cell)*(W/cell)
    superpixels. ``modifier`` balances color vs spatial distance, matching the
    reference's parameter naming (ref: Superpixel.scala:148)."""
    img = np.asarray(image, np.float32)
    if img.ndim == 2:
        img = img[..., None]
    if img.max() <= 1.5:  # normalize to the 0..255 scale `modifier` assumes
        img = img * 255.0
    h, w = img.shape[:2]
    grid_h = max(1, round(h / cell_size))
    grid_w = max(1, round(w / cell_size))
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    yx = np.stack([ys, xs], -1).astype(np.float32)
    # standard SLIC distance: d_color^2 + (modifier/S)^2 * d_spatial^2,
    # colors on the 0..255 scale
    spatial_w = (modifier / cell_size) ** 2
    assign = np.asarray(_slic(jnp.asarray(img), jnp.asarray(yx),
                              grid_h, grid_w, spatial_w, iters))
    # compact ids: drop empty clusters so states have no dead slots
    uniq, compact = np.unique(assign, return_inverse=True)
    return SuperpixelData(compact.reshape(h, w).astype(np.int32), len(uniq))
