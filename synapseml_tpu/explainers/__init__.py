from synapseml_tpu.explainers.local import (
    ImageLIME,
    ImageSHAP,
    LocalExplainer,
    TabularLIME,
    TabularSHAP,
    TextLIME,
    TextSHAP,
    VectorLIME,
    VectorSHAP,
)
from synapseml_tpu.explainers.superpixel import SuperpixelData, superpixels
from synapseml_tpu.explainers.surrogate import (
    weighted_lasso,
    weighted_least_squares,
)

__all__ = [
    "ImageLIME", "ImageSHAP", "LocalExplainer", "TabularLIME", "TabularSHAP",
    "TextLIME", "TextSHAP", "VectorLIME", "VectorSHAP", "SuperpixelData",
    "superpixels", "weighted_lasso", "weighted_least_squares",
]
