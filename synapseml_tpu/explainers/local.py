"""Model-agnostic local explainers: LIME + KernelSHAP for
tabular / vector / image / text inputs.

Re-design of the reference's explainer family
(ref: core/.../explainers/LocalExplainer.scala:16-130, LIMEBase.scala:49-145,
KernelSHAPBase.scala:36-125, TabularLIME/TabularSHAP/VectorLIME/VectorSHAP/
ImageLIME.scala:38/ImageSHAP.scala:35/TextLIME/TextSHAP).

TPU-first shape of the computation:
- sampling draws the whole [rows, samples, features] block at once
- the model scores ONE flattened batch (rows*samples) per explained table —
  the reference instead runs a per-row sampling UDF and groups by id
- every row's surrogate fit runs in a single vmapped device launch
  (:mod:`synapseml_tpu.explainers.surrogate`)

Outputs: ``output_col`` holds a [K, D] (LIME) or [K, D+1] (SHAP, phi0 first)
array per row, K = number of target classes, D = interpretable features.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasInputCol, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.explainers import samplers
from synapseml_tpu.explainers.superpixel import SuperpixelData, superpixels
from synapseml_tpu.explainers.surrogate import (
    batched_lasso,
    batched_least_squares,
    batched_shap_fit,
)


class LocalExplainer(Transformer, HasOutputCol):
    """Common scoring plumbing (ref: LocalExplainer.scala:16-130)."""

    model = ComplexParam("the Transformer being explained")
    target_col = Param("model output column to explain", default="probability")
    target_classes = Param("indices into the output vector", default=(0,))
    num_samples = Param("perturbations per row", default=None)
    seed = Param("rng seed", default=0)

    _DEFAULT_SAMPLES = 100

    def _n_samples(self) -> int:
        return int(self.num_samples or self._DEFAULT_SAMPLES)

    def _score(self, table: Table) -> np.ndarray:
        """Model outputs restricted to target classes, [N, K]."""
        out = self.model.transform(table)
        col = out[self.target_col]
        arr = np.asarray(np.stack(list(col)) if col.dtype == object else col,
                         np.float32)
        if arr.ndim == 1:
            arr = arr[:, None]
        classes = [c if c < arr.shape[1] else arr.shape[1] - 1
                   for c in self.target_classes]
        return arr[:, classes]

    def _replicate_others(self, table: Table, skip: Sequence[str],
                          n_samples: int) -> dict:
        """Repeat non-perturbed columns row-wise for the flattened batch."""
        rep = {}
        for c in table.columns:
            if c not in skip:
                rep[c] = np.repeat(table[c], n_samples, axis=0)
        return rep


class _LIMEFit:
    """LIME surrogate: kernel-weighted lasso on interpretable states
    (ref: LIMEBase.transform:67-115)."""

    kernel_width = Param("LIME kernel width", default=0.75)
    regularization = Param("lasso alpha (0 -> least squares)", default=0.0)

    def _fit_surrogate(self, states: np.ndarray, weights: np.ndarray,
                       y: np.ndarray) -> np.ndarray:
        """states [N,S,D], weights [N,S], y [N,S,K] -> coefs [N,K,D]."""
        n, s, d = states.shape
        k = y.shape[-1]
        st = jnp.asarray(states)
        w = jnp.asarray(weights)
        outs = []
        alpha = float(self.regularization)
        for ki in range(k):
            yk = jnp.asarray(y[..., ki])
            if alpha > 0:
                coefs, _ = batched_lasso(st, yk, w, jnp.full((n,), alpha))
            else:
                coefs, _ = batched_least_squares(st, yk, w)
            outs.append(np.asarray(coefs))
        return np.stack(outs, axis=1)  # [N, K, D]


class _SHAPFit:
    """KernelSHAP surrogate (ref: KernelSHAPBase.transform:42-94)."""

    def _fit_surrogate_shap(self, states: np.ndarray, weights: np.ndarray,
                            y: np.ndarray, fnull: np.ndarray,
                            d_per_row: Optional[Sequence[int]] = None) -> np.ndarray:
        """states [N,S,D], weights [N,S], y [N,S,K], fnull [K] or [N,K]
        -> phis [N,K,D+1] (phi0 first). Sample 0 of every row must be the
        all-on coalition (it supplies f(x) for the efficiency constraint).

        ``d_per_row`` handles ragged features (text tokens / superpixels):
        rows are grouped by their true feature count and each group is fit on
        the unpadded [.., :d] slice — zero-padded phantom columns must never
        enter the constraint elimination."""
        n, s, d = states.shape
        k = y.shape[-1]
        fnull = np.broadcast_to(np.asarray(fnull, np.float32), (n, k))
        ds = (np.full(n, d, int) if d_per_row is None
              else np.asarray(list(d_per_row), int))
        out = np.zeros((n, k, d + 1), np.float32)
        for dv in np.unique(ds):
            idx = np.flatnonzero(ds == dv)
            st = jnp.asarray(states[idx][:, :, :dv])
            w = jnp.asarray(weights[idx])
            for ki in range(k):
                phis = batched_shap_fit(st, jnp.asarray(y[idx, :, ki]), w,
                                        jnp.asarray(fnull[idx, ki]),
                                        jnp.asarray(y[idx, 0, ki]))
                out[idx, ki, :dv + 1] = np.asarray(phis)
        return out  # [N, K, D+1]


# ---------------------------------------------------------------------------
# Vector explainers: input_col is a 2-D numeric features column
# ---------------------------------------------------------------------------

class _VectorBase(LocalExplainer, HasInputCol):
    background = ComplexParam(
        "background row [D] (default: column mean of the explained batch)",
        default=None)

    def _background(self, x: np.ndarray) -> np.ndarray:
        bg = self.background
        return (np.asarray(bg, np.float32) if bg is not None
                else x.mean(axis=0))

    def _score_perturbed(self, table: Table, perturbed: np.ndarray) -> np.ndarray:
        n, s, d = perturbed.shape
        cols = self._replicate_others(table, [self.input_col, self.output_col], s)
        cols[self.input_col] = perturbed.reshape(n * s, d)
        k = len(list(self.target_classes))
        return self._score(Table(cols)).reshape(n, s, k)


class VectorLIME(_VectorBase, _LIMEFit):
    """LIME over a dense feature vector (ref: VectorLIME.scala)."""

    kernel_width = Param("LIME kernel width", default=0.75)
    regularization = Param("lasso alpha (0 -> least squares)", default=0.0)

    def _transform(self, table: Table) -> Table:
        x = np.asarray(table[self.input_col], np.float32)
        n, d = x.shape
        s = self._n_samples()
        rng = np.random.default_rng(int(self.seed))
        states = samplers.lime_state_samples(rng, n, s, d)
        weights = samplers.lime_kernel_weights(states, float(self.kernel_width))
        perturbed = samplers.apply_mask_background(x, states, self._background(x))
        y = self._score_perturbed(table, perturbed)
        coefs = self._fit_surrogate(states, weights, y)
        return table.with_column(self.output_col, coefs)


class VectorSHAP(_VectorBase, _SHAPFit):
    """KernelSHAP over a dense feature vector (ref: VectorSHAP.scala)."""

    def _transform(self, table: Table) -> Table:
        x = np.asarray(table[self.input_col], np.float32)
        n, d = x.shape
        s = self._n_samples()
        rng = np.random.default_rng(int(self.seed))
        states, weights = samplers.kernel_shap_samples(rng, n, s, d)
        bg = self._background(x)
        perturbed = samplers.apply_mask_background(x, states, bg)
        y = self._score_perturbed(table, perturbed)
        # fnull = model on the all-background row
        null_t = Table({**self._replicate_others(table.slice(0, 1),
                                                 [self.input_col, self.output_col], 1),
                        self.input_col: bg.reshape(1, d)})
        fnull = self._score(null_t)[0]
        phis = self._fit_surrogate_shap(states, weights, y, fnull)
        return table.with_column(self.output_col, phis)


# ---------------------------------------------------------------------------
# Tabular explainers: input_cols are scalar numeric columns
# ---------------------------------------------------------------------------

class _TabularBase(LocalExplainer):
    input_cols = Param("numeric columns to explain", default=None)
    background_data = ComplexParam(
        "background Table for feature stats (default: the explained table)",
        default=None)

    def _matrix(self, table: Table) -> np.ndarray:
        return np.column_stack([
            np.asarray(table[c], np.float32) for c in self.input_cols])

    def _stats(self, table: Table):
        bg = self.background_data if self.background_data is not None else table
        m = self._matrix(bg)
        return m.mean(axis=0), m.std(axis=0) + 1e-12

    def _score_perturbed(self, table: Table, perturbed: np.ndarray) -> np.ndarray:
        n, s, d = perturbed.shape
        flat = perturbed.reshape(n * s, d)
        cols = self._replicate_others(
            table, list(self.input_cols) + [self.output_col], s)
        for j, c in enumerate(self.input_cols):
            cols[c] = flat[:, j].astype(np.float64)
        k = len(list(self.target_classes))
        return self._score(Table(cols)).reshape(n, s, k)


class TabularLIME(_TabularBase, _LIMEFit):
    """LIME over raw table columns: off-features resample from background
    stats (ref: TabularLIME.scala:160)."""

    kernel_width = Param("LIME kernel width", default=0.75)
    regularization = Param("lasso alpha (0 -> least squares)", default=0.0)

    def _transform(self, table: Table) -> Table:
        x = self._matrix(table)
        n, d = x.shape
        s = self._n_samples()
        rng = np.random.default_rng(int(self.seed))
        mean, std = self._stats(table)
        states = samplers.lime_state_samples(rng, n, s, d)
        weights = samplers.lime_kernel_weights(states, float(self.kernel_width))
        perturbed = samplers.tabular_value_samples(rng, states, x, mean, std)
        y = self._score_perturbed(table, perturbed)
        coefs = self._fit_surrogate(states, weights, y)
        return table.with_column(self.output_col, coefs)


class TabularSHAP(_TabularBase, _SHAPFit):
    """KernelSHAP over raw table columns (ref: TabularSHAP.scala)."""

    def _transform(self, table: Table) -> Table:
        x = self._matrix(table)
        n, d = x.shape
        s = self._n_samples()
        rng = np.random.default_rng(int(self.seed))
        mean, _ = self._stats(table)
        states, weights = samplers.kernel_shap_samples(rng, n, s, d)
        perturbed = samplers.apply_mask_background(x, states, mean)
        y = self._score_perturbed(table, perturbed)
        null_cols = self._replicate_others(
            table.slice(0, 1), list(self.input_cols) + [self.output_col], 1)
        for j, c in enumerate(self.input_cols):
            null_cols[c] = np.asarray([mean[j]], np.float64)
        fnull = self._score(Table(null_cols))[0]
        phis = self._fit_surrogate_shap(states, weights, y, fnull)
        return table.with_column(self.output_col, phis)


# ---------------------------------------------------------------------------
# Text explainers: input_col is a string column; tokens are the features
# ---------------------------------------------------------------------------

class _TextBase(LocalExplainer, HasInputCol):
    tokens_col = Param("output column holding the token list", default="tokens")

    def _explain_text(self, table: Table, use_shap: bool) -> Table:
        texts = [str(v) for v in table[self.input_col]]
        token_lists = [t.split() for t in texts]
        n = len(texts)
        s = self._n_samples()
        max_d = max((len(t) for t in token_lists), default=1) or 1
        rng = np.random.default_rng(int(self.seed))
        k = len(list(self.target_classes))
        states = np.zeros((n, s, max_d), np.float32)
        weights = np.zeros((n, s), np.float32)
        flat_texts: List[str] = []
        for r, toks in enumerate(token_lists):
            d = max(len(toks), 1)
            if use_shap:
                st, w = samplers.kernel_shap_samples(rng, 1, s, d)
                st, w = st[0], w[0]
            else:
                st = samplers.lime_state_samples(rng, 1, s, d)[0]
                w = samplers.lime_kernel_weights(
                    st, float(self.get("kernel_width", 0.75) or 0.75))[0]
            states[r, :, :d] = st
            weights[r] = w
            for si in range(s):
                kept = [t for t, on in zip(toks, st[si]) if on > 0.5]
                flat_texts.append(" ".join(kept))
        cols = self._replicate_others(table, [self.input_col, self.output_col], s)
        cols[self.input_col] = np.array(flat_texts, dtype=object)
        y = self._score(Table(cols)).reshape(n, s, k)
        if use_shap:
            null_cols = self._replicate_others(
                table.slice(0, 1), [self.input_col, self.output_col], 1)
            null_cols[self.input_col] = np.array([""], dtype=object)
            fnull = self._score(Table(null_cols))[0]
            out = self._fit_surrogate_shap(
                states, weights, y, fnull,
                d_per_row=[max(len(t), 1) for t in token_lists])
        else:
            out = self._fit_surrogate(states, weights, y)
        return (table
                .with_column(self.output_col, out)
                .with_column(self.tokens_col,
                             np.array(token_lists, dtype=object)
                             if len({len(t) for t in token_lists}) > 1
                             else _obj_col(token_lists)))


def _obj_col(values):
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


class TextLIME(_TextBase, _LIMEFit):
    """Token-masking LIME (ref: TextLIME.scala)."""

    kernel_width = Param("LIME kernel width", default=0.75)
    regularization = Param("lasso alpha", default=0.0)

    def _transform(self, table: Table) -> Table:
        return self._explain_text(table, use_shap=False)


class TextSHAP(_TextBase, _SHAPFit):
    """Token-coalition KernelSHAP (ref: TextSHAP.scala)."""

    def _transform(self, table: Table) -> Table:
        return self._explain_text(table, use_shap=True)


# ---------------------------------------------------------------------------
# Image explainers: input_col holds [H, W, C] arrays; superpixels are features
# ---------------------------------------------------------------------------

class _ImageBase(LocalExplainer, HasInputCol):
    cell_size = Param("superpixel cell size", default=16.0)
    modifier = Param("superpixel color/spatial balance", default=130.0)
    background_value = Param("fill for masked superpixels", default=0.0)
    superpixel_col = Param("output column with [H, W] assignments",
                           default="superpixels")

    def _explain_images(self, table: Table, use_shap: bool) -> Table:
        images = [np.asarray(v, np.float32) for v in table[self.input_col]]
        n = len(images)
        s = self._n_samples()
        k = len(list(self.target_classes))
        sps: List[SuperpixelData] = [
            superpixels(img, float(self.cell_size), float(self.modifier))
            for img in images]
        max_d = max(sp.num_clusters for sp in sps)
        rng = np.random.default_rng(int(self.seed))
        states = np.zeros((n, s, max_d), np.float32)
        weights = np.zeros((n, s), np.float32)
        flat_imgs: List[np.ndarray] = []
        bgv = float(self.background_value)
        for r, (img, sp) in enumerate(zip(images, sps)):
            d = sp.num_clusters
            if use_shap:
                st, w = samplers.kernel_shap_samples(rng, 1, s, d)
                st, w = st[0], w[0]
            else:
                st = samplers.lime_state_samples(rng, 1, s, d)[0]
                w = samplers.lime_kernel_weights(
                    st, float(self.get("kernel_width", 0.75) or 0.75))[0]
            states[r, :, :d] = st
            weights[r] = w
            for si in range(s):
                flat_imgs.append(sp.masked_image(img, st[si, :d], bgv))
        cols = self._replicate_others(table, [self.input_col, self.output_col], s)
        cols[self.input_col] = _obj_col(flat_imgs)
        y = self._score(Table(cols)).reshape(n, s, k)
        if use_shap:
            null_cols = self._replicate_others(
                table.slice(0, 1), [self.input_col, self.output_col], 1)
            null_cols[self.input_col] = _obj_col(
                [np.full_like(images[0], bgv)])
            fnull = self._score(Table(null_cols))[0]
            out = self._fit_surrogate_shap(
                states, weights, y, fnull,
                d_per_row=[sp.num_clusters for sp in sps])
        else:
            out = self._fit_surrogate(states, weights, y)
        return (table
                .with_column(self.output_col, out)
                .with_column(self.superpixel_col,
                             _obj_col([sp.assignment for sp in sps])))


class ImageLIME(_ImageBase, _LIMEFit):
    """Superpixel-masking LIME (ref: ImageLIME.scala:38)."""

    kernel_width = Param("LIME kernel width", default=0.75)
    regularization = Param("lasso alpha", default=0.0)
    _DEFAULT_SAMPLES = 50

    def _transform(self, table: Table) -> Table:
        return self._explain_images(table, use_shap=False)


class ImageSHAP(_ImageBase, _SHAPFit):
    """Superpixel-coalition KernelSHAP (ref: ImageSHAP.scala:35)."""

    _DEFAULT_SAMPLES = 50

    def _transform(self, table: Table) -> Table:
        return self._explain_images(table, use_shap=True)
