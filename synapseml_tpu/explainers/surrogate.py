"""Surrogate regressions for LIME / KernelSHAP.

Re-design of the reference's pure-Breeze solvers
(ref: core/.../explainers/LassoRegression.scala:74 — coordinate-descent lasso,
LeastSquaresRegression.scala:8, RegressionBase.scala:20 — weighted
centering/rescaling) as jitted jax kernels, vmappable over a whole batch of
rows so one device launch fits every row's surrogate at once (the reference
fits per-row on the driver).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("fit_intercept",))
def weighted_least_squares(x, y, w, fit_intercept: bool = True, l2: float = 1e-6):
    """Closed-form weighted ridge-stabilized least squares.

    x: [S, D], y: [S], w: [S] sample weights. Returns (coefs [D], intercept).
    (ref: LeastSquaresRegression.scala:8 — normal equations on weighted data)
    """
    w = w / (jnp.sum(w) + 1e-12)
    if fit_intercept:
        xm = jnp.sum(x * w[:, None], axis=0)
        ym = jnp.sum(y * w)
        xc, yc = x - xm, y - ym
    else:
        xm = jnp.zeros(x.shape[1], x.dtype)
        ym = jnp.asarray(0.0, x.dtype)
        xc, yc = x, y
    xw = xc * w[:, None]
    a = xc.T @ xw + l2 * jnp.eye(x.shape[1], dtype=x.dtype)
    b = xw.T @ yc
    coefs = jnp.linalg.solve(a, b)
    intercept = ym - jnp.dot(xm, coefs)
    return coefs, intercept


@partial(jax.jit, static_argnames=("iters",))
def weighted_lasso(x, y, w, alpha, iters: int = 100):
    """Weighted lasso via cyclic coordinate descent with soft-thresholding,
    on standardized features (ref: LassoRegression.scala:10-74
    CoordinateDescentLasso). Returns (coefs [D], intercept) in original scale.

    The coordinate sweep is a ``lax.fori_loop`` over a ``lax.scan`` across
    coordinates — fixed trip count, so XLA compiles one fused kernel and the
    whole batch of per-row fits runs as a single vmapped launch.
    """
    s, d = x.shape
    w = w / (jnp.sum(w) + 1e-12)
    xm = jnp.sum(x * w[:, None], axis=0)
    ym = jnp.sum(y * w)
    xc = x - xm
    yc = y - ym
    scale = jnp.sqrt(jnp.sum(xc * xc * w[:, None], axis=0)) + 1e-12
    xs = xc / scale
    # precompute weighted gram quantities
    g = (xs * w[:, None]).T @ xs          # [D, D]
    c = (xs * w[:, None]).T @ yc          # [D]

    def coord_step(beta, j):
        rho = c[j] - jnp.dot(g[j], beta) + g[j, j] * beta[j]
        bj = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - alpha, 0.0) / (g[j, j] + 1e-12)
        beta = beta.at[j].set(bj)
        return beta, None

    def sweep(_, beta):
        beta, _ = jax.lax.scan(coord_step, beta, jnp.arange(d))
        return beta

    beta = jax.lax.fori_loop(0, iters, sweep, jnp.zeros(d, x.dtype))
    coefs = beta / scale
    intercept = ym - jnp.dot(xm, coefs)
    return coefs, intercept


# batched variants: one launch fits surrogates for every explained row
batched_least_squares = jax.jit(
    jax.vmap(lambda x, y, w: weighted_least_squares(x, y, w)),
)
batched_lasso = jax.jit(
    jax.vmap(lambda x, y, w, a: weighted_lasso(x, y, w, a)),
)


@jax.jit
def shap_weighted_fit(z, y, w, fnull, fx):
    """KernelSHAP solve with the efficiency constraint eliminated exactly.

    z: [S, D] coalition matrix, y: [S] model outputs, w: [S] shapley kernel
    weights, fnull: model output on the all-background sample, fx: output on
    the original row. Instead of soft-pinning the constraint with a huge
    weight (catastrophic in float32), substitute
    ``phi_D = (fx - fnull) - sum(phi_1..D-1)`` and solve the reduced weighted
    least squares — intercept is phi_0 = fnull by construction, matching the
    reference's weighted-LS-with-intercept-phi0 (ref: KernelSHAPBase.scala:42-94).
    Returns [D+1]: phi_0 followed by phi_1..D.
    """
    e = fx - fnull
    zd = z[:, -1:]
    x = z[:, :-1] - zd                    # [S, D-1]
    t = y - fnull - zd[:, 0] * e
    xw = x * w[:, None]
    a = x.T @ xw + 1e-8 * jnp.eye(x.shape[1], dtype=x.dtype)
    b = xw.T @ t
    head = jnp.linalg.solve(a, b)
    last = e - jnp.sum(head)
    return jnp.concatenate([jnp.asarray([fnull], z.dtype), head,
                            jnp.asarray([last], z.dtype)])


batched_shap_fit = jax.jit(jax.vmap(shap_weighted_fit))
