"""Notebook plotting helpers — the ``mmlspark.plot`` analogue
(ref: core/src/main/python/mmlspark/plot/plot.py:17-60 —
``confusionMatrix`` and ``roc`` over a DataFrame/pandas pair of
label/prediction columns, rendered with matplotlib).

TPU-native differences: the inputs are :class:`~synapseml_tpu.data.
table.Table` (or anything column-indexable), the confusion matrix and
ROC points are computed HERE in vectorized numpy (no sklearn), and each
function returns its data so headless pipelines can assert on it —
matplotlib is only touched when an ``ax``/rendering is actually wanted.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def _cols(df, *names):
    return [np.asarray(df[n]) for n in names]


def confusion_matrix(df, y_col: str, y_hat_col: str,
                     labels: Optional[Sequence] = None,
                     normalize: bool = False, ax=None, render: bool = True
                     ) -> np.ndarray:
    """Confusion matrix of ``y_hat_col`` vs ``y_col``; returns the
    [n_labels, n_labels] count matrix (row = true class). ``render=True``
    draws the reference's annotated heatmap (accuracy in the title
    position, per-cell counts) onto ``ax``/the current axes."""
    y, y_hat = _cols(df, y_col, y_hat_col)
    if labels is None:
        labels = np.unique(np.concatenate([y, y_hat]))
    labels = list(labels)
    n = len(labels)
    # vectorized accumulation; rows outside an explicit labels list are
    # ignored (sklearn's confusion_matrix semantics)
    srt = np.argsort(labels, kind="stable")
    slabels = np.asarray(labels)[srt]
    ti = srt[np.clip(np.searchsorted(slabels, y), 0, n - 1)]
    pi = srt[np.clip(np.searchsorted(slabels, y_hat), 0, n - 1)]
    ok = (np.asarray(labels)[ti] == y) & (np.asarray(labels)[pi] == y_hat)
    cm = np.zeros((n, n), np.int64)
    np.add.at(cm, (ti[ok], pi[ok]), 1)
    if render:
        import matplotlib.pyplot as plt

        if ax is None:
            ax = plt.gca()
        cmn = cm.astype(np.float64) / np.maximum(
            cm.sum(axis=1, keepdims=True), 1)
        acc = float(np.mean(y == y_hat))
        ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0,
                  vmax=1)
        ax.set_title(f"Accuracy = {acc * 100:.1f}%")
        ax.set_xticks(range(n), labels)
        ax.set_yticks(range(n), labels)
        for i in range(n):
            for j in range(n):
                ax.text(j, i, int(cm[i, j]), ha="center",
                        color="white" if cmn[i, j] > 0.5 else "black")
        ax.set_xlabel("predicted")
        ax.set_ylabel("true")
    if normalize:
        return cm.astype(np.float64) / np.maximum(
            cm.sum(axis=1, keepdims=True), 1)
    return cm  # counts (row = true class)


def roc(df, y_col: str, y_hat_col: str, thresh: float = 0.5, ax=None,
        render: bool = True) -> Tuple[np.ndarray, np.ndarray, float]:
    """ROC curve points + AUC for score column ``y_hat_col`` against
    labels binarized at ``thresh`` (the reference's convention). Returns
    ``(fpr, tpr, auc)``; sorted-scores sweep, no sklearn."""
    y, s = _cols(df, y_col, y_hat_col)
    y = (np.asarray(y, np.float64) > thresh).astype(np.int64)
    s = np.asarray(s, np.float64)
    p, nneg = int(y.sum()), int((1 - y).sum())
    if p == 0 or nneg == 0:
        raise ValueError(
            f"ROC is undefined with {p} positives / {nneg} negatives "
            f"after binarizing {y_col!r} at {thresh}")
    order = np.argsort(-s, kind="stable")
    y_sorted, s_sorted = y[order], s[order]
    tp = np.concatenate([[0], np.cumsum(y_sorted)])
    fp = np.concatenate([[0], np.cumsum(1 - y_sorted)])
    # keep only threshold boundaries (distinct score steps) + endpoints
    distinct = np.concatenate(
        [[True], s_sorted[1:] != s_sorted[:-1], [True]])
    tpr = tp[distinct] / p
    fpr = fp[distinct] / nneg
    auc = float(np.trapezoid(tpr, fpr))
    if render:
        import matplotlib.pyplot as plt

        if ax is None:
            ax = plt.gca()
        ax.plot(fpr, tpr)
        ax.set_xlabel("False Positive Rate")
        ax.set_ylabel("True Positive Rate")
        ax.set_title(f"AUC = {auc:.3f}")
    return fpr, tpr, auc
