"""Tracing / profiling utilities (SURVEY.md §5: the reference has only
StopWatch-based per-component timing — VW per-partition stats DataFrames,
vw/.../VowpalWabbitBase.scala:294-328,480-489, and the Timer stage; the
TPU build is told to replace these with jax profiler hooks + per-stage
device timing).

Three tiers:
- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace directory (XLA op timeline, HBM usage);
- :class:`StopWatch` — the reference's accumulating stopwatch
  (core/.../core/utils/StopWatch.scala:35), device-sync aware;
- :func:`stage_stats` — per-stage wall/device timing over a pipeline run,
  the VW perf-DataFrame analogue, returned as a Table.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.runtime.locksan import make_lock

# nesting-safe active-trace count: runtime/telemetry.py consults
# trace_active() so the executor's pipeline-stage TraceAnnotations only
# pay their cost while a profiler trace is actually recording
_ACTIVE_LOCK = make_lock("profiling:_ACTIVE_LOCK")
_ACTIVE_TRACES = 0


def tracing_disabled() -> bool:
    """``SYNAPSEML_TRACE=0`` is the kill switch: :func:`trace` and
    :func:`annotate` degrade to no-ops (checked per call, so tests and
    long-lived servers can flip the env var live)."""
    return os.environ.get("SYNAPSEML_TRACE", "") == "0"


def trace_active() -> bool:
    """True while at least one :func:`trace` block is recording."""
    return _ACTIVE_TRACES > 0


def _trace_count(delta: int):
    global _ACTIVE_TRACES
    with _ACTIVE_LOCK:
        _ACTIVE_TRACES = max(0, _ACTIVE_TRACES + delta)


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
    """jax.profiler trace around a block; view in TensorBoard/XProf.
    Degrades to a no-op where the profiler is unsupported, and honors
    the ``SYNAPSEML_TRACE=0`` kill switch."""
    if tracing_disabled():
        yield
        return
    import jax

    try:
        kwargs = {"create_perfetto_link": False}
        opts_cls = getattr(jax.profiler, "ProfileOptions", None)
        if opts_cls is not None:
            opts = opts_cls()
            opts.host_tracer_level = host_tracer_level
            kwargs["profiler_options"] = opts
        jax.profiler.start_trace(log_dir, **kwargs)
        started = True
    except Exception:  # noqa: BLE001 - profiling must never break the job
        try:  # older jax: no profiler_options kwarg
            jax.profiler.start_trace(log_dir, create_perfetto_link=False)
            started = True
        except Exception:  # noqa: BLE001
            started = False
    if started:
        _trace_count(+1)
    try:
        yield
    finally:
        if started:
            _trace_count(-1)
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass


def annotate(name: str):
    """Named region in the device trace (TraceAnnotation). A no-op
    context when ``SYNAPSEML_TRACE=0`` or the profiler is unavailable —
    annotation must never break (or slow) the annotated code."""
    if tracing_disabled():
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 - degrade to no-op
        return contextlib.nullcontext()


def _sync():
    """Block until all dispatched device work completes (so wall times
    include device execution, not just dispatch)."""
    import jax

    try:
        for d in jax.live_arrays():
            d.block_until_ready()
    except Exception:  # noqa: BLE001
        pass


class StopWatch:
    """(ref: core/.../core/utils/StopWatch.scala) — accumulating timer with
    optional device synchronization at measure boundaries.

    Thread-safe: the serving/executor pipeline threads time their stages
    on shared instances now, so accumulation rides a lock and
    :meth:`measure` keeps its start time on the *caller's* stack —
    concurrent measures each contribute their full interval instead of
    overwriting one shared ``_start`` slot (the historical lost-update).
    ``start``/``stop`` keep the single-slot semantics for the sequential
    callers that use them directly, just guarded."""

    def __init__(self, sync_device: bool = False):
        self.elapsed = 0.0
        self._start: Optional[float] = None
        self.sync_device = sync_device
        self._lock = make_lock("StopWatch._lock")

    def start(self):
        if self.sync_device:
            _sync()
        with self._lock:
            self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        if self.sync_device:
            _sync()
        with self._lock:
            if self._start is not None:
                self.elapsed += time.perf_counter() - self._start
                self._start = None
            return self.elapsed

    def add(self, seconds: float) -> float:
        with self._lock:
            self.elapsed += seconds
            return self.elapsed

    @contextlib.contextmanager
    def measure(self):
        if self.sync_device:
            _sync()
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            if self.sync_device:
                _sync()
            self.add(time.perf_counter() - t0)


def stage_stats(pipeline_stages, table: Table,
                sync_device: bool = True) -> tuple:
    """Run stages sequentially, timing each (fit+transform for estimators);
    returns (final_table, stats_table) — the per-partition perf-stats
    DataFrame analogue (VowpalWabbitBase.scala:480-489)."""
    from synapseml_tpu.core.pipeline import Estimator

    names: List[str] = []
    kinds: List[str] = []
    seconds: List[float] = []
    rows_in: List[int] = []
    current = table
    for stage in pipeline_stages:
        sw = StopWatch(sync_device=sync_device)
        n_in = current.num_rows
        with sw.measure():
            if isinstance(stage, Estimator):
                fitted = stage.fit(current)
                current = fitted.transform(current)
                kinds.append("estimator")
            else:
                current = stage.transform(current)
                kinds.append("transformer")
        names.append(type(stage).__name__)
        seconds.append(sw.elapsed)
        rows_in.append(n_in)
    total = sum(seconds) or 1.0
    stats = Table({
        "stage": np.array(names, dtype=object),
        "kind": np.array(kinds, dtype=object),
        "seconds": np.array(seconds, np.float64),
        "pct": np.array([s / total * 100.0 for s in seconds], np.float64),
        "rows_in": np.array(rows_in, np.int64),
    })
    return current, stats


def serving_echo_latency(samples: int = 300, warmup: int = 50,
                         name: str = "latency_probe") -> List[float]:
    """Sorted request->pipeline->reply latencies (seconds) through a
    ContinuousServer echo pipeline over one keep-alive connection.

    Shared by bench.py's ``serving_roundtrip_p50_ms`` metric and the
    serving regression test; raises if any reply is non-200 so a broken
    pipeline can never masquerade as a fast one.
    """
    import http.client
    import json
    import time as _time

    from synapseml_tpu.io.serving import ContinuousServer, make_reply

    def pipeline(table):
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"echo": v})
        return table.with_column("reply", replies)

    cs = ContinuousServer(name, pipeline, max_batch=8).start()
    try:
        conn = http.client.HTTPConnection(
            cs.url.split("//")[1].rstrip("/"), timeout=10)
        body = json.dumps({"x": 1}).encode()

        def once():
            start = _time.perf_counter()
            conn.request("POST", "/", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"echo pipeline replied {resp.status}; latency sample "
                    f"would be meaningless")
            return _time.perf_counter() - start

        for _ in range(warmup):
            once()
        return sorted(once() for _ in range(samples))
    finally:
        cs.stop()
