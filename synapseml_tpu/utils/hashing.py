"""MurmurHash3 (x86 32-bit) — the hash family the reference uses for feature
hashing (ref: vw/.../featurizer/VowpalWabbitMurmurWithPrefix.scala; Spark's
HashingTF also rides murmur3_32).

Scalar path hashes arbitrary byte strings (used for vocab/token hashing, with a
per-process memo so each distinct token is hashed once); the vectorized path
hashes int32 arrays on-device for interaction features.
"""
from __future__ import annotations

import struct
from functools import lru_cache
from typing import Union

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur3_32(data: Union[bytes, str], seed: int = 0) -> int:
    """MurmurHash3 x86_32 over bytes. Returns unsigned 32-bit int."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    h = seed & _MASK
    n = len(data)
    tail = n & ~3
    for i in range(0, tail, 4):
        k = struct.unpack_from("<I", data, i)[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    k = 0
    rem = n & 3
    if rem == 3:
        k ^= data[tail + 2] << 16
    if rem >= 2:
        k ^= data[tail + 1] << 8
    if rem >= 1:
        k ^= data[tail]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def _native_murmur():
    try:
        from synapseml_tpu import native
        if native.available():
            return native.murmur3_32
    except Exception:  # noqa: BLE001 - any native failure -> pure python
        pass
    return None


@lru_cache(maxsize=1)
def _scalar_hash_impl():
    return _native_murmur() or (lambda b, seed=0: murmur3_32(b, seed))


@lru_cache(maxsize=1 << 20)
def hash_token(token: str, seed: int = 0) -> int:
    """Memoized murmur3 of a token — each distinct token hashed once per
    process; the C++ bridge computes it when available (NativeLoader
    analogue, synapseml_tpu.native)."""
    return int(_scalar_hash_impl()(token.encode("utf-8"), seed))


def hash_tokens_batch(tokens, seed: int = 0) -> np.ndarray:
    """Batch token hashing: one native call when the bridge is present,
    else the memoized scalar path."""
    try:
        from synapseml_tpu import native
        if native.available():
            return native.murmur3_32_batch(tokens, seed).astype(np.int64)
    except Exception:  # noqa: BLE001
        pass
    # encode exactly like the native wrapper: bytes pass through, str
    # utf-8 — indices must not depend on whether the bridge compiled
    return np.array([
        murmur3_32(bytes(t) if isinstance(t, (bytes, bytearray))
                   else str(t), seed)
        for t in tokens
    ], np.int64)


def hash_index(token: str, num_features: int, seed: int = 0) -> int:
    return hash_token(token, seed) % num_features


def hash_int_array(values: np.ndarray, seed: int = 0) -> np.ndarray:
    """Vectorized murmur3-style finalizer over an int array (one 4-byte word
    per value). Matches murmur3_32 of the little-endian 4-byte encoding."""
    k = values.astype(np.uint32)
    h = np.full_like(k, seed & _MASK, dtype=np.uint32)
    with np.errstate(over="ignore"):
        k = k * np.uint32(_C1)
        k = (k << np.uint32(15)) | (k >> np.uint32(17))
        k = k * np.uint32(_C2)
        h = h ^ k
        h = (h << np.uint32(13)) | (h >> np.uint32(19))
        h = h * np.uint32(5) + np.uint32(0xE6546B64)
        h = h ^ np.uint32(4)
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
        h = h ^ (h >> np.uint32(16))
    return h
