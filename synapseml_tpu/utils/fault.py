"""Fault-tolerance utilities.

Rebuild of the reference's FaultToleranceUtils + the exponential-backoff
retry pattern used around native/network init
(ref: core/src/main/scala/com/microsoft/ml/spark/core/utils/FaultToleranceUtils.scala:1-33,
lightgbm/.../TrainUtils.scala:279-295 networkInit backoff retries).
"""
from __future__ import annotations

import concurrent.futures
import logging
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")
logger = logging.getLogger("synapseml_tpu")


def retry_with_timeout(fn: Callable[[], T], timeout_s: float,
                       max_retries: int = 3) -> T:
    """Run ``fn`` with a wall-clock timeout, retrying on failure/timeout
    (ref: FaultToleranceUtils.retryWithTimeout:1-33). The attempt runs in a
    worker thread; on timeout the attempt is abandoned and retried."""
    last: Optional[BaseException] = None
    for attempt in range(max_retries):
        # no `with`: __exit__ would wait for a hung attempt, defeating the
        # timeout. shutdown(wait=False) genuinely abandons the thread.
        pool = concurrent.futures.ThreadPoolExecutor(1)
        fut = pool.submit(fn)
        try:
            return fut.result(timeout=timeout_s)
        except concurrent.futures.TimeoutError:
            last = TimeoutError(
                f"attempt {attempt + 1} timed out after {timeout_s}s")
        except Exception as e:  # noqa: BLE001 - mirror reference catch-all
            last = e
        finally:
            pool.shutdown(wait=False)
        logger.warning("retry_with_timeout attempt %d failed: %s",
                       attempt + 1, last)
    raise last  # type: ignore[misc]


def retry_with_backoff(fn: Callable[[], T],
                       backoffs_ms: Tuple[int, ...] = (100, 500, 1000, 5000),
                       retryable: Tuple[Type[BaseException], ...] = (Exception,)
                       ) -> T:
    """Exponential-backoff retry (ref: TrainUtils.networkInit:279-295)."""
    last: Optional[BaseException] = None
    for i in range(len(backoffs_ms) + 1):
        try:
            return fn()
        except retryable as e:
            last = e
            if i < len(backoffs_ms):
                logger.warning("retrying after %dms: %s", backoffs_ms[i], e)
                time.sleep(backoffs_ms[i] / 1000.0)
    raise last  # type: ignore[misc]
