"""Proto-level graph optimizations applied at import time.

The role of onnxruntime's transformer optimizer in the reference stack
(ORT fuses attention subgraphs before CUDA execution; ref ONNXModel
delegates wholesale to ORT, deep-learning/.../onnx/ONNXModel.scala:173).
Here the optimizations rewrite the ONNX graph itself before lowering, so
they are exporter-agnostic and inspectable.

Currently one pass — **parallel-MatMul packing**: N MatMul nodes that
share the same activation input and multiply 2-D weight initializers of
matching inner dimension (the q/k/v projections every transformer export
carries) become ONE MatMul against the concatenated weight followed by a
Split. XLA will not horizontally fuse independent dots; packing turns
three [M,D]x[D,D] MXU calls into one [M,D]x[D,3D] call with triple the
arithmetic intensity per weight load.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from synapseml_tpu.onnx.proto import Msg, numpy_to_tensor, tensor_to_numpy


def _attr_int(node: Msg, name: str, default: int) -> int:
    for a in node.attribute or []:
        if a.name == name:
            return int(a.i)
    return default


def pack_parallel_matmuls(graph: Msg, opset: int = 13,
                          min_group: int = 2) -> int:
    """Rewrite groups of parallel MatMuls in place; returns #groups packed.

    A group: MatMul nodes whose input[0] is the same tensor, whose
    input[1] is a float 2-D initializer with a common inner dim and dtype,
    and whose weights feed nothing else. The packed MatMul + Split are
    spliced at the earliest group position, so every original output name
    is produced no later than before.
    """
    inits: Dict[str, Msg] = {t.name: t for t in graph.initializer}
    uses: Dict[str, int] = {}
    for node in graph.node:
        for i in node.input or []:
            uses[i] = uses.get(i, 0) + 1
    for vi in graph.output:
        uses[vi.name] = uses.get(vi.name, 0) + 1
    # names referenced inside If/Loop/Scan subgraphs capture outer tensors
    # without appearing in top-level node inputs — never touch those
    def _subgraph_refs(g: Msg, out: set):
        for node in g.node or []:
            for i in node.input or []:
                out.add(i)
            for a in node.attribute or []:
                if a.g is not None:
                    _subgraph_refs(a.g, out)
                for sg in a.graphs or []:
                    _subgraph_refs(sg, out)

    sub_refs: set = set()
    for node in graph.node:
        for a in node.attribute or []:
            if a.g is not None:
                _subgraph_refs(a.g, sub_refs)
            for sg in a.graphs or []:
                _subgraph_refs(sg, sub_refs)
    for name in sub_refs:
        uses[name] = uses.get(name, 0) + 1

    # collect candidate groups keyed by (activation, inner_dim, dtype)
    groups: Dict[tuple, List[int]] = {}
    for idx, node in enumerate(graph.node):
        if node.op_type != "MatMul" or len(node.input) != 2:
            continue
        x, w = node.input
        if x in inits or w not in inits:
            continue
        # an initializer that also appears in graph.input is an
        # overridable feed — packing would bake it in and delete the
        # override point for other consumers of the rewritten graph
        if any(vi.name == w for vi in graph.input):
            continue
        t = inits[w]
        dims = [int(d) for d in (t.dims or [])]
        if len(dims) != 2 or uses.get(w, 0) != 1:
            continue
        # graph outputs must keep their producing node's exact identity
        if any(vi.name == node.output[0] for vi in graph.output):
            continue
        groups.setdefault((x, dims[0], int(t.data_type)), []).append(idx)

    packed = 0
    remove_nodes: set = set()
    remove_inits: set = set()
    splices: Dict[int, List[Msg]] = {}  # insert-before position -> nodes
    for (x, inner, _), idxs in groups.items():
        if len(idxs) < min_group:
            continue
        ws = [tensor_to_numpy(inits[graph.node[i].input[1]]) for i in idxs]
        sizes = [w.shape[1] for w in ws]
        w_pack = np.concatenate(ws, axis=1)
        base = graph.node[idxs[0]].output[0]
        pack_w_name = f"{base}__packed_w"
        pack_out = f"{base}__packed"
        split_sizes_name = f"{base}__packed_sizes"
        graph.initializer.append(numpy_to_tensor(w_pack, pack_w_name))

        mm = Msg("NodeProto")
        mm.op_type = "MatMul"
        mm.name = f"{base}__packed_matmul"
        mm.input = [x, pack_w_name]
        mm.output = [pack_out]
        mm.attribute = []
        sp = Msg("NodeProto")
        sp.op_type = "Split"
        sp.name = f"{base}__packed_split"
        sp.output = [graph.node[i].output[0] for i in idxs]
        ax = Msg("AttributeProto")
        ax.name = "axis"
        ax.type = 2  # INT
        ax.i = -1
        sp.attribute = [ax]
        if opset >= 13:  # sizes ride as an input tensor
            graph.initializer.append(numpy_to_tensor(
                np.asarray(sizes, np.int64), split_sizes_name))
            sp.input = [pack_out, split_sizes_name]
        else:            # pre-13 layout: sizes are an attribute
            sp.input = [pack_out]
            sz = Msg("AttributeProto")
            sz.name = "split"
            sz.type = 7  # INTS
            sz.ints = [int(s) for s in sizes]
            sp.attribute.append(sz)

        splices[min(idxs)] = [mm, sp]
        remove_nodes.update(idxs)
        remove_inits.update(graph.node[i].input[1] for i in idxs)
        packed += 1

    if not packed:
        return 0
    new_nodes: List[Msg] = []
    for idx, node in enumerate(graph.node):
        if idx in splices:
            new_nodes.extend(splices[idx])
        if idx not in remove_nodes:
            new_nodes.append(node)
    graph.node = new_nodes
    graph.initializer = [
        t for t in graph.initializer if t.name not in remove_inits
    ]
    return packed


def optimize_graph(graph: Msg, opset: int = 13) -> Msg:
    """All passes, in order. Mutates and returns ``graph``."""
    pack_parallel_matmuls(graph, opset)
    return graph
