"""Bundled ONNX model constructors (ResNet family).

The reference ships a ModelDownloader that fetches pretrained CNTK graphs from
a remote repo (ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/downloader/ModelDownloader.scala:197-265).
This environment has no network egress, so the zoo *constructs* the standard
torchvision-layout ResNet graphs as real ``.onnx`` protobuf bytes with seeded
He-initialized weights — the import / execution path exercised is byte-for-byte
the same one a user's downloaded ResNet-50 file takes: protobuf parse ->
node-by-node lowering -> jit. Weight dicts can also be supplied to build an
ONNX file from externally-trained parameters (the export story).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from synapseml_tpu.onnx.builder import GraphBuilder


class _Rng:
    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)

    def conv_w(self, out_c, in_c, kh, kw):
        fan_in = in_c * kh * kw
        std = np.sqrt(2.0 / fan_in)
        return self.rng.normal(0, std, (out_c, in_c, kh, kw)).astype(np.float32)

    def fc(self, out_f, in_f):
        std = np.sqrt(1.0 / in_f)
        w = self.rng.uniform(-std, std, (out_f, in_f)).astype(np.float32)
        b = self.rng.uniform(-std, std, (out_f,)).astype(np.float32)
        return w, b

    def bn(self, c):
        # running stats of a trained net are not identity; randomize mildly so
        # numerical-equivalence tests exercise the real BN math
        return (np.abs(self.rng.normal(1, 0.1, c)).astype(np.float32),
                self.rng.normal(0, 0.1, c).astype(np.float32),
                self.rng.normal(0, 0.5, c).astype(np.float32),
                np.abs(self.rng.normal(1, 0.2, c)).astype(np.float32) + 0.1)


def _bn_relu(g: GraphBuilder, r: _Rng, x: str, c: int, relu: bool = True) -> str:
    s, b, m, v = r.bn(c)
    y = g.batch_norm(x, s, b, m, v)
    return g.relu(y) if relu else y


def _basic_block(g, r, x, in_c, out_c, stride):
    y = g.conv(x, r.conv_w(out_c, in_c, 3, 3), strides=(stride, stride),
               pads=(1, 1, 1, 1))
    y = _bn_relu(g, r, y, out_c)
    y = g.conv(y, r.conv_w(out_c, out_c, 3, 3), pads=(1, 1, 1, 1))
    y = _bn_relu(g, r, y, out_c, relu=False)
    if stride != 1 or in_c != out_c:
        sc = g.conv(x, r.conv_w(out_c, in_c, 1, 1), strides=(stride, stride))
        sc = _bn_relu(g, r, sc, out_c, relu=False)
    else:
        sc = x
    return g.relu(g.add_node("Add", [y, sc]))


def _bottleneck(g, r, x, in_c, mid_c, stride):
    out_c = mid_c * 4
    y = g.conv(x, r.conv_w(mid_c, in_c, 1, 1))
    y = _bn_relu(g, r, y, mid_c)
    y = g.conv(y, r.conv_w(mid_c, mid_c, 3, 3), strides=(stride, stride),
               pads=(1, 1, 1, 1))
    y = _bn_relu(g, r, y, mid_c)
    y = g.conv(y, r.conv_w(out_c, mid_c, 1, 1))
    y = _bn_relu(g, r, y, out_c, relu=False)
    if stride != 1 or in_c != out_c:
        sc = g.conv(x, r.conv_w(out_c, in_c, 1, 1), strides=(stride, stride))
        sc = _bn_relu(g, r, sc, out_c, relu=False)
    else:
        sc = x
    return g.relu(g.add_node("Add", [y, sc]))


def build_resnet(depths: Sequence[int], bottleneck: bool, num_classes: int = 1000,
                 width: int = 64, image_size: int = 224, seed: int = 0,
                 batch_dim="N") -> bytes:
    """Emit a torchvision-layout ResNet as ONNX bytes."""
    g = GraphBuilder(name=f"resnet{'_bn' if bottleneck else ''}", opset=17)
    r = _Rng(seed)
    x = g.add_input("data", np.float32, [batch_dim, 3, image_size, image_size])
    y = g.conv(x, r.conv_w(width, 3, 7, 7), strides=(2, 2), pads=(3, 3, 3, 3))
    y = _bn_relu(g, r, y, width)
    y = g.add_node("MaxPool", [y], kernel_shape=[3, 3], strides=[2, 2],
                   pads=[1, 1, 1, 1])
    in_c = width
    chan = width
    for stage, n_blocks in enumerate(depths):
        for blk in range(n_blocks):
            stride = 2 if (stage > 0 and blk == 0) else 1
            if bottleneck:
                y = _bottleneck(g, r, y, in_c, chan, stride)
                in_c = chan * 4
            else:
                y = _basic_block(g, r, y, in_c, chan, stride)
                in_c = chan
        chan *= 2
    y = g.add_node("GlobalAveragePool", [y])
    y = g.add_node("Flatten", [y], axis=1)
    w, b = r.fc(num_classes, in_c)
    y = g.gemm(y, w, b)
    g.add_output(y, np.float32, [batch_dim, num_classes])
    return g.to_bytes()


def resnet50(num_classes: int = 1000, image_size: int = 224, seed: int = 0) -> bytes:
    return build_resnet([3, 4, 6, 3], bottleneck=True, num_classes=num_classes,
                        image_size=image_size, seed=seed)


def resnet18(num_classes: int = 1000, image_size: int = 224, seed: int = 0) -> bytes:
    return build_resnet([2, 2, 2, 2], bottleneck=False, num_classes=num_classes,
                        image_size=image_size, seed=seed)


def tiny_resnet(num_classes: int = 10, image_size: int = 32, seed: int = 0) -> bytes:
    """Small ResNet for tests: same op inventory as resnet50, tiny shapes."""
    return build_resnet([1, 1], bottleneck=True, num_classes=num_classes,
                        width=8, image_size=image_size, seed=seed)


def mlp(layer_sizes: Sequence[int], num_classes: int, seed: int = 0,
        activation: str = "Relu") -> bytes:
    """Plain MLP with a trailing Softmax — the classical-ML ONNX shape."""
    g = GraphBuilder(name="mlp", opset=17)
    r = _Rng(seed)
    x = g.add_input("input", np.float32, ["N", layer_sizes[0]])
    y = x
    dims = list(layer_sizes[1:]) + [num_classes]
    prev = layer_sizes[0]
    for i, d in enumerate(dims):
        w, b = r.fc(d, prev)
        y = g.gemm(y, w, b)
        if i < len(dims) - 1:
            y = g.add_node(activation, [y])
        prev = d
    probs = g.add_node("Softmax", [y], axis=-1)
    g.add_output(probs, np.float32, ["N", num_classes])
    return g.to_bytes()


def bilstm_tagger(vocab: int, embed: int, hidden: int, n_tags: int,
                  seq_len: int = 64, seed: int = 0) -> bytes:
    """Bidirectional-LSTM token tagger (the reference's BiLSTM medical-entity
    config, BASELINE config #5) as an ONNX graph: Gather(embedding) -> LSTM
    (bidirectional) -> Gemm per token."""
    g = GraphBuilder(name="bilstm_tagger", opset=17)
    r = _Rng(seed)
    ids = g.add_input("tokens", np.int64, ["N", seq_len])
    emb = g.add_initializer(
        "embedding", r.rng.normal(0, 0.1, (vocab, embed)).astype(np.float32))
    x = g.add_node("Gather", [emb, ids], axis=0)          # (N, S, E)
    x = g.add_node("Transpose", [x], perm=[1, 0, 2])      # (S, N, E)
    w = g.add_initializer("lstm_w", np.stack([
        r.rng.normal(0, 0.1, (4 * hidden, embed)).astype(np.float32)
        for _ in range(2)]))
    rr = g.add_initializer("lstm_r", np.stack([
        r.rng.normal(0, 0.1, (4 * hidden, hidden)).astype(np.float32)
        for _ in range(2)]))
    b = g.add_initializer(
        "lstm_b", np.zeros((2, 8 * hidden), dtype=np.float32))
    y = g.add_node("LSTM", [x, w, rr, b], outputs=["lstm_y", "lstm_h", "lstm_c"],
                   hidden_size=hidden, direction="bidirectional")
    y = y[0] if isinstance(y, list) else y
    y = g.add_node("Transpose", [y], perm=[2, 0, 1, 3])   # (N, S, dirs, H)
    shp = g.add_initializer("flat_shape", np.array([0, seq_len, 2 * hidden],
                                                   dtype=np.int64))
    y = g.add_node("Reshape", [y, shp])
    wf, bf = r.fc(n_tags, 2 * hidden)
    wn = g.add_initializer("head_w", np.ascontiguousarray(wf.T))
    bn = g.add_initializer("head_b", bf)
    y = g.add_node("MatMul", [y, wn])
    y = g.add_node("Add", [y, bn])
    g.add_output(y, np.float32, ["N", seq_len, n_tags])
    return g.to_bytes()


def transformer_encoder(vocab: int, d_model: int, n_heads: int,
                        ffn_dim: int, n_layers: int, seq_len: int = 32,
                        causal: bool = False, seed: int = 0) -> bytes:
    """Pre-LN transformer encoder as an ONNX graph (the BERT-era op diet:
    Gather embeddings, MatMul/Transpose/Softmax attention,
    LayerNormalization, Gelu FFN, Trilu causal mask when requested) —
    exercises the importer on modern-architecture graphs the way resnet50
    exercises the CNN opset."""
    assert d_model % n_heads == 0
    hd = d_model // n_heads
    # opset 20: Gelu joined the default ai.onnx domain at 20 (Trilu needs
    # >=14, LayerNormalization >=17) — a lower opset would be spec-invalid
    g = GraphBuilder(name="transformer_encoder", opset=20)
    r = _Rng(seed)

    ids = g.add_input("tokens", np.int64, ["N", seq_len])
    emb = g.add_initializer(
        "tok_emb", r.rng.normal(0, 0.05, (vocab, d_model)).astype(np.float32))
    pos = g.add_initializer(
        "pos_emb", r.rng.normal(0, 0.05, (seq_len, d_model)).astype(np.float32))
    x = g.add_node("Gather", [emb, ids], axis=0)          # (N, S, D)
    x = g.add_node("Add", [x, pos])

    if causal:
        ones = g.add_initializer(
            "mask_ones", np.ones((seq_len, seq_len), np.float32))
        upper = g.add_node("Trilu", [ones], upper=1)
        diag = g.add_node("Trilu", [upper], upper=0)      # identity diag
        strict_upper = g.add_node("Sub", [upper, diag])
        neg = g.add_initializer("neg_inf", np.float32(-1e9))
        causal_bias = g.add_node("Mul", [strict_upper, neg])  # (S, S)

    def lin(x, out_f, in_f, name):
        w, b = r.fc(out_f, in_f)
        wn = g.add_initializer(f"{name}_w", np.ascontiguousarray(w.T))
        bn = g.add_initializer(f"{name}_b", b)
        y = g.add_node("MatMul", [x, wn])
        return g.add_node("Add", [y, bn])

    def layer_norm(x, name):
        s = g.add_initializer(f"{name}_s", np.ones(d_model, np.float32))
        b = g.add_initializer(f"{name}_b", np.zeros(d_model, np.float32))
        return g.add_node("LayerNormalization", [x, s, b], axis=-1)

    heads_shape = g.add_initializer(
        "heads_shape", np.array([0, seq_len, n_heads, hd], np.int64))
    merge_shape = g.add_initializer(
        "merge_shape", np.array([0, seq_len, d_model], np.int64))
    scale = g.add_initializer("attn_scale",
                              np.float32(1.0 / np.sqrt(hd)))

    for li in range(n_layers):
        ln1 = layer_norm(x, f"l{li}_ln1")
        q = lin(ln1, d_model, d_model, f"l{li}_q")
        k = lin(ln1, d_model, d_model, f"l{li}_k")
        v = lin(ln1, d_model, d_model, f"l{li}_v")

        def split_heads(t):
            t = g.add_node("Reshape", [t, heads_shape])   # (N, S, H, hd)
            return g.add_node("Transpose", [t], perm=[0, 2, 1, 3])

        qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
        kt = g.add_node("Transpose", [kh], perm=[0, 1, 3, 2])
        logits = g.add_node("MatMul", [qh, kt])           # (N, H, S, S)
        logits = g.add_node("Mul", [logits, scale])
        if causal:
            logits = g.add_node("Add", [logits, causal_bias])
        attn = g.add_node("Softmax", [logits], axis=-1)
        ctxv = g.add_node("MatMul", [attn, vh])           # (N, H, S, hd)
        ctxv = g.add_node("Transpose", [ctxv], perm=[0, 2, 1, 3])
        ctxv = g.add_node("Reshape", [ctxv, merge_shape])
        proj = lin(ctxv, d_model, d_model, f"l{li}_o")
        x = g.add_node("Add", [x, proj])

        ln2 = layer_norm(x, f"l{li}_ln2")
        h = lin(ln2, ffn_dim, d_model, f"l{li}_ff1")
        h = g.add_node("Gelu", [h])
        h = lin(h, d_model, ffn_dim, f"l{li}_ff2")
        x = g.add_node("Add", [x, h])

    x = layer_norm(x, "final_ln")
    g.add_output(x, np.float32, ["N", seq_len, d_model])
    return g.to_bytes()


def tiny_decoder(vocab: int = 64, d_model: int = 32, n_heads: int = 4,
                 kv_heads: int = 2, n_layers: int = 2,
                 max_seq: int = 128, seed: int = 0) -> bytes:
    """Decoder-only LM in the ORT-GenAI serving-cache layout: packed-QKV
    GroupQueryAttention with ``past_present_share_buffer=1`` and internal
    rotary, pre-LN FFN blocks, LM head. Every attention input/output is
    symbolic in B/S/T, so ONE file serves every (batch, chunk, buffer)
    geometry the decode scheduler compiles — prefill feeds S=chunk
    against a zeroed buffer, decode feeds S=1 against the live buffer,
    and ``seqlens_k`` (ORT convention: total valid keys - 1) carries each
    row's write position. ``max_seq`` caps the rope cache, so every KV
    buffer bucket must satisfy T <= max_seq."""
    assert d_model % n_heads == 0 and n_heads % kv_heads == 0
    hd = d_model // n_heads
    g = GraphBuilder(name="tiny_decoder", opset=21)
    r = _Rng(seed)

    ids = g.add_input("input_ids", np.int64, ["B", "S"])
    seqlens = g.add_input("seqlens_k", np.int32, ["B"])
    emb = g.add_initializer(
        "tok_emb", r.rng.normal(0, 0.05, (vocab, d_model)).astype(
            np.float32))
    x = g.add_node("Gather", [emb, ids], axis=0)          # (B, S, D)

    inv = 10000.0 ** (np.arange(hd // 2) / (hd // 2))
    ang = np.arange(max_seq)[:, None] / inv
    cos = g.add_initializer("rope_cos", np.cos(ang).astype(np.float32))
    sin = g.add_initializer("rope_sin", np.sin(ang).astype(np.float32))

    def lin(x, out_f, in_f, name):
        w, b = r.fc(out_f, in_f)
        wn = g.add_initializer(f"{name}_w", np.ascontiguousarray(w.T))
        bn = g.add_initializer(f"{name}_b", b)
        y = g.add_node("MatMul", [x, wn])
        return g.add_node("Add", [y, bn])

    def layer_norm(x, name):
        s = g.add_initializer(f"{name}_s", np.ones(d_model, np.float32))
        b = g.add_initializer(f"{name}_b", np.zeros(d_model, np.float32))
        return g.add_node("LayerNormalization", [x, s, b], axis=-1)

    presents: List[str] = []
    for li in range(n_layers):
        ln1 = layer_norm(x, f"l{li}_ln1")
        qkv = lin(ln1, (n_heads + 2 * kv_heads) * hd, d_model,
                  f"l{li}_qkv")
        pk = g.add_input(f"past_key_{li}", np.float32,
                         ["B", kv_heads, "T", hd])
        pv = g.add_input(f"past_value_{li}", np.float32,
                         ["B", kv_heads, "T", hd])
        att, prk, prv = g.add_node(
            "GroupQueryAttention",
            [qkv, "", "", pk, pv, seqlens, "", cos, sin],
            outputs=[f"att_{li}", f"present_key_{li}",
                     f"present_value_{li}"],
            domain="com.microsoft", num_heads=n_heads,
            kv_num_heads=kv_heads, do_rotary=1,
            past_present_share_buffer=1)
        presents += [prk, prv]
        proj = lin(att, d_model, n_heads * hd, f"l{li}_o")
        x = g.add_node("Add", [x, proj])

        ln2 = layer_norm(x, f"l{li}_ln2")
        h = lin(ln2, 2 * d_model, d_model, f"l{li}_ff1")
        h = g.add_node("Gelu", [h])
        h = lin(h, d_model, 2 * d_model, f"l{li}_ff2")
        x = g.add_node("Add", [x, h])

    x = layer_norm(x, "final_ln")
    logits = lin(x, vocab, d_model, "lm_head")
    g.add_output(logits, np.float32, ["B", "S", vocab])
    for p in presents:
        g.add_output(p, np.float32, None)
    return g.to_bytes()
