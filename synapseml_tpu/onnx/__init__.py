"""ONNX subsystem: protobuf codec, graph->jax importer, ONNXModel transformer.

TPU-native replacement of the reference's onnxruntime-backed ONNXModel
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala).
"""
from synapseml_tpu.onnx.builder import GraphBuilder
from synapseml_tpu.onnx.importer import ImportedGraph, import_model, supported_ops
from synapseml_tpu.onnx.convert import convert_lightgbm
from synapseml_tpu.onnx.model import ONNXModel
from synapseml_tpu.onnx import proto, zoo

__all__ = [
    "GraphBuilder", "ImportedGraph", "ONNXModel", "convert_lightgbm",
    "import_model", "supported_ops", "proto", "zoo",
]
