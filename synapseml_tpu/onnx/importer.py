"""ONNX graph -> jax importer: re-lowers a loaded ONNX graph to XLA.

This is the TPU-native replacement of the reference's onnxruntime execution
path (ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala:173-193,305-355):
instead of handing the serialized graph to a native session per partition, the
graph is parsed once (:mod:`synapseml_tpu.onnx.proto`), each node is mapped to
a jax/lax op, and the whole model becomes a single pure ``apply(params, *inputs)``
function that ``jax.jit`` compiles to one fused XLA program — weights live on
device as a pytree, so sharding/donation work like any jax model.

Design notes (TPU-first):
- **Static shape propagation**: shape-manipulation subgraphs that exporters
  emit (Shape -> Gather -> Concat -> Reshape chains) are computed eagerly in
  numpy during tracing, so XLA always sees static shapes.
- **Opset awareness**: ops whose signature changed across opsets (Squeeze /
  Unsqueeze / Slice / Clip / Pad axes-as-attr vs axes-as-input, Softmax
  flatten-vs-axis semantics) dispatch on the model's opset version.
- Recurrent ops (LSTM/GRU/RNN) lower to ``lax.scan`` so long sequences stay
  on-device with O(1) compiled program size.
"""
from __future__ import annotations

import math
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from synapseml_tpu.onnx import proto
from synapseml_tpu.onnx.proto import Msg, node_attrs, tensor_to_numpy

_REGISTRY: Dict[str, Callable] = {}


def op(*names: str):
    def deco(fn):
        for n in names:
            _REGISTRY[n] = fn
        return fn
    return deco


class OpContext:
    """Per-node context handed to op impls."""

    __slots__ = ("attrs", "opset", "name", "op_type", "n_outputs")

    def __init__(self, attrs: Dict[str, Any], opset: int, name: str,
                 op_type: str, n_outputs: int):
        self.attrs = attrs
        self.opset = opset
        self.name = name
        self.op_type = op_type
        self.n_outputs = n_outputs

    def attr(self, key: str, default: Any = None) -> Any:
        return self.attrs.get(key, default)


def _is_host(x) -> bool:
    return isinstance(x, (np.ndarray, np.generic, int, float, bool))


def _all_host(inputs) -> bool:
    return all(x is None or _is_host(x) for x in inputs)


def _static_int_list(x, what: str) -> List[int]:
    """Require a host-side (concrete) integer vector — used for shapes/axes."""
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [int(v) for v in x]
    if _is_host(x):
        return [int(v) for v in np.asarray(x).reshape(-1)]
    raise ValueError(
        f"ONNX import: {what} must be statically known (got traced value); "
        "constant-fold the producing subgraph or use an initializer")


# ---------------------------------------------------------------------------
# Elementwise / math
# ---------------------------------------------------------------------------

def _ew(fn_np, fn_jnp=None):
    fn_jnp = fn_jnp or fn_np

    def impl(ctx, *xs):
        if _all_host(xs):
            return fn_np(*[np.asarray(x) for x in xs])
        return fn_jnp(*xs)
    return impl


for _name, _np_fn, _jnp_fn in [
    ("Add", np.add, jnp.add), ("Sub", np.subtract, jnp.subtract),
    ("Mul", np.multiply, jnp.multiply), ("Div", np.divide, jnp.divide),
    ("Pow", np.power, jnp.power),
    ("Equal", np.equal, jnp.equal), ("Greater", np.greater, jnp.greater),
    ("Less", np.less, jnp.less),
    ("GreaterOrEqual", np.greater_equal, jnp.greater_equal),
    ("LessOrEqual", np.less_equal, jnp.less_equal),
    ("And", np.logical_and, jnp.logical_and),
    ("Or", np.logical_or, jnp.logical_or),
    ("Xor", np.logical_xor, jnp.logical_xor),
]:
    _REGISTRY[_name] = _ew(_np_fn, _jnp_fn)

# Div on integers is floor-toward-zero in ONNX; jnp.divide promotes to float.
def _int_safe_div(ctx, a, b):
    xp = np if _all_host((a, b)) else jnp
    if np.issubdtype(np.asarray(a).dtype if xp is np else a.dtype, np.integer):
        return xp.sign(a) * xp.sign(b) * (xp.abs(a) // xp.abs(b))
    return xp.divide(a, b)
_REGISTRY["Div"] = _int_safe_div


for _name, _fn in [
    ("Relu", lambda x: jnp.maximum(x, 0)), ("Sigmoid", jax.nn.sigmoid),
    ("Tanh", jnp.tanh), ("Exp", jnp.exp), ("Log", jnp.log),
    ("Sqrt", jnp.sqrt), ("Reciprocal", lambda x: 1.0 / x),
    ("Neg", jnp.negative), ("Abs", jnp.abs), ("Floor", jnp.floor),
    ("Ceil", jnp.ceil), ("Sign", jnp.sign), ("Erf", jax.scipy.special.erf),
    ("Softplus", jax.nn.softplus), ("Not", jnp.logical_not),
    ("Sin", jnp.sin), ("Cos", jnp.cos), ("Tan", jnp.tan),
    ("Asin", jnp.arcsin), ("Acos", jnp.arccos), ("Atan", jnp.arctan),
    ("Sinh", jnp.sinh), ("Cosh", jnp.cosh),
    ("Asinh", jnp.arcsinh), ("Acosh", jnp.arccosh), ("Atanh", jnp.arctanh),
    ("Det", jnp.linalg.det),
    ("IsNaN", jnp.isnan), ("IsInf", jnp.isinf),
    ("Softsign", lambda x: x / (1 + jnp.abs(x))),
    ("Round", jnp.round),
]:
    _REGISTRY[_name] = (lambda f: lambda ctx, x: f(x))(_fn)


@op("LeakyRelu")
def _leaky_relu(ctx, x):
    return jnp.where(x >= 0, x, ctx.attr("alpha", 0.01) * x)


@op("PRelu")
def _prelu(ctx, x, slope):
    # slope broadcasts from channel axis; ONNX allows unidirectional broadcast
    if slope.ndim < x.ndim and slope.ndim >= 1:
        slope = slope.reshape((1,) + slope.shape + (1,) * (x.ndim - slope.ndim - 1))
    return jnp.where(x >= 0, x, slope * x)


@op("Elu")
def _elu(ctx, x):
    a = ctx.attr("alpha", 1.0)
    return jnp.where(x >= 0, x, a * (jnp.exp(x) - 1))


@op("Selu")
def _selu(ctx, x):
    a = ctx.attr("alpha", 1.6732632423543772)
    g = ctx.attr("gamma", 1.0507009873554805)
    return g * jnp.where(x >= 0, x, a * (jnp.exp(x) - 1))


@op("HardSigmoid")
def _hard_sigmoid(ctx, x):
    a, b = ctx.attr("alpha", 0.2), ctx.attr("beta", 0.5)
    return jnp.clip(a * x + b, 0, 1)


@op("HardSwish")
def _hard_swish(ctx, x):
    return x * jnp.clip(x / 6.0 + 0.5, 0, 1)


@op("Gelu")
def _gelu(ctx, x):
    return jax.nn.gelu(x, approximate=ctx.attr("approximate", "none") == "tanh")


@op("Mish")
def _mish(ctx, x):
    return x * jnp.tanh(jax.nn.softplus(x))


@op("Celu")
def _celu(ctx, x):
    a = ctx.attr("alpha", 1.0)
    return jnp.maximum(x, 0) + jnp.minimum(0.0, a * (jnp.exp(x / a) - 1))


@op("Affine")
def _affine(ctx, x):
    # legacy experimental op (pre-opset-10 exporters): alpha * x + beta
    return ctx.attr("alpha", 1.0) * x + ctx.attr("beta", 0.0)


@op("ThresholdedRelu")
def _thresholded_relu(ctx, x):
    a = ctx.attr("alpha", 1.0)
    return jnp.where(x > a, x, 0.0)


@op("Shrink")
def _shrink(ctx, x):
    lambd = ctx.attr("lambd", 0.5)
    bias = ctx.attr("bias", 0.0)
    return jnp.where(x < -lambd, x + bias,
                     jnp.where(x > lambd, x - bias, 0.0))


@op("BitShift")
def _bit_shift(ctx, x, y):
    xp = np if _all_host((x, y)) else jnp
    if ctx.attr("direction", "LEFT") == "LEFT":
        return xp.left_shift(x, y)
    return xp.right_shift(x, y)


def _per_axis_qparams(x, axis, scale, zp):
    """Reshape 1-D per-channel quantization scale/zero-point for
    broadcast along ``axis`` of ``x`` (shared by Quantize/Dequantize)."""
    if np.ndim(scale) == 1 and np.ndim(x) > 1:
        shape = [1] * np.ndim(x)
        shape[axis % np.ndim(x)] = -1
        scale = jnp.reshape(jnp.asarray(scale), shape)
        zp = jnp.reshape(jnp.asarray(zp), shape) if np.ndim(zp) == 1 else zp
    return scale, zp


@op("QuantizeLinear")
def _quantize_linear(ctx, x, scale, zero_point=None):
    """fp -> int8/uint8 affine quantization (the mobile-export idiom).
    axis applies when scale is 1-D per-channel."""
    dtype = np.uint8 if zero_point is None else np.asarray(zero_point).dtype
    zp = 0 if zero_point is None else zero_point
    scale, zp = _per_axis_qparams(x, ctx.attr("axis", 1), scale, zp)
    info = np.iinfo(np.dtype(dtype))
    q = jnp.round(jnp.asarray(x) / scale) + jnp.asarray(zp, jnp.float32)
    return jnp.clip(q, info.min, info.max).astype(dtype)


@op("DequantizeLinear")
def _dequantize_linear(ctx, x, scale, zero_point=None):
    zp = 0 if zero_point is None else zero_point
    scale, zp = _per_axis_qparams(x, ctx.attr("axis", 1), scale, zp)
    return (jnp.asarray(x).astype(jnp.float32)
            - jnp.asarray(zp).astype(jnp.float32)) * scale


def _matmul_wide_core(a, b, a_zp=None, b_zp=None):
    """Widened integer matmul: operands upcast to int32, zero points
    subtracted BEFORE the contraction — the always-correct reference
    formulation (and the fallback lane of the int8 router)."""
    a32 = jnp.asarray(a).astype(jnp.int32)
    b32 = jnp.asarray(b).astype(jnp.int32)
    if a_zp is not None:
        zp = jnp.asarray(a_zp).astype(jnp.int32)
        if zp.ndim == 1:  # per-ROW zero point broadcasts down the rows
            zp = zp[:, None]
        a32 = a32 - zp
    if b_zp is not None:  # 1-D b_zp is per-column: trailing-axis broadcast
        b32 = b32 - jnp.asarray(b_zp).astype(jnp.int32)
    return jax.lax.dot_general(
        a32, b32,
        (((a32.ndim - 1,), (b32.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.int32) if a32.ndim == 2 and b32.ndim == 2 \
        else jnp.matmul(a32, b32)


def _to_int8(x):
    """(int8 view, offset) with ``x == view + offset`` elementwise:
    int8 passes through, uint8 rides an exact -128 shift so the MXU's
    s8xs8 path consumes it natively."""
    x = jnp.asarray(x)
    if x.dtype == jnp.uint8:
        return (x.astype(jnp.int16) - 128).astype(jnp.int8), 128
    return x, 0


def _matmul_int8_core(a, b, a_zp=None, b_zp=None):
    """TRUE int8 matmul lane: the contraction consumes int8 operands
    (``preferred_element_type=int32`` — the MXU's native s8xs8 path);
    zero points become EXACT integer correction terms after the dot:

        (a - za)·(b - zb) = a·b - za*colsum(b) - zb*rowsum(a) + K*za*zb

    (with the uint8 -128 shift folded into za/zb). Bit-identical to
    :func:`_matmul_wide_core` — the router's probe asserts exactly
    that before this lane ever serves. 2-D x 2-D only (router-gated)."""
    a8, a_off = _to_int8(a)
    b8, b_off = _to_int8(b)
    acc = jax.lax.dot_general(
        a8, b8, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                     # [N, M]
    kdim = a8.shape[1]
    za = (jnp.asarray(a_zp).astype(jnp.int32) - a_off
          if a_zp is not None else jnp.int32(-a_off))
    zb = (jnp.asarray(b_zp).astype(jnp.int32) - b_off
          if b_zp is not None else jnp.int32(-b_off))
    za_col = za[:, None] if za.ndim == 1 else za              # [N,1]|scalar
    zb_row = zb[None, :] if zb.ndim == 1 else zb              # [1,M]|scalar
    need_za = a_zp is not None or a_off
    need_zb = b_zp is not None or b_off
    if need_za:
        cs = jnp.sum(b8.astype(jnp.int32), axis=0)[None, :]   # [1, M]
        acc = acc - za_col * cs
    if need_zb:
        rs = jnp.sum(a8.astype(jnp.int32), axis=1)[:, None]   # [N, 1]
        acc = acc - zb_row * rs
    if need_za and need_zb:
        acc = acc + kdim * za_col * zb_row
    return acc


@op("MatMulInteger")
def _matmul_integer(ctx, a, b, a_zp=None, b_zp=None):
    """int8 matmul accumulating in int32 (quantized-model compute).
    Routed (onnx/quant_route.py): the true-int8 lane where the
    measured prober verified it exact and faster, the widened int32
    formulation everywhere else — a lane failure silently falls back."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    from synapseml_tpu.onnx import quant_route

    if quant_route.route_matmul(a, b, a_zp, b_zp,
                                do_count=False) == "int8":
        try:
            out = _matmul_int8_core(a, b, a_zp, b_zp)
            quant_route.count("int8")
            return out
        except Exception:  # noqa: BLE001 - silent fallback is the contract
            quant_route.poison_matmul(a, b, a_zp, b_zp)
    # served-by honesty (catalog contract): the routed-away case AND a
    # trace-time int8-leg failure both count the widened lane
    quant_route.count("dequant")
    return _matmul_wide_core(a, b, a_zp, b_zp)


@op("DynamicQuantizeLinear")
def _dynamic_quantize_linear(ctx, x):
    """x -> (uint8 y, scale, zero_point), ONNX spec formula: the range is
    extended to include 0 so zero stays exactly representable (the
    dynamic-quantization idiom onnxruntime emits for int8 inference)."""
    x = jnp.asarray(x, jnp.float32)
    mn = jnp.minimum(x.min(), 0.0)
    mx = jnp.maximum(x.max(), 0.0)
    scale = (mx - mn) / 255.0
    scale = jnp.where(scale <= 0, jnp.float32(1.0), scale)  # constant input
    zp = jnp.clip(jnp.round(-mn / scale), 0, 255)
    y = jnp.clip(jnp.round(x / scale) + zp, 0, 255).astype(jnp.uint8)
    return y, scale.astype(jnp.float32), zp.astype(jnp.uint8)


def _conv_params(ctx, x_shape, w_shape):
    rank = len(x_shape) - 2
    strides = ctx.attr("strides", [1] * rank)
    dilations = ctx.attr("dilations", [1] * rank)
    group = ctx.attr("group", 1)
    kernel = ctx.attr("kernel_shape", list(w_shape[2:]))
    pads = _resolve_pads(ctx, x_shape[2:], kernel, strides, dilations)
    return rank, strides, dilations, group, pads


def _conv_wide_core(ctx, x, w, x_zp=None, w_zp=None):
    """Widened integer conv: operands upcast to int32, zero points
    subtracted BEFORE the conv — the reference formulation (and the
    fallback lane of the int8 router)."""
    x32 = jnp.asarray(x).astype(jnp.int32)
    w32 = jnp.asarray(w).astype(jnp.int32)
    if x_zp is not None:
        x32 = x32 - jnp.asarray(x_zp).astype(jnp.int32)  # scalar per spec
    if w_zp is not None:
        zp = jnp.asarray(w_zp).astype(jnp.int32)
        if zp.ndim == 1:  # per-output-channel
            zp = zp.reshape((-1,) + (1,) * (w32.ndim - 1))
        w32 = w32 - zp
    rank, strides, dilations, group, pads = _conv_params(
        ctx, x32.shape, w32.shape)
    return lax.conv_general_dilated(
        x32, w32, window_strides=strides, padding=pads,
        rhs_dilation=dilations, feature_group_count=group,
        dimension_numbers=_conv_dims(rank),
        preferred_element_type=jnp.int32)


def _conv_int8_core(ctx, x, w, x_zp=None, w_zp=None):
    """TRUE int8 conv lane: the conv consumes int8 operands
    (``preferred_element_type=int32``); the activation zero point
    becomes ONE exact integer correction conv after the big one:

        conv(x - zx, w) = conv(x, w) - zx * conv(ones_like(x), w)

    where both convs share the zero padding, so the correction's
    ones-conv yields each output position's valid-window weight sum —
    identical border behavior to shifting before padding (the widened
    path pads the ALREADY-shifted activations with zero). The uint8
    -128 shift folds into zx. Weights must be int8 with a zero (or
    absent) zero point — the router gates on exactly that — so no
    weight-side correction exists. Bit-identical to
    :func:`_conv_wide_core`; the router's probe asserts it."""
    x8, x_off = _to_int8(x)
    w8 = jnp.asarray(w)  # int8 already (router-gated), w_zp == 0
    rank, strides, dilations, group, pads = _conv_params(
        ctx, x8.shape, w8.shape)

    def int8_conv(lhs, rhs):
        return lax.conv_general_dilated(
            lhs, rhs, window_strides=strides, padding=pads,
            rhs_dilation=dilations, feature_group_count=group,
            dimension_numbers=_conv_dims(rank),
            preferred_element_type=jnp.int32)

    acc = int8_conv(x8, w8)
    zx = (jnp.asarray(x_zp).astype(jnp.int32) - x_off
          if x_zp is not None else jnp.int32(-x_off))
    if x_zp is not None or x_off:
        ones = jnp.ones((1,) + x8.shape[1:], jnp.int8)
        acc = acc - zx * int8_conv(ones, w8)   # [1, Cout, *] broadcasts
    return acc


def _int_conv_core(ctx, x, w, x_zp=None, w_zp=None):
    """Integer conv accumulating in int32 — the shared engine of
    ConvInteger and QLinearConv, routed (onnx/quant_route.py): the
    true-int8 lane where the measured prober verified it exact and
    faster, the widened int32 formulation everywhere else."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    from synapseml_tpu.onnx import quant_route

    attrs = _conv_attr_key(ctx, x, w)
    if quant_route.route_conv(x, w, x_zp, w_zp, attrs,
                              do_count=False) == "int8":
        try:
            out = _conv_int8_core(ctx, x, w, x_zp, None)
            quant_route.count("int8")
            return out
        except Exception:  # noqa: BLE001 - silent fallback is the contract
            quant_route.poison_conv(x, w, x_zp, attrs)
    quant_route.count("dequant")
    return _conv_wide_core(ctx, x, w, x_zp, w_zp)


def _conv_attr_key(ctx, x, w) -> str:
    """The conv attributes that change the compiled program, as a
    stable JSON key fragment for the router (also how the probe
    reconstructs an equivalent ctx outside a real graph)."""
    import json

    rank = x.ndim - 2
    return json.dumps({
        "strides": list(ctx.attr("strides", [1] * rank)),
        "dilations": list(ctx.attr("dilations", [1] * rank)),
        "group": ctx.attr("group", 1),
        "kernel_shape": list(ctx.attr("kernel_shape",
                                      list(w.shape[2:]))),
        "pads": ctx.attr("pads"),
        "auto_pad": ctx.attr("auto_pad", "NOTSET"),
    }, sort_keys=True)


@op("ConvInteger")
def _conv_integer(ctx, x, w, x_zp=None, w_zp=None):
    """int8/uint8 conv -> raw int32 accumulator (the integer half of a
    dynamically-quantized conv; requantization happens in the graph)."""
    return _int_conv_core(ctx, x, w, x_zp, w_zp)


def _requantize(acc32, combined_scale, y_zp):
    """int32 accumulator -> affine-quantized output: scale in float32,
    round-half-to-even, shift by the output zero point, saturate to the
    zero point's dtype (onnxruntime's requantization semantics)."""
    out_dt = np.dtype(np.asarray(y_zp).dtype)
    info = np.iinfo(out_dt)
    q = (jnp.round(acc32.astype(jnp.float32) * combined_scale)
         + jnp.asarray(y_zp).astype(jnp.float32))
    return jnp.clip(q, info.min, info.max).astype(out_dt)


@op("QLinearConv")
def _qlinear_conv(ctx, x, x_scale, x_zp, w, w_scale, w_zp, y_scale, y_zp,
                  b=None):
    """Statically-quantized conv (onnxruntime static-QDQ exports,
    ref ONNXModel.scala:173-193 — the reference scores whatever ORT
    runs): int32 accumulation, then requantization. Bias is int32 at
    scale x_scale*w_scale per spec; w_scale may be per-output-channel."""
    acc = _int_conv_core(ctx, x, w, x_zp, w_zp)
    rank = acc.ndim - 2
    if b is not None:
        acc = acc + jnp.asarray(b).astype(jnp.int32).reshape(
            (1, -1) + (1,) * rank)
    w_s = jnp.asarray(w_scale, jnp.float32)
    if w_s.ndim == 1:
        w_s = w_s.reshape((1, -1) + (1,) * rank)
    combined = (jnp.asarray(x_scale, jnp.float32) * w_s
                / jnp.asarray(y_scale, jnp.float32))
    return _requantize(acc, combined, y_zp)


@op("QLinearMatMul")
def _qlinear_matmul(ctx, a, a_scale, a_zp, b, b_scale, b_zp, y_scale,
                    y_zp):
    """Statically-quantized matmul: MatMulInteger accumulation + the
    shared requantization. 1-D a_scale is per-row, 1-D b_scale is
    per-column (ONNX spec broadcast)."""
    acc = _matmul_integer(ctx, a, b, a_zp, b_zp)
    a_s = jnp.asarray(a_scale, jnp.float32)
    if a_s.ndim == 1:
        a_s = a_s[:, None]
    combined = (a_s * jnp.asarray(b_scale, jnp.float32)
                / jnp.asarray(y_scale, jnp.float32))
    return _requantize(acc, combined, y_zp)


def _dq(x, scale, zp):
    """Affine dequantize to f32 (per-tensor, the com.microsoft contrib
    ops' convention)."""
    return ((jnp.asarray(x).astype(jnp.float32)
             - jnp.asarray(zp).astype(jnp.float32))
            * jnp.asarray(scale, jnp.float32))


def _q(val, y_scale, y_zp):
    """Affine quantize f32 -> the zero point's dtype, saturating."""
    out_dt = np.dtype(np.asarray(y_zp).dtype)
    info = np.iinfo(out_dt)
    q = (jnp.round(jnp.asarray(val) / jnp.asarray(y_scale, jnp.float32))
         + jnp.asarray(y_zp).astype(jnp.float32))
    return jnp.clip(q, info.min, info.max).astype(out_dt)


# com.microsoft QOperator contrib family — what onnxruntime's static
# quantizer (quant_format=QOperator) emits between the QLinearConv/
# QLinearMatMul nodes. Dispatch is by op_type (domains carry no
# semantics here); compute is dequant -> f32 op -> requant, which
# matches ORT's lookup-table kernels to <=1 LSB.
def _qlinear_binary(fn):
    def impl(ctx, a, a_scale, a_zp, b, b_scale, b_zp, c_scale, c_zp):
        return _q(fn(_dq(a, a_scale, a_zp), _dq(b, b_scale, b_zp)),
                  c_scale, c_zp)
    return impl


_REGISTRY["QLinearAdd"] = _qlinear_binary(jnp.add)
_REGISTRY["QLinearMul"] = _qlinear_binary(jnp.multiply)


@op("QLinearSigmoid")
def _qlinear_sigmoid(ctx, x, x_scale, x_zp, y_scale, y_zp):
    return _q(jax.nn.sigmoid(_dq(x, x_scale, x_zp)), y_scale, y_zp)


@op("QLinearLeakyRelu")
def _qlinear_leaky_relu(ctx, x, x_scale, x_zp, y_scale, y_zp):
    alpha = ctx.attr("alpha", 0.01)
    v = _dq(x, x_scale, x_zp)
    return _q(jnp.where(v >= 0, v, alpha * v), y_scale, y_zp)


@op("QLinearGlobalAveragePool")
def _qlinear_global_avg_pool(ctx, x, x_scale, x_zp, y_scale, y_zp):
    axes = (tuple(range(1, jnp.ndim(x) - 1))
            if ctx.attr("channels_last", 0)
            else tuple(range(2, jnp.ndim(x))))
    # mean over the int values first (exact in f32 for int8 sums of
    # typical spatial extents), then one affine rescale
    m = jnp.mean(jnp.asarray(x).astype(jnp.float32), axis=axes,
                 keepdims=True)
    return _q((m - jnp.asarray(x_zp, jnp.float32))
              * jnp.asarray(x_scale, jnp.float32), y_scale, y_zp)


@op("QLinearConcat")
def _qlinear_concat(ctx, y_scale, y_zp, *parts):
    axis = ctx.attr("axis")
    if axis is None:
        raise ValueError("QLinearConcat needs an axis attribute")
    if len(parts) % 3:
        raise ValueError("QLinearConcat inputs must be (X, scale, zp) "
                         "triplets after (Y_scale, Y_zp)")
    deq = [_dq(parts[i], parts[i + 1], parts[i + 2])
           for i in range(0, len(parts), 3)]
    return _q(jnp.concatenate(deq, axis=int(axis)), y_scale, y_zp)


@op("QGemm")
def _qgemm(ctx, a, a_scale, a_zp, b, b_scale, b_zp, c=None, y_scale=None,
           y_zp=None):
    """com.microsoft QGemm: integer gemm with optional int32 bias;
    float output when y_scale is absent, requantized otherwise."""
    alpha = ctx.attr("alpha", 1.0)
    a32 = jnp.asarray(a).astype(jnp.int32)
    b32 = jnp.asarray(b).astype(jnp.int32)
    if ctx.attr("transA", 0):
        a32 = a32.T
    if ctx.attr("transB", 0):
        b32 = b32.T
    a32 = a32 - jnp.asarray(a_zp).astype(jnp.int32)
    bz = jnp.asarray(b_zp).astype(jnp.int32)
    b32 = b32 - (bz if bz.ndim == 0 else bz[None, :])
    acc = jax.lax.dot_general(
        a32, b32, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    if c is not None:
        acc = acc + jnp.asarray(c).astype(jnp.int32)
    combined = (alpha * jnp.asarray(a_scale, jnp.float32)
                * jnp.asarray(b_scale, jnp.float32))
    if y_scale is None:
        return acc.astype(jnp.float32) * combined
    return _requantize(acc, combined / jnp.asarray(y_scale, jnp.float32),
                       y_zp)


@op("Clip")
def _clip(ctx, x, lo=None, hi=None):
    if ctx.opset < 11:
        lo = ctx.attr("min", -np.inf)
        hi = ctx.attr("max", np.inf)
    lo = -np.inf if lo is None else lo
    hi = np.inf if hi is None else hi
    return jnp.clip(x, lo, hi)


@op("Min")
def _min(ctx, *xs):
    xp = np if _all_host(xs) else jnp  # shape chains clamp via Min/Max
    out = xs[0]
    for x in xs[1:]:
        out = xp.minimum(out, x)
    return out


@op("Max")
def _max(ctx, *xs):
    xp = np if _all_host(xs) else jnp
    out = xs[0]
    for x in xs[1:]:
        out = xp.maximum(out, x)
    return out


@op("Sum")
def _sum(ctx, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@op("Mean")
def _mean(ctx, *xs):
    return _sum(ctx, *xs) / float(len(xs))


@op("Where")
def _where(ctx, cond, a, b):
    xp = np if _all_host((cond, a, b)) else jnp
    return xp.where(cond, a, b)


@op("Trilu")
def _trilu(ctx, x, k=None):
    """Upper/lower triangle (causal-mask construction in transformer
    graphs)."""
    kk = int(np.asarray(k).reshape(())) if k is not None else 0
    xp = np if _all_host((x,)) else jnp
    if ctx.attr("upper", 1):
        return xp.triu(x, kk)
    return xp.tril(x, kk)


@op("Mod")
def _mod(ctx, a, b):
    # host-preserving: exporters route SHAPE arithmetic through Mod
    # (torch MultiheadAttention's head-split checks); a device result
    # here would poison downstream Reshape/Slice static params
    xp = np if _all_host((a, b)) else jnp
    if ctx.attr("fmod", 0):
        return xp.fmod(a, b)
    return xp.mod(a, b)


# ---------------------------------------------------------------------------
# Linear algebra
# ---------------------------------------------------------------------------

@op("MatMul")
def _matmul(ctx, a, b):
    return jnp.matmul(a, b)


@op("Gemm")
def _gemm(ctx, a, b, c=None):
    alpha, beta = ctx.attr("alpha", 1.0), ctx.attr("beta", 1.0)
    if ctx.attr("transA", 0):
        a = a.T
    if ctx.attr("transB", 0):
        b = b.T
    y = jnp.matmul(a, b)
    dt = y.dtype
    # dtype-pinned scalars: a bare python float would make numpy promote
    # host-side bf16 weights to f32 and poison the whole tail of the graph
    if alpha != 1.0:
        y = y * np.asarray(alpha, dtype=dt)
    if c is not None:
        cc = np.asarray(c, dtype=dt) if isinstance(c, np.ndarray) else c.astype(dt)
        y = y + (np.asarray(beta, dtype=dt) * cc if beta != 1.0 else cc)
    return y


@op("Einsum")
def _einsum(ctx, *xs):
    return jnp.einsum(ctx.attr("equation"), *xs)


# ---------------------------------------------------------------------------
# Convolution & pooling
# ---------------------------------------------------------------------------

def _conv_dims(rank: int):
    # ONNX tensors are N,C,spatial... ; weights O,I,spatial...
    sp = "DHW"[3 - rank:]
    return lax.conv_dimension_numbers(
        (1,) * (rank + 2), (1,) * (rank + 2),
        (f"NC{sp}", f"OI{sp}", f"NC{sp}"))


def _resolve_pads(ctx, x_sp: Sequence[int], kernel: Sequence[int],
                  strides: Sequence[int], dilations: Sequence[int],
                  ceil_mode: int = 0) -> List[Tuple[int, int]]:
    rank = len(kernel)
    auto = ctx.attr("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        out: List[Tuple[int, int]] = []
        for i in range(rank):
            o = math.ceil(x_sp[i] / strides[i])
            eff_k = (kernel[i] - 1) * dilations[i] + 1
            total = max(0, (o - 1) * strides[i] + eff_k - x_sp[i])
            if auto == "SAME_UPPER":
                out.append((total // 2, total - total // 2))
            else:
                out.append((total - total // 2, total // 2))
        return out
    pads = ctx.attr("pads", [0] * (2 * rank))
    out = [(int(pads[i]), int(pads[i + rank])) for i in range(rank)]
    if ceil_mode:
        # grow the high-side pad so the final (ceil'd) window fits
        for i in range(rank):
            eff_k = (kernel[i] - 1) * dilations[i] + 1
            padded = x_sp[i] + out[i][0] + out[i][1]
            o = math.ceil((padded - eff_k) / strides[i]) + 1
            need = (o - 1) * strides[i] + eff_k
            if need > padded:
                out[i] = (out[i][0], out[i][1] + need - padded)
    return out


@op("Conv")
def _conv(ctx, x, w, b=None):
    rank = x.ndim - 2
    strides = ctx.attr("strides", [1] * rank)
    dilations = ctx.attr("dilations", [1] * rank)
    group = ctx.attr("group", 1)
    kernel = ctx.attr("kernel_shape", list(w.shape[2:]))
    pads = _resolve_pads(ctx, x.shape[2:], kernel, strides, dilations)
    y = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, feature_group_count=group,
        dimension_numbers=_conv_dims(rank))
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * rank)
    return y


@op("ConvTranspose")
def _conv_transpose(ctx, x, w, b=None):
    rank = x.ndim - 2
    strides = ctx.attr("strides", [1] * rank)
    dilations = ctx.attr("dilations", [1] * rank)
    group = ctx.attr("group", 1)
    kernel = ctx.attr("kernel_shape", list(w.shape[2:]))
    out_pad = ctx.attr("output_padding", [0] * rank)
    pads = ctx.attr("pads", None)
    if pads is None:
        auto = ctx.attr("auto_pad", "NOTSET")
        if auto in ("SAME_UPPER", "SAME_LOWER"):
            pads_pairs = []
            for i in range(rank):
                eff_k = (kernel[i] - 1) * dilations[i] + 1
                total = max(0, eff_k - strides[i])
                lo = total // 2 if auto == "SAME_UPPER" else total - total // 2
                pads_pairs.append((lo, total - lo))
        else:
            pads_pairs = [(0, 0)] * rank
    else:
        pads_pairs = [(int(pads[i]), int(pads[i + rank])) for i in range(rank)]
    # ONNX ConvTranspose: lhs-dilate x by stride, then conv with flipped kernel.
    eff = [(kernel[i] - 1) * dilations[i] + 1 for i in range(rank)]
    conv_pads = [
        (eff[i] - 1 - pads_pairs[i][0], eff[i] - 1 - pads_pairs[i][1] + out_pad[i])
        for i in range(rank)
    ]
    # weights are (I, O/g, spatial): flip spatial, swap to (O, I/g, spatial)
    w_flip = jnp.flip(w, axis=tuple(range(2, w.ndim)))
    if group == 1:
        w_t = jnp.swapaxes(w_flip, 0, 1)
    else:
        i_per_g = w.shape[0] // group
        w_g = w_flip.reshape((group, i_per_g) + w_flip.shape[1:])
        w_t = jnp.swapaxes(w_g, 1, 2).reshape(
            (group * w_flip.shape[1], i_per_g) + w_flip.shape[2:])
    y = lax.conv_general_dilated(
        x, w_t, window_strides=[1] * rank, padding=conv_pads,
        lhs_dilation=strides, rhs_dilation=dilations,
        feature_group_count=group, dimension_numbers=_conv_dims(rank))
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * rank)
    return y


@op("DeformConv")
def _deform_conv(ctx, x, w, offset, b=None, mask=None):
    """DeformConv (opset 19, torchvision deform_conv2d semantics):
    per-output-pixel learned sampling offsets, bilinear interpolation
    with zero padding, optional modulation mask (v2). Lowered as one
    batched 4-corner gather over [N, C, kH*kW, oH*oW] plus a grouped
    einsum — all static shapes, MXU-contractable."""
    if x.ndim != 4:
        raise NotImplementedError("DeformConv supports 2-D (NCHW) only")
    x = jnp.asarray(x, jnp.float32)
    w = jnp.asarray(w, jnp.float32)
    offset = jnp.asarray(offset, jnp.float32)
    n, c, h, wd = x.shape
    oc, cg_w, kh, kw = w.shape
    strides = [int(v) for v in ctx.attr("strides", [1, 1])]
    dil = [int(v) for v in ctx.attr("dilations", [1, 1])]
    pads = [int(v) for v in ctx.attr("pads", [0, 0, 0, 0])]
    group = int(ctx.attr("group", 1))
    og = int(ctx.attr("offset_group", 1))
    oh, ow = offset.shape[2], offset.shape[3]
    k = kh * kw
    p = oh * ow
    cg = c // og

    # base sampling grid [k, p] then + offsets -> [N, og, k, p]
    ker_y = (np.arange(kh)[:, None] * dil[0]).repeat(kw, 1).reshape(-1)
    ker_x = np.tile(np.arange(kw) * dil[1], kh)
    byx = np.stack([  # [2, k, p]
        ker_y[:, None] + (np.arange(oh) * strides[0]
                          - pads[0]).repeat(ow)[None, :],
        ker_x[:, None] + np.tile(np.arange(ow) * strides[1]
                                 - pads[1], oh)[None, :]])
    off = offset.reshape(n, og, k, 2, p)  # [..., (dy, dx), ...]
    py = byx[0][None, None] + off[:, :, :, 0]          # [N, og, k, p]
    px = byx[1][None, None] + off[:, :, :, 1]

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    fy, fx = py - y0, px - x0
    x_r = x.reshape(n, og, cg, h * wd)

    def corner(yy, xx):
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0)
                 & (xx <= wd - 1))
        idx = (jnp.clip(yy, 0, h - 1).astype(jnp.int32) * wd
               + jnp.clip(xx, 0, wd - 1).astype(jnp.int32))
        g = jnp.take_along_axis(
            x_r, idx.reshape(n, og, 1, k * p), axis=3
        ).reshape(n, og, cg, k, p)
        return g * valid[:, :, None].astype(x.dtype).reshape(
            n, og, 1, k, p)

    samp = (corner(y0, x0) * ((1 - fy) * (1 - fx))[:, :, None]
            + corner(y0, x0 + 1) * ((1 - fy) * fx)[:, :, None]
            + corner(y0 + 1, x0) * (fy * (1 - fx))[:, :, None]
            + corner(y0 + 1, x0 + 1) * (fy * fx)[:, :, None])
    if mask is not None:
        samp = samp * jnp.asarray(mask, jnp.float32).reshape(
            n, og, 1, k, p)
    # grouped contraction: [N, g, C/g, k, p] x [g, oC/g, C/g, k]
    samp = samp.reshape(n, group, c // group, k, p)
    w_g = w.reshape(group, oc // group, cg_w, k)
    out = jnp.einsum("ngckp,gock->ngop", samp, w_g,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, oc, oh, ow)
    if b is not None:
        out = out + jnp.asarray(b, jnp.float32)[None, :, None, None]
    return out


@op("ImageDecoder")
def _image_decoder(ctx, encoded):
    """ImageDecoder (opset 20): host-side decode of an encoded image
    byte stream to [H, W, C] uint8 via PIL. Decoding is inherently host
    work — a traced byte tensor is rejected loudly. (The column-level
    image path lives in synapseml_tpu.image.reader; this op covers
    in-graph decode nodes, whose pixel_format/channel contract differs
    from the reader's BGR column layout.)"""
    if not _is_host(encoded):
        raise NotImplementedError(
            "ImageDecoder needs host bytes: image decoding cannot run "
            "under jit — decode ahead of the graph or feed host values")
    import io as _io

    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL baked into image
        raise NotImplementedError(
            "ImageDecoder requires PIL for this codec") from e
    data = np.asarray(encoded, np.uint8).tobytes()
    img = Image.open(_io.BytesIO(data))
    fmt = str(ctx.attr("pixel_format", "RGB"))
    if fmt == "Grayscale":
        return np.asarray(img.convert("L"), np.uint8)[:, :, None]
    rgb = np.asarray(img.convert("RGB"), np.uint8)
    return rgb[:, :, ::-1] if fmt == "BGR" else rgb


@op("MaxPool")
def _max_pool(ctx, x):
    rank = x.ndim - 2
    kernel = ctx.attr("kernel_shape")
    strides = ctx.attr("strides", [1] * rank)
    dilations = ctx.attr("dilations", [1] * rank)
    pads = _resolve_pads(ctx, x.shape[2:], kernel, strides, dilations,
                         ctx.attr("ceil_mode", 0))
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    if ctx.n_outputs > 1:
        if int(ctx.attr("storage_order", 0)):
            raise NotImplementedError(
                "MaxPool storage_order=1 (column-major Indices) is not "
                "supported; re-export with row-major indices")
        return _max_pool_with_indices(x, kernel, strides, dilations,
                                      pads, init)
    return lax.reduce_window(
        x, init, lax.max,
        window_dimensions=(1, 1) + tuple(kernel),
        window_strides=(1, 1) + tuple(strides),
        window_dilation=(1, 1) + tuple(dilations),
        padding=((0, 0), (0, 0)) + tuple(pads))


def _max_pool_with_indices(x, kernel, strides, dilations, pads, init):
    """MaxPool's optional Indices output (the SegNet/DeconvNet pattern,
    paired with MaxUnpool): windows are gathered as one patch tensor with
    compile-time index grids, argmax picks the row-major-first winner
    (onnxruntime's tie-break), and indices are flattened over the WHOLE
    [N, C, spatial] input per spec."""
    rank = len(kernel)
    sp = x.shape[2:]
    n, c = x.shape[0], x.shape[1]
    padded = jnp.pad(jnp.asarray(x), ((0, 0), (0, 0)) + tuple(pads),
                     constant_values=init)
    out_sp = [(sp[d] + pads[d][0] + pads[d][1]
               - (kernel[d] - 1) * dilations[d] - 1) // strides[d] + 1
              for d in range(rank)]
    grids = []
    for d in range(rank):  # G[o, k] = o*stride + k*dilation, into padded
        g = (np.arange(out_sp[d])[:, None] * strides[d]
             + np.arange(kernel[d])[None, :] * dilations[d])
        shape = [1] * (2 * rank)
        shape[d], shape[rank + d] = out_sp[d], kernel[d]
        grids.append(g.reshape(shape))
    patches = padded[(slice(None), slice(None)) + tuple(grids)]
    flat = patches.reshape(patches.shape[:2 + rank] + (-1,))
    vals = jnp.max(flat, axis=-1)
    amax = jnp.argmax(flat, axis=-1)
    coords = []  # unravel the window argmax into original-tensor coords
    in_bounds = None
    rem = amax
    for d in reversed(range(rank)):
        kd = rem % kernel[d]
        rem = rem // kernel[d]
        shape = [1] * (2 + rank)
        shape[2 + d] = out_sp[d]
        o_d = jnp.asarray(np.arange(out_sp[d]).reshape(shape))
        raw = o_d * strides[d] + kd * dilations[d] - pads[d][0]
        # a window that falls ENTIRELY inside the padding has its argmax
        # on a padded cell, whose recovered coordinate lands outside
        # [0, sp[d]-1]: unguarded, the negative flat index WRAPS under
        # MaxUnpool's scatter and corrupts the tensor tail. Track
        # in-bounds-ness and clamp the coordinate so the flat index
        # stays well-formed either way
        ok_d = (raw >= 0) & (raw < sp[d])
        in_bounds = ok_d if in_bounds is None else (in_bounds & ok_d)
        coords.insert(0, jnp.clip(raw, 0, sp[d] - 1))
    flat_sp = coords[0]
    for d in range(1, rank):
        flat_sp = flat_sp * sp[d] + coords[d]
    n_idx = jnp.arange(n).reshape((n,) + (1,) * (1 + rank))
    c_idx = jnp.arange(c).reshape((1, c) + (1,) * rank)
    gidx = (n_idx * c + c_idx) * int(np.prod(sp)) + flat_sp
    # degenerate (all-padding) windows take the dtype-max sentinel:
    # non-negative (no wraparound) and out of range for ANY unpool
    # output — including a spec-sanctioned output_shape LARGER than the
    # pool input, which an input-sized sentinel would land inside — so
    # MaxUnpool's .at[].set() drops the update instead of colliding
    # with a real cell
    gidx = gidx.astype(jnp.int64)
    gidx = jnp.where(in_bounds, gidx, jnp.iinfo(gidx.dtype).max)
    return vals, gidx


@op("MaxUnpool")
def _max_unpool(ctx, x, idx, output_shape=None):
    """MaxUnpool: scatter pooled values back to the positions recorded by
    MaxPool's Indices output (global row-major flat indices per spec), the
    SegNet decoder op. Output geometry from the explicit output_shape
    input when present, else inverted from kernel/stride/pads."""
    kernel = ctx.attr("kernel_shape")
    rank = len(kernel)
    strides = ctx.attr("strides", [1] * rank)
    pads = [int(p) for p in ctx.attr("pads", [0] * (2 * rank))]
    if output_shape is not None:
        out_shape = tuple(_static_int_list(
            output_shape, "MaxUnpool output_shape"))
    else:
        sp = x.shape[2:]
        out_shape = tuple(x.shape[:2]) + tuple(
            (sp[d] - 1) * strides[d] + kernel[d] - pads[d] - pads[rank + d]
            for d in range(rank))
    total = int(np.prod(out_shape))
    out = jnp.zeros(total, jnp.asarray(x).dtype)
    out = out.at[jnp.asarray(idx).reshape(-1)].set(
        jnp.asarray(x).reshape(-1))
    return out.reshape(out_shape)


@op("AveragePool")
def _avg_pool(ctx, x):
    rank = x.ndim - 2
    kernel = ctx.attr("kernel_shape")
    strides = ctx.attr("strides", [1] * rank)
    pads = _resolve_pads(ctx, x.shape[2:], kernel, strides, [1] * rank,
                         ctx.attr("ceil_mode", 0))
    dims = (1, 1) + tuple(kernel)
    strd = (1, 1) + tuple(strides)
    padc = ((0, 0), (0, 0)) + tuple(pads)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strd, padding=padc)
    if ctx.attr("count_include_pad", 0):
        return s / float(np.prod(kernel))
    ones = jnp.ones(x.shape[2:], x.dtype)[None, None]
    cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strd, padding=padc)
    return s / cnt


@op("GlobalAveragePool")
def _gap(ctx, x):
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("GlobalMaxPool")
def _gmp(ctx, x):
    return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("LpPool")
def _lp_pool(ctx, x):
    rank = x.ndim - 2
    p = ctx.attr("p", 2)
    kernel = ctx.attr("kernel_shape")
    strides = ctx.attr("strides", [1] * rank)
    dilations = ctx.attr("dilations", [1] * rank)  # opset 18+
    pads = _resolve_pads(ctx, x.shape[2:], kernel, strides, dilations,
                         ctx.attr("ceil_mode", 0))
    s = lax.reduce_window(
        jnp.abs(x) ** p, 0.0, lax.add,
        (1, 1) + tuple(kernel), (1, 1) + tuple(strides),
        window_dilation=(1, 1) + tuple(dilations),
        padding=((0, 0), (0, 0)) + tuple(pads))
    return s ** (1.0 / p)


@op("GlobalLpPool")
def _global_lp_pool(ctx, x):
    p = ctx.attr("p", 2)
    axes = tuple(range(2, x.ndim))
    return jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)


@op("LRN")
def _lrn(ctx, x):
    size = ctx.attr("size")
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    bias = ctx.attr("bias", 1.0)
    half_lo = (size - 1) // 2
    half_hi = size - 1 - half_lo
    sq = jnp.square(x)
    window = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, size) + (1,) * (x.ndim - 2),
        window_strides=(1,) * x.ndim,
        padding=((0, 0), (half_lo, half_hi)) + ((0, 0),) * (x.ndim - 2))
    return x / jnp.power(bias + (alpha / size) * window, beta)


@op("ScatterND")
def _scatter_nd(ctx, data, indices, updates):
    reduction = ctx.attr("reduction", "none")
    if _all_host((data, indices, updates)):
        # stay on host so integer results can still feed shape slots
        out = np.array(data)
        idx = tuple(np.moveaxis(np.asarray(indices), -1, 0))
        upd = np.asarray(updates)
        if reduction == "add":
            np.add.at(out, idx, upd)
        elif reduction in ("mul", "multiply"):
            np.multiply.at(out, idx, upd)
        elif reduction == "min":
            np.minimum.at(out, idx, upd)
        elif reduction == "max":
            np.maximum.at(out, idx, upd)
        else:
            out[idx] = upd
        return out
    ref = jnp.asarray(data).at[
        tuple(jnp.moveaxis(jnp.asarray(indices), -1, 0))]
    if reduction == "add":
        return ref.add(updates)
    if reduction in ("mul", "multiply"):
        return ref.multiply(updates)
    if reduction == "min":
        return ref.min(updates)
    if reduction == "max":
        return ref.max(updates)
    return ref.set(updates)


@op("GridSample")
def _grid_sample(ctx, x, grid):
    """Bilinear/nearest sampling on [N,C,H,W] with a [-1,1] grid
    (torch-exported spatial transformers)."""
    mode = ctx.attr("mode", "bilinear")
    padding = ctx.attr("padding_mode", "zeros")
    align = bool(ctx.attr("align_corners", 0))
    if mode not in ("bilinear", "linear", "nearest"):
        raise NotImplementedError(f"GridSample mode {mode!r}")
    if padding not in ("zeros", "border"):
        raise NotImplementedError(f"GridSample padding_mode {padding!r}")
    if np.ndim(x) != 4:
        raise NotImplementedError(
            "GridSample: only 4-D [N,C,H,W] input is supported "
            f"(got {np.ndim(x)}-D)")
    from jax.scipy.ndimage import map_coordinates

    x = jnp.asarray(x)
    grid = jnp.asarray(grid)
    n, c, h, w = x.shape

    def unnorm(g, size):
        if align:
            return (g + 1.0) * (size - 1) / 2.0
        return ((g + 1.0) * size - 1.0) / 2.0

    xs = unnorm(grid[..., 0], w)  # [N, Ho, Wo]
    ys = unnorm(grid[..., 1], h)
    order = 1 if mode in ("bilinear", "linear") else 0
    nd_mode = "constant" if padding == "zeros" else "nearest"

    def sample_img(img, ys_i, xs_i):     # img [C,H,W]
        return jax.vmap(lambda ch: map_coordinates(
            ch, [ys_i, xs_i], order=order, mode=nd_mode, cval=0.0))(img)

    return jax.vmap(sample_img)(x, ys, xs)


def _lower_nodes(nodes, opset: int):
    """Pre-extract (impl, ctx, inputs, outputs) per node — shared by
    ImportedGraph.__init__ and subgraph lowering, so apply()/If
    execution does no proto work per call."""
    lowered = []
    for node in nodes:
        impl = _REGISTRY.get(node.op_type)
        if impl is None:
            raise NotImplementedError(
                f"ONNX op {node.op_type!r} (node {node.name!r}) is not "
                f"supported by the importer; supported: "
                f"{sorted(_REGISTRY)}")
        # positional arity: through the last *used* output slot — ONNX
        # marks skipped optional outputs with "" placeholders
        arity = max((i + 1 for i, o in enumerate(node.output) if o),
                    default=0)
        # exporters commonly leave node.name empty; the first output name
        # is unique per graph (spec) and keeps per-node derivations (e.g.
        # random-op fallback seeds) distinct
        ctx = OpContext(node_attrs(node), opset,
                        node.name or (node.output[0] if node.output else ""),
                        node.op_type, arity)
        # control-flow subgraphs lower EAGERLY so an unsupported op inside
        # a branch is rejected at import time, not on live traffic
        if node.op_type == "If":
            ctx.attrs["__lowered__"] = (
                _Subgraph(ctx.attr("then_branch"), opset),
                _Subgraph(ctx.attr("else_branch"), opset))
        elif node.op_type in ("Loop", "Scan", "SequenceMap"):
            ctx.attrs["__lowered_body__"] = _Subgraph(ctx.attr("body"),
                                                      opset)
        lowered.append((impl, ctx, list(node.input), list(node.output)))
    return lowered


def _run_nodes(lowered, env: Dict[str, Any]):
    for impl, ctx, in_names, out_names in lowered:
        args = [env[n] if n else None for n in in_names]
        if getattr(impl, "_needs_env", False):
            # control-flow ops (If) run subgraphs that capture outer
            # names beyond their declared inputs
            out = impl(ctx, *args, env=env)
        else:
            out = impl(ctx, *args)
        if not isinstance(out, tuple):
            out = (out,)
        for name, val in zip(out_names, out):
            if name:  # "" marks a skipped optional output
                env[name] = val


class _Subgraph:
    """A branch/body GraphProto lowered once at import time."""

    def __init__(self, graph: Msg, opset: int):
        self.inits = {t.name: tensor_to_numpy(t) for t in graph.initializer}
        self.lowered = _lower_nodes(graph.node, opset)
        self.input_names = [vi.name for vi in graph.input]
        self.output_names = [vi.name for vi in graph.output]

    def captured_names(self) -> set:
        """Names read from the outer scope: node inputs not produced
        inside the subgraph (recursively through nested control flow)."""
        produced = set(self.input_names) | set(self.inits)
        captured = set()
        for impl, ctx, in_names, out_names in self.lowered:
            for nm in in_names:
                if nm and nm not in produced:
                    captured.add(nm)
            for sub in _subgraphs_of(ctx):
                captured |= sub.captured_names() - produced
            produced.update(n for n in out_names if n)
        return captured

    def run(self, env: Dict[str, Any]):
        sub_env = dict(env)
        sub_env.update(self.inits)
        _run_nodes(self.lowered, sub_env)
        return tuple(sub_env[n] for n in self.output_names)


def _subgraphs_of(ctx) -> List["_Subgraph"]:
    out = []
    lowered = ctx.attrs.get("__lowered__")
    if lowered:
        out.extend(lowered)
    body = ctx.attrs.get("__lowered_body__")
    if body is not None:
        out.append(body)
    return out


@op("If")
def _if(ctx, cond, env=None):
    """then/else subgraphs with outer capture. A host-side condition
    picks one branch at trace time (the common exported pattern:
    shape-derived flags); a traced condition runs both branches and
    selects elementwise, so their output shapes must match."""
    then_b, else_b = ctx.attrs["__lowered__"]  # lowered at import time
    env = env or {}
    if _is_host(cond):
        branch = then_b if bool(np.asarray(cond).reshape(())) else else_b
        out = branch.run(env)
    else:
        t_out = then_b.run(env)
        e_out = else_b.run(env)
        c = jnp.asarray(cond).reshape(())
        out = tuple(
            jnp.where(c, jnp.asarray(t), jnp.asarray(e))
            for t, e in zip(t_out, e_out))
    return out if len(out) != 1 else out[0]


_if._needs_env = True


@op("Loop")
def _loop(ctx, max_trip, cond, *v_initial, env=None):
    """ONNX Loop. Host-static trip counts / conditions (the exported
    for-range pattern) run as a host loop with full scan-output support.
    Traced (data-dependent) trip counts or termination conditions — the
    scripted-while pattern real exporters emit — lower to
    ``lax.while_loop`` with shape-invariant carries; scan outputs are
    unsupported there because their length would be data-dependent,
    which XLA's static-shape model cannot express."""
    body = ctx.attrs["__lowered_body__"]  # lowered at import time
    in_names = body.input_names
    if max_trip is None and cond is None:
        raise ValueError("Loop needs a trip count or a condition")
    n_carried = len(v_initial)
    n_scan = len(body.output_names) - 1 - n_carried
    traced_entry = (
        (max_trip is not None and not _is_host(max_trip))
        or (cond is not None and not _is_host(cond)))
    if traced_entry:
        return _loop_via_while(body, env, max_trip, cond, v_initial, n_scan)
    trips = int(np.asarray(max_trip).reshape(())) if max_trip is not None \
        else None
    keep_going = True if cond is None else bool(
        np.asarray(cond).reshape(()))

    carried = list(v_initial)
    scan_acc: List[List[Any]] = []
    i = 0
    while keep_going and (trips is None or i < trips):
        sub_env = dict(env or {})
        vals = [np.int64(i), np.bool_(True)] + carried
        for nm, v in zip(in_names, vals):
            sub_env[nm] = v
        outs = body.run(sub_env)
        cond_out, outs = outs[0], outs[1:]
        carried = list(outs[:n_carried])
        scans = outs[n_carried:]
        if not scan_acc:
            scan_acc = [[] for _ in scans]
        for acc, s in zip(scan_acc, scans):
            acc.append(s)
        if _is_host(cond_out):
            keep_going = bool(np.asarray(cond_out).reshape(()))
        else:
            # the body computes its own termination on device — restart
            # as a lax.while_loop (the body is functional, so the partial
            # host iteration above is discarded without side effects)
            return _loop_via_while(
                body, env, max_trip, cond, v_initial, n_scan)
        i += 1

    if i == 0 and n_scan > 0:
        # zero-trip loops still owe empty scan outputs; probe the body
        # once for their shapes (results discarded)
        sub_env = dict(env or {})
        vals = [np.int64(0), np.bool_(True)] + list(v_initial)
        for nm, v in zip(in_names, vals):
            sub_env[nm] = v
        probe = body.run(sub_env)[1 + n_carried:]
        stacked = [
            np.zeros((0,) + tuple(np.shape(p)),
                     dtype=np.asarray(p).dtype if _is_host(p) else p.dtype)
            for p in probe
        ]
    else:
        stacked = [
            (np.stack(a) if _all_host(a) else jnp.stack(
                [jnp.asarray(v) for v in a]))
            for a in scan_acc
        ]
    out = tuple(carried) + tuple(stacked)
    return out if len(out) != 1 else out[0]


_loop._needs_env = True


def _loop_via_while(body, env, max_trip, cond, v_initial, n_scan: int):
    """Data-dependent Loop as ``lax.while_loop``: continue while
    ``i < M  AND  cond`` with carry ``(i, cond, *carried)``. Carried
    values must keep shape and dtype across iterations (the ONNX spec
    allows shape changes; XLA does not — the jax error surfaces that).
    Parity target: the reference executes these natively via
    onnxruntime (deep-learning/.../onnx/ONNXModel.scala:173-193)."""
    if n_scan > 0:
        raise NotImplementedError(
            "Loop: scan outputs with a data-dependent trip count have a "
            "data-dependent shape, which XLA cannot express; restructure "
            "the model to a static trip count or carried accumulators")
    outer = dict(env or {})
    in_names = body.input_names
    if max_trip is not None and _is_host(max_trip) \
            and int(np.asarray(max_trip).reshape(())) >= 2**31 - 1:
        # torch exports unbounded `while cond:` as M = INT64_MAX; with
        # x64 disabled jnp would canonicalize that to int32 -1 and the
        # loop would silently run ZERO iterations — treat as unbounded
        max_trip = None
    trips = None if max_trip is None else jnp.asarray(max_trip).reshape(())
    if trips is not None and trips.dtype == jnp.int32:
        # with x64 disabled, a *traced* INT64_MAX trip count was already
        # canonicalized to int32 upstream, overflowing to -1; the spec
        # forbids negative trip counts, so negative means "unbounded".
        # (Under x64 the dtype stays int64 and no reinterpretation is
        # needed — INT64_MAX is unbounded in practice.)
        trips = jnp.where(trips < 0, jnp.iinfo(jnp.int32).max, trips)
    cond0 = jnp.asarray(True) if cond is None \
        else jnp.asarray(cond).reshape(()).astype(bool)
    carried0 = tuple(jnp.asarray(v) for v in v_initial)

    def pred_fn(carry):
        i, keep = carry[0], carry[1]
        return keep if trips is None else jnp.logical_and(i < trips, keep)

    def body_fn(carry):
        i, keep, carried = carry[0], carry[1], carry[2:]
        sub_env = dict(outer)
        vals = [i, keep] + list(carried)
        for nm, v in zip(in_names, vals):
            sub_env[nm] = v
        outs = body.run(sub_env)
        cond_out = jnp.asarray(outs[0]).reshape(()).astype(bool)
        new_carried = tuple(
            jnp.asarray(o).astype(c.dtype)
            for o, c in zip(outs[1:], carried))
        return (i + 1, cond_out) + new_carried

    init = (jnp.asarray(np.int64(0)), cond0) + carried0
    final = jax.lax.while_loop(pred_fn, body_fn, init)
    out = final[2:]
    return out if len(out) != 1 else out[0]


@op("Scan")
def _scan(ctx, *inputs, env=None):
    """Scan: per-iteration slices of the scan inputs drive the body
    (the pre-Loop RNN export pattern, opset 9+ layout — no
    sequence_lens). State variables carry across iterations; scan
    outputs stack on axis 0. Non-zero scan axes and reverse directions
    are supported; the sequence length is a static shape, so the host
    loop unrolls under jit exactly like the LSTM lowering."""
    if ctx.opset < 9:
        raise NotImplementedError(
            "Scan: the opset-8 layout (sequence_lens input, batch axis) "
            "is not supported; re-export at opset >= 9")
    body = ctx.attrs["__lowered_body__"]  # lowered at import time
    m = int(ctx.attr("num_scan_inputs"))
    n_state = len(inputs) - m
    state = list(inputs[:n_state])
    scans = list(inputs[n_state:])
    in_axes = list(ctx.attr("scan_input_axes", [0] * m))
    in_dirs = list(ctx.attr("scan_input_directions", [0] * m))
    n_scan_out = len(body.output_names) - n_state
    out_axes = list(ctx.attr("scan_output_axes", [0] * n_scan_out))
    out_dirs = list(ctx.attr("scan_output_directions", [0] * n_scan_out))

    xp0 = np if _all_host(scans) else jnp
    scans = [xp0.moveaxis(xp0.asarray(s), in_axes[j] % np.ndim(s), 0)
             for j, s in enumerate(scans)]
    length = int(scans[0].shape[0]) if scans else 0

    # long sequences compile as ONE lax.scan body instead of `length`
    # unrolled copies (compile time would grow linearly otherwise); short
    # ones unroll, which also tolerates bodies with host-static needs
    if length > 16:
        try:
            return _scan_via_lax(body, env, state, scans, in_dirs,
                                 out_dirs, out_axes, n_state, n_scan_out)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerBoolConversionError,
                jax.errors.TracerIntegerConversionError,
                NotImplementedError, ValueError) as e:
            # ConcretizationTypeError/Tracer*Error: int()/bool()/asarray on
            # a tracer; ValueError: _static_int_list's "must be statically
            # known"; NotImplementedError: ops that only do host execution.
            # only host-static demands from the body justify trading the
            # single compiled lax.scan body for `length` unrolled copies;
            # genuine op bugs must surface, not silently unroll
            warnings.warn(
                f"Scan: body needs host-static values ({type(e).__name__}); "
                f"falling back to unrolled execution over {length} steps",
                RuntimeWarning, stacklevel=2)

    acc: List[List[Any]] = [[] for _ in range(n_scan_out)]
    for i in range(length):
        sub_env = dict(env or {})
        vals = list(state) + [
            s[length - 1 - i] if in_dirs[j] else s[i]
            for j, s in enumerate(scans)
        ]
        for nm, v in zip(body.input_names, vals):
            sub_env[nm] = v
        outs = body.run(sub_env)
        state = list(outs[:n_state])
        for a, s in zip(acc, outs[n_state:]):
            a.append(s)
    stacked = []
    for j, a in enumerate(acc):
        if out_dirs[j]:
            a = a[::-1]
        xp = np if _all_host(a) else jnp
        st = xp.stack([xp.asarray(v) for v in a])
        stacked.append(xp.moveaxis(st, 0, out_axes[j] % st.ndim))
    out = tuple(state) + tuple(stacked)
    return out if len(out) != 1 else out[0]


def _scan_via_lax(body, env, state, scans, in_dirs, out_dirs, out_axes,
                  n_state: int, n_scan_out: int):
    outer = dict(env or {})
    state0 = tuple(jnp.asarray(s) for s in state)
    xs = tuple(
        jnp.flip(jnp.asarray(s), 0) if in_dirs[j] else jnp.asarray(s)
        for j, s in enumerate(scans)
    )

    def body_fn(carry, slices):
        sub_env = dict(outer)
        vals = list(carry) + list(slices)
        for nm, v in zip(body.input_names, vals):
            sub_env[nm] = v
        outs = body.run(sub_env)
        new_state = tuple(jnp.asarray(o) for o in outs[:n_state])
        scan_outs = tuple(jnp.asarray(o) for o in outs[n_state:])
        return new_state, scan_outs

    final_state, stacked_raw = lax.scan(body_fn, state0, xs)
    stacked = []
    for j in range(n_scan_out):
        st = stacked_raw[j]
        if out_dirs[j]:
            st = jnp.flip(st, 0)
        stacked.append(jnp.moveaxis(st, 0, out_axes[j] % st.ndim))
    out = tuple(final_state) + tuple(stacked)
    return out if len(out) != 1 else out[0]


_scan._needs_env = True


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

@op("BatchNormalization")
def _batch_norm(ctx, x, scale, b, mean, var):
    eps = ctx.attr("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    # fold running stats into one multiply-add, computed in f32 then cast to
    # the activation dtype — keeps bf16 graphs bf16 (numpy would promote the
    # host-side `var + eps` to f32) and hands XLA a fuse-friendly affine op
    dt = x.dtype
    f32 = jnp.float32
    inv = lax.rsqrt(var.astype(f32) + eps)
    w = (inv * scale.astype(f32)).astype(dt)
    bias = (b.astype(f32) - mean.astype(f32) * inv * scale.astype(f32)).astype(dt)
    return x * w.reshape(shape) + bias.reshape(shape)


@op("InstanceNormalization")
def _instance_norm(ctx, x, scale, b):
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) + b.reshape(shape)


@op("LayerNormalization")
def _layer_norm(ctx, x, scale, b=None):
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps) * scale
    if b is not None:
        y = y + b
    if ctx.n_outputs > 1:
        return (y, mean, lax.rsqrt(var + eps))[: ctx.n_outputs]
    return y


@op("GroupNormalization")
def _group_norm(ctx, x, scale, b):
    eps = ctx.attr("epsilon", 1e-5)
    groups = int(ctx.attr("num_groups"))
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    # opset 18 ships PER-GROUP scale/bias [num_groups]; opset 21 changed
    # to per-channel [C] — distinguish by length and repeat groups out
    if scale.shape[0] == groups and groups != c:
        scale = jnp.repeat(scale, c // groups)
        b = jnp.repeat(b, c // groups)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return y * scale.reshape(shape) + b.reshape(shape)


@op("MeanVarianceNormalization")
def _mvn(ctx, x):
    axes = tuple(int(a) for a in ctx.attr("axes", [0, 2, 3]))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    return (x - mean) * lax.rsqrt(var + 1e-9)


@op("LpNormalization")
def _lp_normalization(ctx, x):
    axis = ctx.attr("axis", -1)
    p = ctx.attr("p", 2)
    if p == 1:
        norm = jnp.sum(jnp.abs(x), axis=axis, keepdims=True)
    else:
        norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return x / jnp.maximum(norm, 1e-12)


# ---------------------------------------------------------------------------
# Shape / structure ops (host-foldable where possible)
# ---------------------------------------------------------------------------

@op("Shape")
def _shape(ctx, x):
    start = ctx.attr("start", 0)
    end = ctx.attr("end", None)
    shp = list(np.shape(x))
    shp = shp[start:end] if end is not None else shp[start:]
    return np.asarray(shp, dtype=np.int64)


@op("Size")
def _size(ctx, x):
    return np.asarray(int(np.prod(np.shape(x))), dtype=np.int64)


@op("EyeLike")
def _eye_like(ctx, x):
    dt = proto.TENSOR_DTYPES.get(ctx.attr("dtype")) or \
        (np.asarray(x).dtype if _is_host(x) else x.dtype)
    k = ctx.attr("k", 0)
    n, m = np.shape(x)
    return np.eye(n, m, k=k, dtype=dt)  # shape-static: host constant


@op("ReverseSequence")
def _reverse_sequence(ctx, x, seq_lens):
    batch_axis = ctx.attr("batch_axis", 1)
    time_axis = ctx.attr("time_axis", 0)
    xj = jnp.asarray(x)
    t = xj.shape[time_axis]
    idx = jnp.arange(t)
    lens = jnp.asarray(seq_lens).astype(jnp.int32)

    def rev_one(row_len):
        # positions < row_len reverse; the rest stay in place
        return jnp.where(idx < row_len, row_len - 1 - idx, idx)

    gather_idx = jax.vmap(rev_one)(lens)          # [B, T]
    moved = jnp.moveaxis(xj, (batch_axis, time_axis), (0, 1))
    out = jnp.take_along_axis(
        moved, gather_idx.reshape(gather_idx.shape + (1,) * (moved.ndim - 2)),
        axis=1)
    return jnp.moveaxis(out, (0, 1), (batch_axis, time_axis))


@op("NonZero")
def _non_zero(ctx, x):
    if not _is_host(x):
        raise NotImplementedError(
            "NonZero on traced tensors has a data-dependent output shape, "
            "which XLA cannot express; restructure with Where/masking")
    return np.stack(np.nonzero(np.asarray(x))).astype(np.int64)


@op("Reshape")
def _reshape(ctx, x, shape=None):
    target = _static_int_list(shape if shape is not None else ctx.attr("shape"),
                              "Reshape shape")
    allowzero = ctx.attr("allowzero", 0)
    cur = list(np.shape(x))
    out = []
    for i, d in enumerate(target):
        if d == 0 and not allowzero:
            out.append(cur[i])
        else:
            out.append(d)
    xp = np if _is_host(x) else jnp
    return xp.reshape(x, out)


@op("Flatten")
def _flatten(ctx, x):
    axis = ctx.attr("axis", 1) % (x.ndim + 1)
    lead = int(np.prod(np.shape(x)[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


@op("Transpose")
def _transpose(ctx, x):
    perm = ctx.attr("perm", list(range(x.ndim))[::-1])
    xp = np if _is_host(x) else jnp
    return xp.transpose(x, perm)


@op("Squeeze")
def _squeeze(ctx, x, axes=None):
    if ctx.opset < 13:
        axes = ctx.attr("axes", None)
    ax = _static_int_list(axes, "Squeeze axes") if axes is not None else None
    xp = np if _is_host(x) else jnp
    if not ax:
        return xp.squeeze(x)
    return xp.squeeze(x, axis=tuple(a % x.ndim for a in ax))


@op("Unsqueeze")
def _unsqueeze(ctx, x, axes=None):
    if ctx.opset < 13:
        axes = ctx.attr("axes")
    ax = _static_int_list(axes, "Unsqueeze axes")
    out_rank = np.ndim(x) + len(ax)
    ax = sorted(a % out_rank for a in ax)
    xp = np if _is_host(x) else jnp
    for a in ax:
        x = xp.expand_dims(x, a)
    return x


@op("Concat")
def _concat(ctx, *xs):
    axis = ctx.attr("axis")
    xp = np if _all_host(xs) else jnp
    return xp.concatenate([xp.asarray(x) for x in xs], axis=axis)


@op("Split")
def _split(ctx, x, split=None):
    axis = ctx.attr("axis", 0)
    if ctx.opset < 13:
        split = ctx.attr("split", None)
    n_out = ctx.n_outputs
    dim = np.shape(x)[axis]
    if split is None:
        sizes = [dim // n_out + (1 if i < dim % n_out else 0) for i in range(n_out)]
    else:
        sizes = _static_int_list(split, "Split sizes")
    offs = np.cumsum([0] + sizes)
    xp = np if _is_host(x) else jnp
    outs = tuple(
        lax.slice_in_dim(x, int(offs[i]), int(offs[i + 1]), axis=axis)
        if xp is jnp else np.take(x, range(offs[i], offs[i + 1]), axis=axis)
        for i in range(len(sizes)))
    return outs


@op("Slice")
def _slice(ctx, x, starts=None, ends=None, axes=None, steps=None):
    if ctx.opset < 10:
        starts, ends = ctx.attr("starts"), ctx.attr("ends")
        axes = ctx.attr("axes", None)
    starts = _static_int_list(starts, "Slice starts")
    ends = _static_int_list(ends, "Slice ends")
    axes = (_static_int_list(axes, "Slice axes") if axes is not None
            else list(range(len(starts))))
    steps = _static_int_list(steps, "Slice steps") if steps is not None else [1] * len(starts)
    slices = [slice(None)] * np.ndim(x)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        d = np.shape(x)[ax]
        INT_MAX = 2**62
        st = max(st + d, 0) if st < 0 else min(st, d)
        if en < -INT_MAX:
            en = None if sp < 0 else 0
        elif en < 0:
            en = max(en + d, -1)
            en = None if (sp < 0 and en < 0) else en
        else:
            en = min(en, d)
        slices[ax % np.ndim(x)] = slice(st, en, sp)
    return x[tuple(slices)]


@op("Gather")
def _gather(ctx, x, idx):
    axis = ctx.attr("axis", 0)
    xp = np if _all_host((x, idx)) else jnp
    return xp.take(x, np.asarray(idx, dtype=np.int64) if xp is np else idx, axis=axis)


@op("GatherElements")
def _gather_elements(ctx, x, idx):
    axis = ctx.attr("axis", 0)
    return jnp.take_along_axis(jnp.asarray(x), jnp.asarray(idx), axis=axis)


@op("GatherND")
def _gather_nd(ctx, x, idx):
    batch_dims = int(ctx.attr("batch_dims", 0))
    x = jnp.asarray(x)
    idx = jnp.asarray(idx)

    def core(xx, ii):
        k = ii.shape[-1]
        flat = ii.reshape(-1, k)
        out = xx[tuple(flat[:, i] for i in range(k))]
        return out.reshape(ii.shape[:-1] + xx.shape[k:])

    fn = core
    for _ in range(batch_dims):  # leading dims batch (detection heads'
        fn = jax.vmap(fn)        # post-NMS gathers use batch_dims=1)
    return fn(x, idx)


@op("ScatterElements")
def _scatter_elements(ctx, x, idx, updates):
    axis = ctx.attr("axis", 0)
    reduction = ctx.attr("reduction", "none")
    x, idx, updates = jnp.asarray(x), jnp.asarray(idx), jnp.asarray(updates)
    dims = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    index = tuple(idx if d == axis else dims[d] for d in range(x.ndim))
    at = x.at[index]
    if reduction == "add":
        return at.add(updates)
    if reduction == "mul":
        return at.multiply(updates)
    return at.set(updates)


# deprecated opset-9 name for the same op (no reduction attr back then)
_REGISTRY["Scatter"] = _scatter_elements


@op("Expand")
def _expand(ctx, x, shape):
    # bidirectional numpy broadcast: align ranks from the right, then each
    # result dim is max(cur, target) with 1s broadcasting either way
    target = _static_int_list(shape, "Expand shape")
    cur = list(np.shape(x))
    rank = max(len(cur), len(target))
    cur = [1] * (rank - len(cur)) + cur
    target = [1] * (rank - len(target)) + target
    out = []
    for c, t in zip(cur, target):
        if c != t and 1 not in (c, t):
            raise ValueError(f"Expand: incompatible dims {c} vs {t}")
        out.append(max(c, t))
    xp = np if _is_host(x) else jnp
    return xp.broadcast_to(xp.reshape(x, cur), out)


@op("Tile")
def _tile(ctx, x, repeats):
    reps = _static_int_list(repeats, "Tile repeats")
    xp = np if _is_host(x) else jnp
    return xp.tile(x, reps)


@op("Pad")
def _pad(ctx, x, pads=None, value=None, axes=None):
    mode = ctx.attr("mode", "constant")
    if ctx.opset < 11:
        pads = ctx.attr("pads")
        value = ctx.attr("value", 0.0)
    plist = _static_int_list(pads, "Pad pads")
    if axes is not None:
        ax = _static_int_list(axes, "Pad axes")
    else:
        ax = list(range(x.ndim))
    half = len(plist) // 2
    width = [(0, 0)] * x.ndim
    for i, a in enumerate(ax):
        width[a % x.ndim] = (plist[i], plist[half + i])
    if mode == "constant":
        cv = 0.0 if value is None else (float(np.asarray(value).reshape(()))
                                        if np.asarray(value).size else 0.0)
        return jnp.pad(x, width, constant_values=cv)
    jmode = {"reflect": "reflect", "edge": "edge", "wrap": "wrap"}[mode]
    return jnp.pad(x, width, mode=jmode)


@op("Cast")
def _cast(ctx, x):
    to = proto.TENSOR_DTYPES[ctx.attr("to")]
    if _is_host(x):
        return np.asarray(x).astype(to)
    return x.astype(to)


@op("CastLike")
def _cast_like(ctx, x, like):
    dt = np.asarray(like).dtype if _is_host(like) else like.dtype
    if _is_host(x):
        return np.asarray(x).astype(dt)
    return x.astype(dt)


def _node_key(ctx):
    """PRNG key for a random op: the ONNX ``seed`` attribute when given,
    else a per-node seed derived from the node name — two random nodes
    in one graph draw differently, and a given graph is deterministic
    across runs (the spec leaves the unseeded case implementation-
    defined; XLA cannot express ambient nondeterminism)."""
    import zlib
    seed = ctx.attr("seed")
    if seed is None:
        seed = zlib.crc32(f"{ctx.name}|{ctx.op_type}".encode())
    # the spec types seed as float; bit-cast keeps distinct floats distinct
    return jax.random.PRNGKey(
        int(np.float32(seed).view(np.uint32)) if not float(
            seed).is_integer() else int(seed) & 0x7FFFFFFF)


def _random_dtype(ctx, like=None, default=np.float32):
    dt = ctx.attr("dtype")
    if dt is not None:
        return proto.TENSOR_DTYPES[int(dt)]
    if like is not None:
        return like.dtype
    return default


@op("RandomNormal")
def _random_normal(ctx):
    shape = tuple(int(s) for s in ctx.attr("shape"))
    dt = _random_dtype(ctx)
    return (jax.random.normal(_node_key(ctx), shape)
            * ctx.attr("scale", 1.0) + ctx.attr("mean", 0.0)).astype(dt)


@op("RandomNormalLike")
def _random_normal_like(ctx, x):
    dt = _random_dtype(ctx, like=jnp.asarray(x))
    return (jax.random.normal(_node_key(ctx), jnp.shape(x))
            * ctx.attr("scale", 1.0) + ctx.attr("mean", 0.0)).astype(dt)


@op("RandomUniform")
def _random_uniform(ctx):
    shape = tuple(int(s) for s in ctx.attr("shape"))
    dt = _random_dtype(ctx)
    return jax.random.uniform(
        _node_key(ctx), shape, minval=ctx.attr("low", 0.0),
        maxval=ctx.attr("high", 1.0)).astype(dt)


@op("RandomUniformLike")
def _random_uniform_like(ctx, x):
    dt = _random_dtype(ctx, like=jnp.asarray(x))
    return jax.random.uniform(
        _node_key(ctx), jnp.shape(x), minval=ctx.attr("low", 0.0),
        maxval=ctx.attr("high", 1.0)).astype(dt)


@op("Bernoulli")
def _bernoulli(ctx, x):
    x = jnp.asarray(x)
    dt = _random_dtype(ctx, like=x)
    draws = jax.random.uniform(_node_key(ctx), x.shape)
    return (draws < x.astype(jnp.float32)).astype(dt)


@op("Multinomial")
def _multinomial(ctx, x):
    """Multinomial: ``sample_size`` draws per batch row from unnormalized
    LOG-probabilities (the spec's input is runtime-values of a softmax's
    input)."""
    n = int(ctx.attr("sample_size", 1))
    dt = proto.TENSOR_DTYPES[int(ctx.attr("dtype", 6))]
    x = jnp.asarray(x)
    return jax.random.categorical(
        _node_key(ctx), x[:, None, :], axis=-1,
        shape=(x.shape[0], n)).astype(dt)


@op("STFT")
def _stft(ctx, signal, frame_step, window=None, frame_length=None):
    """STFT (opset 17): framed DFT with static frame geometry — frames
    are gathered as one [B, frames, flen] tensor and transformed with a
    single batched (r)fft, not a per-frame loop. The speech front-end
    op (pairs with cognitive/speech.py's WAV pull-stream)."""
    (step,) = _static_int_list(frame_step, "STFT frame_step")
    sig = jnp.asarray(signal)
    if sig.ndim == 3:  # [B, length, 1]
        if sig.shape[-1] != 1:
            raise NotImplementedError(
                "STFT: complex input signals are not supported")
        sig = sig[..., 0]
    if window is not None:
        win = jnp.asarray(window)
        flen = int(win.shape[0])
        if frame_length is not None:
            (fl2,) = _static_int_list(frame_length, "STFT frame_length")
            if fl2 != flen:
                raise ValueError(
                    f"STFT: window length {flen} != frame_length {fl2}")
    else:
        if frame_length is None:
            raise ValueError("STFT needs window and/or frame_length")
        (flen,) = _static_int_list(frame_length, "STFT frame_length")
        win = jnp.ones((flen,), sig.dtype)
    length = sig.shape[-1]
    frames = 1 + (length - flen) // step
    idx = (jnp.arange(frames)[:, None] * step
           + jnp.arange(flen)[None, :])                  # [frames, flen]
    framed = sig[..., idx] * win.astype(sig.dtype)       # [B, frames, flen]
    onesided = bool(ctx.attr("onesided", 1))
    spec = jnp.fft.rfft(framed) if onesided else jnp.fft.fft(framed)
    out = jnp.stack([jnp.real(spec), jnp.imag(spec)], axis=-1)
    return out.astype(jnp.float32 if sig.dtype != jnp.float64
                      else jnp.float64)


def _cosine_window(name: str, coeffs):
    """Opset-17 generalized-cosine window family. ``size`` is geometry
    (static); ``periodic=1`` (default) divides by N, symmetric by N-1 —
    the spec's formulas, emitted eagerly as a host constant so a window
    feeding STFT stays a weight, not a traced value."""
    def impl(ctx, size):
        (n,) = _static_int_list(size, f"{name} size")
        dt = proto.TENSOR_DTYPES[int(ctx.attr("output_datatype", 1))]
        denom = n if int(ctx.attr("periodic", 1)) else n - 1
        k = 2.0 * np.pi * np.arange(n) / max(denom, 1)
        w = np.zeros(n, np.float64)
        for j, a in enumerate(coeffs):
            w += a * np.cos(j * k) * (-1.0 if j % 2 else 1.0)
        return np.asarray(w, dt)
    return impl


_REGISTRY["HannWindow"] = _cosine_window("HannWindow", (0.5, 0.5))
_REGISTRY["HammingWindow"] = _cosine_window(
    "HammingWindow", (25.0 / 46.0, 21.0 / 46.0))
_REGISTRY["BlackmanWindow"] = _cosine_window(
    "BlackmanWindow", (0.42, 0.5, 0.08))


@op("MelWeightMatrix")
def _mel_weight_matrix(ctx, num_mel_bins, dft_length, sample_rate,
                       lower_edge_hertz, upper_edge_hertz):
    """MelWeightMatrix (opset 17): triangular HTK-mel filterbank,
    [dft_length//2 + 1, num_mel_bins] — spec formula, fully vectorized."""
    (n_mel,) = _static_int_list(num_mel_bins, "MelWeightMatrix bins")
    (n_dft,) = _static_int_list(dft_length, "MelWeightMatrix dft_length")
    sr = float(np.asarray(sample_rate).reshape(()))
    lo = float(np.asarray(lower_edge_hertz).reshape(()))
    hi = float(np.asarray(upper_edge_hertz).reshape(()))
    n_bins = n_dft // 2 + 1

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)

    # the spec QUANTIZES edge frequencies to spectrogram-bin indices
    # (floor((dft+1) * hz / sr)) and builds the triangles over bin
    # indices — matching onnx's reference/ORT numerics exactly, peak
    # 1.0 at each quantized center bin
    edges_hz = mel_to_hz(
        np.linspace(hz_to_mel(lo), hz_to_mel(hi), n_mel + 2))
    bins = np.floor((n_dft + 1) * edges_hz / sr).astype(np.int64)
    left, center, right = bins[:-2], bins[1:-1], bins[2:]
    f = np.arange(n_bins)[:, None].astype(np.float64)   # [n_bins, 1]
    up = (f - left) / np.maximum(center - left, 1)
    down = (right - f) / np.maximum(right - center, 1)
    w = np.maximum(0.0, np.minimum(up, down))
    dt = proto.TENSOR_DTYPES[int(ctx.attr("output_datatype", 1))]
    return jnp.asarray(w.astype(dt))


@op("Identity")
def _identity(ctx, x):
    return x


@op("Dropout")
def _dropout(ctx, x, ratio=None, training_mode=None):
    # inference semantics: pass-through (+ all-true mask if requested)
    if ctx.n_outputs > 1:
        return x, jnp.ones(np.shape(x), dtype=bool)
    return x


@op("Constant")
def _constant(ctx):
    for key in ("value", "value_float", "value_int"):
        v = ctx.attr(key)
        if v is not None:
            return np.asarray(v)
    for key, dt in (("value_floats", np.float32), ("value_ints", np.int64)):
        v = ctx.attr(key)
        if v is not None:
            return np.asarray(v, dtype=dt)
    raise ValueError("Constant node without value")


@op("ConstantOfShape")
def _constant_of_shape(ctx, shape):
    dims = _static_int_list(shape, "ConstantOfShape shape")
    v = ctx.attr("value")
    if v is None:
        return np.zeros(dims, dtype=np.float32)
    v = np.asarray(v)
    return np.full(dims, v.reshape(-1)[0], dtype=v.dtype)


@op("Range")
def _range(ctx, start, limit, delta):
    if _all_host((start, limit, delta)):
        return np.arange(int(np.asarray(start)), int(np.asarray(limit)),
                         int(np.asarray(delta)),
                         dtype=np.asarray(start).dtype)
    raise ValueError("Range with traced bounds is not supported (dynamic shape)")


@op("OneHot")
def _one_hot(ctx, indices, depth, values):
    axis = ctx.attr("axis", -1)
    d = int(np.asarray(depth).reshape(()))
    off_val, on_val = values[0], values[1]
    oh = jax.nn.one_hot(jnp.asarray(indices), d, axis=axis)
    return oh * (on_val - off_val) + off_val


@op("SpaceToDepth")
def _space_to_depth(ctx, x):
    b = ctx.attr("blocksize")
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


@op("DepthToSpace")
def _depth_to_space(ctx, x):
    b = ctx.attr("blocksize")
    mode = ctx.attr("mode", "DCR")
    n, c, h, w = x.shape
    if mode == "DCR":
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    else:
        x = x.reshape(n, c // (b * b), b, b, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (b * b), h * b, w * b)


# ---------------------------------------------------------------------------
# Reductions / softmax / top-k
# ---------------------------------------------------------------------------

def _reduce(jnp_fn):
    def impl(ctx, x, axes=None):
        if ctx.opset < 18 and axes is None:
            axes = ctx.attr("axes", None)
        keep = bool(ctx.attr("keepdims", 1))
        if axes is None or (hasattr(axes, "__len__") and len(axes) == 0):
            if ctx.attr("noop_with_empty_axes", 0):
                return x
            ax = None
        else:
            ax = tuple(a % x.ndim for a in _static_int_list(axes, "Reduce axes"))
        return jnp_fn(x, axis=ax, keepdims=keep)
    return impl


_REGISTRY["ReduceMean"] = _reduce(jnp.mean)
_REGISTRY["ReduceSum"] = _reduce(jnp.sum)
_REGISTRY["ReduceMax"] = _reduce(jnp.max)
_REGISTRY["ReduceMin"] = _reduce(jnp.min)
_REGISTRY["ReduceProd"] = _reduce(jnp.prod)
_REGISTRY["ReduceL1"] = _reduce(lambda x, axis, keepdims: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims))
_REGISTRY["ReduceL2"] = _reduce(lambda x, axis, keepdims: jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims)))
_REGISTRY["ReduceLogSumExp"] = _reduce(lambda x, axis, keepdims: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
_REGISTRY["ReduceLogSum"] = _reduce(lambda x, axis, keepdims: jnp.log(jnp.sum(x, axis=axis, keepdims=keepdims)))
_REGISTRY["ReduceSumSquare"] = _reduce(lambda x, axis, keepdims: jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


@op("ArgMax")
def _argmax(ctx, x):
    axis = ctx.attr("axis", 0)
    keep = bool(ctx.attr("keepdims", 1))
    out = jnp.argmax(x, axis=axis).astype(jnp.int64)
    return jnp.expand_dims(out, axis) if keep else out


@op("ArgMin")
def _argmin(ctx, x):
    axis = ctx.attr("axis", 0)
    keep = bool(ctx.attr("keepdims", 1))
    out = jnp.argmin(x, axis=axis).astype(jnp.int64)
    return jnp.expand_dims(out, axis) if keep else out


def _softmax_impl(ctx, x, log: bool):
    axis = ctx.attr("axis", -1 if ctx.opset >= 13 else 1)
    fn = jax.nn.log_softmax if log else jax.nn.softmax
    if ctx.opset >= 13:
        return fn(x, axis=axis)
    # legacy semantics: flatten to 2D at `axis`, softmax, reshape back
    axis = axis % x.ndim
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    flat = x.reshape(lead, -1)
    return fn(flat, axis=-1).reshape(x.shape)


@op("Softmax")
def _softmax(ctx, x):
    return _softmax_impl(ctx, x, log=False)


@op("LogSoftmax")
def _log_softmax(ctx, x):
    return _softmax_impl(ctx, x, log=True)


@op("Hardmax")
def _hardmax(ctx, x):
    axis = ctx.attr("axis", -1 if ctx.opset >= 13 else 1)
    idx = jnp.argmax(x, axis=axis)
    return jax.nn.one_hot(idx, x.shape[axis], axis=axis, dtype=x.dtype)


@op("TopK")
def _topk(ctx, x, k=None):
    axis = ctx.attr("axis", -1)
    largest = ctx.attr("largest", 1)
    if ctx.opset < 10:
        kk = ctx.attr("k")
    else:
        kk = int(np.asarray(k).reshape(()))
    x = jnp.asarray(x)
    moved = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(moved if largest else -moved, kk)
    if not largest:
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis).astype(jnp.int64))


@op("CumSum")
def _cumsum(ctx, x, axis):
    ax = int(np.asarray(axis).reshape(()))
    y = jnp.asarray(x)
    if ctx.attr("reverse", 0):
        y = jnp.flip(y, ax)
    out = jnp.cumsum(y, axis=ax)
    if ctx.attr("exclusive", 0):
        out = out - y
    if ctx.attr("reverse", 0):
        out = jnp.flip(out, ax)
    return out


# ---------------------------------------------------------------------------
# Resize / Upsample
# ---------------------------------------------------------------------------

def _resize_nearest_asymmetric(x, out_shape, nearest_mode: str):
    """out[i] = in[round(i / scale)] with the requested rounding — the
    opset-10 / Upsample-compatible convention torch exports by default."""
    y = x
    for axis, (o, i) in enumerate(zip(out_shape, x.shape)):
        if o == i:
            continue
        pos = np.arange(o) * (i / o)
        if nearest_mode in ("floor", ""):
            idx = np.floor(pos)
        elif nearest_mode == "ceil":
            idx = np.ceil(pos)
        elif nearest_mode == "round_prefer_ceil":
            idx = np.floor(pos + 0.5)
        else:  # round_prefer_floor (spec default)
            idx = np.ceil(pos - 0.5)
        y = jnp.take(y, np.clip(idx, 0, i - 1).astype(np.int32), axis=axis)
    return y


@op("Resize")
def _resize(ctx, x, roi=None, scales=None, sizes=None):
    mode = ctx.attr("mode", "nearest")
    coord = ctx.attr("coordinate_transformation_mode", "half_pixel")
    if sizes is not None and np.asarray(sizes).size:
        out_shape = _static_int_list(sizes, "Resize sizes")
    else:
        sc = np.asarray(scales).reshape(-1)
        out_shape = [int(math.floor(s * f)) for s, f in zip(x.shape, sc)]
    if mode == "nearest" and coord == "asymmetric":
        return _resize_nearest_asymmetric(
            x, out_shape, ctx.attr("nearest_mode", "round_prefer_floor"))
    if coord not in ("half_pixel", "pytorch_half_pixel"):
        raise NotImplementedError(
            f"Resize coordinate_transformation_mode={coord!r} with "
            f"mode={mode!r} is not supported (half_pixel family and "
            "nearest+asymmetric are)")
    method = {"nearest": "nearest", "linear": "linear", "cubic": "cubic"}[mode]
    return jax.image.resize(x, out_shape, method=method)


@op("Upsample")
def _upsample(ctx, x, scales=None):
    if scales is None:
        scales = ctx.attr("scales")
    sc = np.asarray(scales).reshape(-1)
    out_shape = [int(math.floor(s * f)) for s, f in zip(x.shape, sc)]
    mode = ctx.attr("mode", "nearest")
    if mode == "nearest":  # legacy Upsample uses asymmetric-floor indexing
        return _resize_nearest_asymmetric(x, out_shape, "floor")
    return jax.image.resize(x, out_shape, method="linear")


# ---------------------------------------------------------------------------
# Recurrent: LSTM / GRU / RNN via lax.scan
# ---------------------------------------------------------------------------

def _direction_slices(direction: str):
    if direction == "bidirectional":
        return [(0, False), (1, True)]
    return [(0, direction == "reverse")]


@op("LSTM")
def _lstm(ctx, x, w, r, b=None, seq_lens=None, init_h=None, init_c=None, p=None):
    """ONNX LSTM (gate order i,o,f,c) lowered to lax.scan per direction."""
    hidden = ctx.attr("hidden_size")
    direction = ctx.attr("direction", "forward")
    seq, batch, _ = x.shape
    n_dirs = w.shape[0]

    def run_dir(d, reverse):
        wd, rd = w[d], r[d]  # (4H, I), (4H, H)
        if b is not None:
            wb, rb = b[d][: 4 * hidden], b[d][4 * hidden:]
        else:
            wb = rb = jnp.zeros((4 * hidden,), x.dtype)
        h0 = init_h[d] if init_h is not None else jnp.zeros((batch, hidden), x.dtype)
        c0 = init_c[d] if init_c is not None else jnp.zeros((batch, hidden), x.dtype)
        xs = jnp.flip(x, 0) if reverse else x
        # precompute input contributions as one big matmul (MXU-friendly)
        x_proj = jnp.einsum("sbi,gi->sbg", xs, wd) + wb

        def step(carry, xp_t):
            h, c = carry
            gates = xp_t + h @ rd.T + rb
            i_g, o_g, f_g, c_g = jnp.split(gates, 4, axis=-1)
            i_g = jax.nn.sigmoid(i_g)
            o_g = jax.nn.sigmoid(o_g)
            f_g = jax.nn.sigmoid(f_g)
            c_new = f_g * c + i_g * jnp.tanh(c_g)
            h_new = o_g * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (h_f, c_f), ys = lax.scan(step, (h0, c0), x_proj)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, h_f, c_f

    outs, hs, cs = [], [], []
    for d, rev in _direction_slices(direction):
        ys, h_f, c_f = run_dir(d, rev)
        outs.append(ys)
        hs.append(h_f)
        cs.append(c_f)
    y = jnp.stack(outs, axis=1)  # (seq, dirs, batch, hidden)
    y_h = jnp.stack(hs, axis=0)
    y_c = jnp.stack(cs, axis=0)
    return (y, y_h, y_c)[: max(ctx.n_outputs, 1)] if ctx.n_outputs > 1 else y


@op("GRU")
def _gru(ctx, x, w, r, b=None, seq_lens=None, init_h=None):
    hidden = ctx.attr("hidden_size")
    direction = ctx.attr("direction", "forward")
    linear_before_reset = ctx.attr("linear_before_reset", 0)
    seq, batch, _ = x.shape

    def run_dir(d, reverse):
        wd, rd = w[d], r[d]  # (3H, I), (3H, H) gate order z,r,h
        if b is not None:
            wb, rb = b[d][: 3 * hidden], b[d][3 * hidden:]
        else:
            wb = rb = jnp.zeros((3 * hidden,), x.dtype)
        h0 = init_h[d] if init_h is not None else jnp.zeros((batch, hidden), x.dtype)
        xs = jnp.flip(x, 0) if reverse else x
        x_proj = jnp.einsum("sbi,gi->sbg", xs, wd) + wb

        def step(h, xp_t):
            xz, xr, xh = jnp.split(xp_t, 3, axis=-1)
            hz, hr, hh = jnp.split(h @ rd.T, 3, axis=-1)
            rbz, rbr, rbh = jnp.split(rb, 3)
            z = jax.nn.sigmoid(xz + hz + rbz)
            rg = jax.nn.sigmoid(xr + hr + rbr)
            if linear_before_reset:
                h_cand = jnp.tanh(xh + rg * (hh + rbh))
            else:
                h_cand = jnp.tanh(xh + (rg * h) @ jnp.split(rd, 3, axis=0)[2].T + rbh)
            h_new = (1 - z) * h_cand + z * h
            return h_new, h_new

        h_f, ys = lax.scan(step, h0, x_proj)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, h_f

    outs, hs = [], []
    for d, rev in _direction_slices(direction):
        ys, h_f = run_dir(d, rev)
        outs.append(ys)
        hs.append(h_f)
    y = jnp.stack(outs, axis=1)
    y_h = jnp.stack(hs, axis=0)
    return (y, y_h)[: max(ctx.n_outputs, 1)] if ctx.n_outputs > 1 else y


@op("RNN")
def _rnn(ctx, x, w, r, b=None, seq_lens=None, init_h=None):
    hidden = ctx.attr("hidden_size")
    direction = ctx.attr("direction", "forward")
    acts = [a.decode() if isinstance(a, bytes) else str(a)
            for a in (ctx.attr("activations", None) or [])]
    if len(set(acts)) > 1:
        raise NotImplementedError(
            f"RNN: per-direction activations {acts} are not supported")
    _ACTS = {"Tanh": jnp.tanh, "Relu": jax.nn.relu,
             "Sigmoid": jax.nn.sigmoid}
    name = acts[0] if acts else "Tanh"
    act = _ACTS.get(name)
    if act is None:
        raise NotImplementedError(f"RNN activation {name!r}")
    seq, batch, _ = x.shape

    def run_dir(d, reverse):
        wd, rd = w[d], r[d]
        if b is not None:
            wb, rb = b[d][:hidden], b[d][hidden:]
        else:
            wb = rb = jnp.zeros((hidden,), x.dtype)
        h0 = init_h[d] if init_h is not None else jnp.zeros((batch, hidden), x.dtype)
        xs = jnp.flip(x, 0) if reverse else x
        x_proj = jnp.einsum("sbi,gi->sbg", xs, wd) + wb

        def step(h, xp_t):
            h_new = act(xp_t + h @ rd.T + rb)
            return h_new, h_new

        h_f, ys = lax.scan(step, h0, x_proj)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, h_f

    outs, hs = [], []
    for d, rev in _direction_slices(direction):
        ys, h_f = run_dir(d, rev)
        outs.append(ys)
        hs.append(h_f)
    y = jnp.stack(outs, axis=1)
    y_h = jnp.stack(hs, axis=0)
    return (y, y_h)[: max(ctx.n_outputs, 1)] if ctx.n_outputs > 1 else y


for _name, _np_fn, _jnp_fn in [
    ("BitwiseAnd", np.bitwise_and, jnp.bitwise_and),
    ("BitwiseOr", np.bitwise_or, jnp.bitwise_or),
    ("BitwiseXor", np.bitwise_xor, jnp.bitwise_xor),
]:
    _REGISTRY[_name] = _ew(_np_fn, _jnp_fn)
_REGISTRY["BitwiseNot"] = _ew(np.invert, jnp.invert)


@op("DFT")
def _dft(ctx, x, dft_length=None, axis=None):
    """Discrete Fourier transform (opset 17 axis-attr / 20 axis-input).
    Real input [..., n, 1] or complex [..., n, 2]; output [..., m, 2]."""
    x = jnp.asarray(x)
    if axis is not None:
        (ax,) = _static_int_list(axis, "DFT axis")
    else:
        # opset 20 moved axis to an input with default -2; opset 17's
        # attribute default is 1. Axes count over the FULL rank
        # (including the trailing re/im dim) per the ONNX spec.
        ax = ctx.attr("axis", -2 if ctx.opset >= 20 else 1)
    ax = ax % x.ndim
    if ax == x.ndim - 1:
        raise ValueError("DFT cannot transform the trailing re/im dim")
    n_fft = None
    if dft_length is not None:
        (n_fft,) = _static_int_list(dft_length, "DFT dft_length")
    inverse = bool(ctx.attr("inverse", 0))
    onesided = bool(ctx.attr("onesided", 0))
    if x.shape[-1] == 2:
        sig = jax.lax.complex(x[..., 0], x[..., 1])
    elif x.shape[-1] == 1:
        sig = x[..., 0].astype(jnp.complex64)
    else:
        raise ValueError("DFT input must end in a [1|2] re/im dimension")
    if inverse:
        if onesided:
            raise NotImplementedError("DFT: inverse+onesided")
        spec = jnp.fft.ifft(sig, n=n_fft, axis=ax)
    elif onesided:
        if x.shape[-1] == 2:
            raise ValueError(
                "DFT: onesided=1 requires a real signal ([..., 1]); a "
                "complex input's spectrum is not conjugate-symmetric")
        spec = jnp.fft.rfft(jnp.real(sig), n=n_fft, axis=ax)
    else:
        spec = jnp.fft.fft(sig, n=n_fft, axis=ax)
    out = jnp.stack([jnp.real(spec), jnp.imag(spec)], axis=-1)
    # same-T output constraint: preserve the input's float dtype
    return out.astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.float32)


@op("CenterCropPad")
def _center_crop_pad(ctx, x, shape):
    """Center-crop or zero-pad each listed axis to the target extent."""
    x = jnp.asarray(x) if not _is_host(x) else np.asarray(x)
    target = _static_int_list(shape, "CenterCropPad shape")
    axes = ctx.attr("axes")
    if axes is None:
        axes = list(range(len(target)))
    xp = np if _is_host(x) else jnp
    for ax, want in zip(axes, target):
        ax = ax % x.ndim
        have = x.shape[ax]
        if want < have:  # crop: extra-at-start goes to the low side
            lo = (have - want) // 2
            x = jax.lax.slice_in_dim(x, lo, lo + want, axis=ax) \
                if xp is jnp else np.take(x, range(lo, lo + want), axis=ax)
        elif want > have:  # pad: extra-at-end goes to the high side
            lo = (want - have) // 2
            pads = [(0, 0)] * x.ndim
            pads[ax] = (lo, want - have - lo)
            x = xp.pad(x, pads)
    return x


@op("Col2Im")
def _col2im(ctx, x, image_shape, block_shape):
    """Inverse of the exporters' im2col unfolding (opset 18): scatter-add
    column blocks back into the image. One vectorized index build + one
    .at[].add — XLA lowers it as a single scatter."""
    img = _static_int_list(image_shape, "Col2Im image_shape")
    blk = _static_int_list(block_shape, "Col2Im block_shape")
    rank = len(img)
    strides = ctx.attr("strides", [1] * rank)
    dil = ctx.attr("dilations", [1] * rank)
    pads = ctx.attr("pads", [0] * (2 * rank))
    x = jnp.asarray(x)
    n, ckk, L = x.shape
    kprod = int(np.prod(blk))
    c = ckk // kprod
    # per-dim output positions of each (block offset, column) pair
    outs = [1 + (img[d] + pads[d] + pads[d + rank]
                 - dil[d] * (blk[d] - 1) - 1) // strides[d]
            for d in range(rank)]
    if int(np.prod(outs)) != L:
        raise ValueError(
            f"Col2Im: {L} columns do not factor into positions {outs}")
    k_idx = np.stack(np.unravel_index(np.arange(kprod), blk), 0)  # [r,K]
    l_idx = np.stack(np.unravel_index(np.arange(L), outs), 0)     # [r,L]
    coords = []
    valid = np.ones((kprod, L), bool)
    for d in range(rank):
        pos = (k_idx[d][:, None] * dil[d]
               + l_idx[d][None, :] * strides[d] - pads[d])  # [K, L]
        valid &= (pos >= 0) & (pos < img[d])
        coords.append(np.clip(pos, 0, img[d] - 1))
    flat = np.zeros((kprod, L), np.int64)
    for d in range(rank):
        flat = flat * img[d] + coords[d]
    vals = x.reshape(n, c, kprod, L) * jnp.asarray(valid, x.dtype)
    out = jnp.zeros((n, c, int(np.prod(img))), x.dtype)
    out = out.at[:, :, jnp.asarray(flat.reshape(-1))].add(
        vals.reshape(n, c, -1))
    return out.reshape((n, c) + tuple(img))


@op("AffineGrid")
def _affine_grid(ctx, theta, size):
    """Sampling-grid generator (opset 20) — pairs with GridSample, the
    torch.nn.functional.affine_grid export."""
    dims = _static_int_list(size, "AffineGrid size")
    align = bool(ctx.attr("align_corners", 0))
    theta = jnp.asarray(theta, jnp.float32)
    spatial = dims[2:]
    rank = len(spatial)
    if rank not in (2, 3):
        raise NotImplementedError("AffineGrid supports 4-D/5-D sizes")

    def axis_coords(n):
        if align:
            return (jnp.linspace(-1.0, 1.0, n) if n > 1
                    else jnp.zeros((1,)))
        step = 2.0 / n
        return -1.0 + step / 2 + step * jnp.arange(n, dtype=jnp.float32)

    axes = [axis_coords(s) for s in spatial]
    mesh = jnp.meshgrid(*axes, indexing="ij")          # rank x spatial
    # homogeneous coords ordered (x, y[, z]) = reversed spatial order
    ones = jnp.ones_like(mesh[0])
    pts = jnp.stack(list(reversed(mesh)) + [ones], -1)  # [*sp, rank+1]
    grid = jnp.einsum("...k,njk->n...j", pts, theta)
    return grid.astype(jnp.float32)


@op("Unique")
def _unique(ctx, x):
    """Data-dependent output shape: host-side only (same contract as the
    reference's ORT CPU kernel; a traced input cannot produce a
    dynamic-shape XLA result)."""
    if not _is_host(x):
        raise NotImplementedError(
            "Unique produces data-dependent shapes; feed it host-side "
            "data (constant-folded subgraph) or move it out of the "
            "jitted region")
    x = np.asarray(x)
    axis = ctx.attr("axis")
    is_sorted = bool(ctx.attr("sorted", 1))
    y, first_idx, inverse, counts = np.unique(
        x, return_index=True, return_inverse=True, return_counts=True,
        axis=axis)
    if not is_sorted:
        order = np.argsort(first_idx, kind="stable")
        rank_of = np.empty_like(order)
        rank_of[order] = np.arange(len(order))
        y = np.take(y, order, axis=axis if axis is not None else 0)
        first_idx = first_idx[order]
        counts = counts[order]
        inverse = rank_of[inverse]
    outs = (y, first_idx.astype(np.int64),
            inverse.reshape(-1).astype(np.int64),
            counts.astype(np.int64))
    return outs[: max(ctx.n_outputs, 1)] if ctx.n_outputs > 1 else y


@op("Compress")
def _compress(ctx, x, condition):
    """Boolean-mask selection — output length is data-dependent, so the
    condition must be host-side (initializer / folded)."""
    if not (_is_host(condition) and _is_host(x)):
        raise NotImplementedError(
            "Compress produces data-dependent shapes; condition and data "
            "must be host-side (constant-folded)")
    return np.compress(np.asarray(condition, bool).reshape(-1),
                       np.asarray(x), axis=ctx.attr("axis"))


def _nll_core(logp, target, weight, reduction, ignore_index):
    n, c = logp.shape[0], logp.shape[1]
    t = jnp.asarray(target).astype(jnp.int32)
    gather = jnp.take_along_axis(
        logp, t[:, None] if logp.ndim == 2
        else t[:, None, ...], axis=1).squeeze(1)
    w = (jnp.asarray(weight, jnp.float32)[t.clip(0, c - 1)]
         if weight is not None else jnp.ones_like(gather))
    if ignore_index is not None:
        w = jnp.where(t == ignore_index, 0.0, w)
    loss = -gather * w
    if reduction == "none":
        return loss
    if reduction == "sum":
        return loss.sum()
    return loss.sum() / jnp.maximum(w.sum(), 1e-12)


@op("NegativeLogLikelihoodLoss")
def _nll_loss(ctx, x, target, weight=None):
    return _nll_core(jnp.asarray(x, jnp.float32), target, weight,
                     ctx.attr("reduction", "mean"),
                     ctx.attr("ignore_index"))


@op("SoftmaxCrossEntropyLoss")
def _softmax_ce_loss(ctx, scores, target, weight=None):
    logp = jax.nn.log_softmax(jnp.asarray(scores, jnp.float32), axis=1)
    loss = _nll_core(logp, target, weight, ctx.attr("reduction", "mean"),
                     ctx.attr("ignore_index"))
    return (loss, logp) if ctx.n_outputs > 1 else loss


@op("MatMulNBits")
def _matmul_nbits(ctx, a, b_packed, scales, zero_points=None):
    """com.microsoft blockwise 4-bit quantized matmul — the quantized-LLM
    weight format. B is [N, K/block, block/2] packed nibbles (low nibble
    = even element); dequantize blockwise to [K, N] once (XLA keeps it
    fused into the dot's operand) and run one MXU matmul."""
    bits = int(ctx.attr("bits", 4))
    if bits != 4:
        raise NotImplementedError("MatMulNBits: only bits=4 is supported")
    K = int(ctx.attr("K"))
    N = int(ctx.attr("N"))
    block = int(ctx.attr("block_size"))
    bp = jnp.asarray(b_packed)
    n_blocks = bp.shape[1]
    lo = (bp & 0xF).astype(jnp.int32)
    hi = (bp >> 4).astype(jnp.int32)
    nibbles = jnp.stack([lo, hi], -1).reshape(N, n_blocks, -1)  # [N,nb,blk]
    sc = jnp.asarray(scales, jnp.float32).reshape(N, n_blocks)
    if zero_points is None:
        zp = jnp.full((N, n_blocks), 8.0, jnp.float32)
    else:
        zpa = jnp.asarray(zero_points)
        if zpa.dtype == jnp.uint8 and zpa.ndim == 1:
            # packed 4-bit zero points, one nibble per block
            zl = (zpa & 0xF).astype(jnp.float32)
            zh = (zpa >> 4).astype(jnp.float32)
            zp = jnp.stack([zl, zh], -1).reshape(N, -1)[:, :n_blocks]
        else:
            zp = zpa.astype(jnp.float32).reshape(N, n_blocks)
    deq = (nibbles.astype(jnp.float32) - zp[..., None]) * sc[..., None]
    w = deq.reshape(N, n_blocks * block)[:, :K]               # [N, K]
    a = jnp.asarray(a)
    return jnp.matmul(a, w.T.astype(a.dtype))


def _apply_rope(t, cc, ss, interleaved, rot):
    """Rotate the leading ``rot`` features of ``t`` by (cos, sin) —
    the core shared by RotaryEmbedding and GroupQueryAttention's
    internal rope. ``cc``/``ss`` broadcast against t[..., :rot//2]."""
    tr, tp = t[..., :rot], t[..., rot:]
    if interleaved:
        t1, t2 = tr[..., 0::2], tr[..., 1::2]
    else:
        t1, t2 = tr[..., : rot // 2], tr[..., rot // 2:]
    o1 = t1 * cc - t2 * ss
    o2 = t2 * cc + t1 * ss
    out = (jnp.stack([o1, o2], -1).reshape(tr.shape) if interleaved
           else jnp.concatenate([o1, o2], -1))
    return jnp.concatenate([out.astype(t.dtype), tp], -1)


@op("RotaryEmbedding")
def _rotary_embedding(ctx, x, position_ids, cos_cache, sin_cache):
    """com.microsoft rotary position embedding (the LLM export op).
    3-D [B, S, H] (num_heads attr) or 4-D [B, NH, S, Hd] input;
    interleaved and half-split layouts."""
    interleaved = bool(ctx.attr("interleaved", 0))
    x = jnp.asarray(x)
    squeeze_back = x.ndim == 3
    if squeeze_back:
        nh = int(ctx.attr("num_heads", 0))
        b, s, h = x.shape
        if nh <= 0:
            raise ValueError("RotaryEmbedding: 3-D input needs num_heads")
        x = x.reshape(b, s, nh, h // nh).transpose(0, 2, 1, 3)
    b, nh, s, hd = x.shape
    rot = int(ctx.attr("rotary_embedding_dim", 0)) or hd
    pos = jnp.asarray(position_ids).astype(jnp.int32)
    if pos.size == 1:
        # ORT's start-offset form: one scalar position id means
        # positions start there and increment per token
        pos = pos.reshape(1, 1) + jnp.arange(s, dtype=jnp.int32)[None, :]
    elif pos.ndim == 1:
        pos = pos[None, :]
    cos = jnp.asarray(cos_cache, jnp.float32)[pos][:, None]  # [B,1,S,rot/2]
    sin = jnp.asarray(sin_cache, jnp.float32)[pos][:, None]
    out = _apply_rope(x, cos, sin, interleaved, rot)
    if squeeze_back:
        out = out.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
    return out


@op("TfIdfVectorizer")
def _tfidf_vectorizer(ctx, x):
    """N-gram counting over integer token rows (the sklearn
    CountVectorizer/TfidfVectorizer export op). Skip-grams follow the
    onnxruntime interpretation: for each skip value s in
    [0, max_skip_count], n-gram items are taken at EQUAL stride s+1.
    Matching is one vectorized windows==pool comparison per
    (n, skip) pair — [N, W, P] elementwise on device, no per-row loops.
    """
    mode = str(ctx.attr("mode", "TF"))
    min_n = int(ctx.attr("min_gram_length", 1))
    max_n = int(ctx.attr("max_gram_length", 1))
    max_skip = int(ctx.attr("max_skip_count", 0))
    if ctx.attr("pool_int64s") is None or \
            ctx.attr("pool_strings") is not None:
        raise NotImplementedError(
            "TfIdfVectorizer: only pool_int64s token pools are supported")
    pool = np.asarray(ctx.attr("pool_int64s"), np.int64)
    counts_attr = [int(v) for v in ctx.attr("ngram_counts")]
    indexes = np.asarray(ctx.attr("ngram_indexes"), np.int64)
    weights = ctx.attr("weights")
    n_out = int(indexes.max()) + 1 if indexes.size else 0
    x = jnp.asarray(x)
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None, :]
    rows, seq = x.shape

    # pool layout: ngram_counts[i] = flat offset of the (i+1)-gram block
    bounds = counts_attr + [len(pool)]
    out = jnp.zeros((rows, n_out), jnp.float32)
    idx_cursor = 0
    for level in range(len(counts_attr)):
        n = level + 1
        lo, hi = bounds[level], bounds[level + 1]
        n_grams = (hi - lo) // max(n, 1)
        if n_grams == 0:
            continue
        cols = indexes[idx_cursor: idx_cursor + n_grams]
        idx_cursor += n_grams
        if not (min_n <= n <= max_n):
            continue  # pool level present but not counted
        grams = jnp.asarray(pool[lo:hi].reshape(n_grams, n))
        skips = range(max_skip + 1) if n > 1 else (0,)
        level_counts = jnp.zeros((rows, n_grams), jnp.float32)
        for s in skips:
            stride = s + 1
            span = (n - 1) * stride + 1
            if span > seq:
                continue
            w = seq - span + 1
            win_idx = (np.arange(w)[:, None]
                       + np.arange(n)[None, :] * stride)    # [W, n]
            windows = x[:, win_idx]                         # [N, W, n]

            def count_chunk(gchunk):
                # per-position AND accumulation: the peak intermediate
                # is [N, W, chunk], never [N, W, chunk, n]
                m = jnp.ones((rows, w, gchunk.shape[0]), bool)
                for kk in range(n):
                    m = m & (windows[:, :, kk, None]
                             == gchunk[None, None, :, kk])
                return m.sum(1).astype(jnp.float32)         # [N, chunk]

            # chunk the pool so rows*W*chunk stays bounded (a real text
            # export carries tens of thousands of n-grams)
            chunk = max(1, min(n_grams, (1 << 24) // max(rows * w, 1)))
            if chunk >= n_grams:
                level_counts = level_counts + count_chunk(grams)
            else:
                n_chunks = -(-n_grams // chunk)
                pad = n_chunks * chunk - n_grams
                gp = jnp.pad(grams, ((0, pad), (0, 0)),
                             constant_values=-1)  # -1 never matches
                _, per = lax.scan(
                    lambda c, g: (c, count_chunk(g)), None,
                    gp.reshape(n_chunks, chunk, n))
                level_counts = level_counts + jnp.moveaxis(
                    per, 0, 1).reshape(rows, -1)[:, :n_grams]
        out = out.at[:, cols].add(level_counts)
    if mode in ("IDF", "TFIDF"):
        # weights align with the POOL order; scatter to output columns
        wv_np = np.ones(n_out, np.float32)
        if weights is not None:
            wv_np[np.asarray(indexes)] = np.asarray(weights, np.float32)
        wv = jnp.asarray(wv_np)
        if mode == "IDF":
            out = jnp.where(out > 0, wv[None, :], 0.0)
        else:
            out = out * wv[None, :]
    elif mode != "TF":
        raise ValueError(f"TfIdfVectorizer mode {mode!r}")
    return out[0] if squeeze else out


# Optional wrappers: the env's natural None/value distinction IS the
# optional type (absent optional inputs already flow as None)
_REGISTRY["Optional"] = lambda ctx, x=None: x
_REGISTRY["OptionalHasElement"] = lambda ctx, x=None: np.bool_(
    x is not None)


@op("OptionalGetElement")
def _optional_get_element(ctx, x=None):
    if x is None:
        raise ValueError("OptionalGetElement on an empty optional")
    return x


# -- Sequence ops (torch unbind/split/list exports) -----------------------
# A sequence is a Python list of tensors: the LENGTH and every position
# index must be static (they shape the program), while the elements may
# be traced — under jit a list of tracers is just a pytree, so sequence
# graphs compile like any other.

_REGISTRY["SequenceEmpty"] = lambda ctx: []
_REGISTRY["SequenceConstruct"] = lambda ctx, *xs: list(xs)
_REGISTRY["SequenceLength"] = lambda ctx, seq: np.int64(len(seq))


def _seq_pos(pos, n, what):
    (p,) = _static_int_list(pos, what)
    if not -n <= p <= n - 1:
        raise ValueError(f"{what}: position {p} out of range for a "
                         f"{n}-element sequence")
    return p  # python list indexing handles the negative form


@op("SequenceAt")
def _sequence_at(ctx, seq, pos):
    return seq[_seq_pos(pos, len(seq), "SequenceAt position")]


@op("SequenceInsert")
def _sequence_insert(ctx, seq, tensor, pos=None):
    out = list(seq)
    if pos is None:
        out.append(tensor)
    else:
        (p,) = _static_int_list(pos, "SequenceInsert position")
        if not -len(seq) <= p <= len(seq):
            raise ValueError(
                f"SequenceInsert: position {p} out of range for a "
                f"{len(seq)}-element sequence")
        # python insert matches the ONNX reference for negatives too:
        # insert(-1) places BEFORE the last element
        out.insert(p, tensor)
    return out


@op("SequenceErase")
def _sequence_erase(ctx, seq, pos=None):
    out = list(seq)
    del out[-1 if pos is None
            else _seq_pos(pos, len(seq), "SequenceErase position")]
    return out


@op("ConcatFromSequence")
def _concat_from_sequence(ctx, seq):
    axis = ctx.attr("axis")
    if axis is None:
        raise ValueError("ConcatFromSequence needs an axis attribute")
    # preserve host-ness: an all-constant sequence must stay foldable
    # for static-shape consumers downstream (the _concat convention)
    xp = np if _all_host(seq) else jnp
    if ctx.attr("new_axis", 0):
        return xp.stack(list(seq), axis=int(axis))
    return xp.concatenate(list(seq), axis=int(axis))


@op("SplitToSequence")
def _split_to_sequence(ctx, x, split=None):
    keepdims = ctx.attr("keepdims", 1)
    x = jnp.asarray(x) if not _is_host(x) else np.asarray(x)
    axis = int(ctx.attr("axis", 0)) % x.ndim  # spec allows [-r, r-1]
    n = x.shape[axis]
    if split is None:
        parts = [jax.lax.index_in_dim(x, i, axis=axis, keepdims=True)
                 if not _is_host(x) else np.take(x, [i], axis=axis)
                 for i in range(n)]
        if not keepdims:
            xp = np if _is_host(x) else jnp
            parts = [xp.squeeze(p, axis=axis) for p in parts]
        return parts
    sizes = _static_int_list(split, "SplitToSequence split")
    if len(sizes) == 1 and np.ndim(split) == 0:
        size = sizes[0]
        sizes = [size] * (n // size) + ([n % size] if n % size else [])
    bounds = np.cumsum(sizes)[:-1].tolist()
    if _is_host(x):
        return list(np.split(x, bounds, axis=axis))
    return jnp.split(x, bounds, axis=axis)


@op("SequenceMap")
def _sequence_map(ctx, seq, *extra, env=None):
    """SequenceMap: run the body subgraph once per sequence element.
    Sequences are static-length python lists here (see the sequence-op
    section header), so the map is a host loop whose per-element bodies
    trace into one jax program — additional tensor inputs broadcast,
    additional sequence inputs zip elementwise, per spec."""
    body = ctx.attrs["__lowered_body__"]  # lowered at import time
    n_out = len(body.output_names)
    outs: List[List[Any]] = [[] for _ in range(n_out)]
    for i in range(len(seq)):
        sub_env = dict(env or {})
        vals = [seq[i]] + [e[i] if isinstance(e, list) else e
                           for e in extra]
        for nm, v in zip(body.input_names, vals):
            sub_env[nm] = v
        for acc, r in zip(outs, body.run(sub_env)):
            acc.append(r)
    return tuple(outs) if n_out > 1 else outs[0]


_sequence_map._needs_env = True


# -- String ops (host-side: object-dtype arrays, the TfIdf/tokenizer
#    preprocessing family sklearn/ORT text pipelines emit) ----------------

def _host_strings(x, opname: str) -> np.ndarray:
    if not _is_host(x):
        raise NotImplementedError(
            f"{opname} operates on host string tensors; string data "
            "cannot be device-traced — feed it as a host input")
    return np.asarray(x, dtype=object)


@op("StringConcat")
def _string_concat(ctx, a, b):
    a = _host_strings(a, "StringConcat")
    b = _host_strings(b, "StringConcat")
    return np.frompyfunc(
        lambda s, t: str(s) + str(t), 2, 1)(a, b).astype(object)


@op("StringSplit")
def _string_split(ctx, x):
    """StringSplit (opset 20): ragged splits padded with "" to the max
    token count (the spec's dense output), plus per-element counts."""
    x = _host_strings(x, "StringSplit")
    delim = ctx.attr("delimiter", None)
    maxsplit = ctx.attr("maxsplit", None)
    ms = -1 if maxsplit is None else int(maxsplit)
    toks = []
    for s in x.reshape(-1):
        s = str(s)
        if delim:  # explicit delimiter: empty strings between separators kept
            toks.append(s.split(delim, ms) if ms >= 0 else s.split(delim))
        else:      # whitespace mode: runs collapse, no empty tokens
            toks.append(s.split(None, ms) if ms >= 0 else s.split())
    width = max((len(t) for t in toks), default=0)
    out = np.full((len(toks), width), "", dtype=object)
    for i, t in enumerate(toks):
        out[i, :len(t)] = t
    counts = np.asarray([len(t) for t in toks], np.int64).reshape(x.shape)
    return out.reshape(x.shape + (width,)), counts


@op("StringNormalizer")
def _string_normalizer(ctx, x):
    """StringNormalizer (opset 10): stopword filtering + case folding on
    a [C] or [1, C] string tensor; an all-filtered input yields the
    spec's single empty string."""
    x = _host_strings(x, "StringNormalizer")
    two_d = x.ndim == 2
    if two_d and x.shape[0] != 1:
        raise ValueError(
            f"StringNormalizer input must be [C] or [1, C], got {x.shape}")
    flat = [str(s) for s in x.reshape(-1)]
    action = str(ctx.attr("case_change_action", "NONE")).upper()
    stop = ctx.attr("stopwords") or []
    if stop:
        if int(ctx.attr("is_case_sensitive", 0)):
            stops = set(stop)
            keep = [s for s in flat if s not in stops]
        else:
            lowered = {w.lower() for w in stop}
            keep = [s for s in flat if s.lower() not in lowered]
    else:
        keep = flat
    if action == "LOWER":
        keep = [s.lower() for s in keep]
    elif action == "UPPER":
        keep = [s.upper() for s in keep]
    if not keep:
        keep = [""]
    out = np.asarray(keep, dtype=object)
    return out.reshape(1, -1) if two_d else out


@op("RegexFullMatch")
def _regex_full_match(ctx, x):
    import re as _re

    x = _host_strings(x, "RegexFullMatch")
    pattern = ctx.attr("pattern")
    if pattern is None:
        raise ValueError("RegexFullMatch needs a pattern attribute")
    # the spec prescribes RE2 syntax; python `re` accepts the shared
    # common subset (RE2 extras like \p{...} raise a loud re.error)
    rx = _re.compile(pattern)
    return np.frompyfunc(
        lambda s: rx.fullmatch(str(s)) is not None, 1, 1)(x).astype(bool)


@op("GroupQueryAttention")
def _group_query_attention(ctx, query, key=None, value=None,
                           past_key=None, past_value=None, seqlens_k=None,
                           total_sequence_length=None, cos_cache=None,
                           sin_cache=None):
    """com.microsoft GroupQueryAttention — the decoder-attention op of
    ORT GenAI exports (completes the quantized-LLM triad with
    MatMulNBits + RotaryEmbedding). Causal grouped-head attention with
    an optional KV cache: ``past_key/past_value`` concatenate ahead of
    this call's keys, ``present_*`` outputs return the extended cache.

    Supported surface (documented limits, loud errors otherwise):
    separate or packed QKV; prefill and left-aligned decode (the cache
    is assumed densely packed — per-batch ``seqlens_k`` bounds the
    attended keys); internal rotary via ``do_rotary`` with
    batch-uniform position offset = past length. Everything lowers to
    one einsum-softmax-einsum chain per call; XLA fuses the mask.

    ``past_present_share_buffer=1`` switches to the serving-cache
    layout the decode scheduler (runtime/decode.py) compiles against:
    ``past_key/past_value`` are MAX-LENGTH buffers ``[B, Hkv, T, D]``
    whose shape never changes across steps (one compiled program per
    (S, T) geometry — the recompile sentinel stays silent), the new
    K/V rows are scattered in place at each row's write position
    ``past_len_b = seqlens_k + 1 - S`` (clamped at 0 so right-padded
    prefill rows and masked-out idle rows write at the origin), rotary
    uses the same per-row offsets, and attention masks per row to
    ``k_pos <= past_len_b + q_idx`` so slots beyond the live frontier
    — junk from padding, stale evicted rows — are never attended.
    ``present_*`` return the updated same-shape buffers."""
    num_heads = int(ctx.attr("num_heads", 0))
    kv_heads = int(ctx.attr("kv_num_heads", 0))
    if num_heads <= 0 or kv_heads <= 0:
        raise ValueError("GroupQueryAttention needs num_heads and "
                         "kv_num_heads attributes")
    q = jnp.asarray(query)
    b, s, dq = q.shape
    if key is None or (hasattr(key, "size") and np.size(key) == 0):
        # packed QKV: [B, S, (Hq + 2*Hkv) * D]
        head = dq // (num_heads + 2 * kv_heads)
        q, k, v = jnp.split(
            q, [num_heads * head, (num_heads + kv_heads) * head], axis=-1)
    else:
        head = dq // num_heads
        k, v = jnp.asarray(key), jnp.asarray(value)
    dt = q.dtype

    def heads(t, h):
        return t.reshape(b, s, h, head).transpose(0, 2, 1, 3)

    q = heads(q, num_heads)                        # [B, Hq, S, D]
    k = heads(k, kv_heads)                         # [B, Hkv, S, D]
    v = heads(v, kv_heads)
    share = bool(ctx.attr("past_present_share_buffer", 0))
    if share and (past_key is None or seqlens_k is None):
        raise ValueError(
            "GroupQueryAttention: past_present_share_buffer=1 needs "
            "past_key/past_value buffers and seqlens_k")
    past_len = 0
    if past_key is not None:
        past_len = jnp.asarray(past_key).shape[2]
    past_len_b = None
    if share:
        # ORT share-buffer convention: seqlens_k = total valid keys - 1
        # INCLUDING this call's S new tokens, so each row's write
        # position is seqlens_k + 1 - S. The clamp makes right-padded
        # prefill rows (valid v < S => position v - S < 0) and idle
        # batch rows (seqlens_k = 0) write at the origin; their junk
        # lands at/beyond the attention frontier and is either masked
        # or overwritten before it ever becomes attendable.
        lens = jnp.asarray(seqlens_k).astype(jnp.int32).reshape(b)
        past_len_b = jnp.maximum(lens + 1 - s, 0)  # [B] write positions

    if bool(ctx.attr("do_rotary", 0)):
        if cos_cache is None or sin_cache is None:
            raise ValueError("do_rotary=1 needs cos_cache/sin_cache")
        cos = jnp.asarray(cos_cache, jnp.float32)
        sin = jnp.asarray(sin_cache, jnp.float32)
        rot = 2 * cos.shape[-1]
        max_pos = past_len if share else past_len + s
        if max_pos > cos.shape[0]:
            # a clamped gather would silently freeze the rotary angle
            raise ValueError(
                f"GroupQueryAttention: positions up to {max_pos} exceed "
                f"the exported rope cache ({cos.shape[0]} rows); "
                "re-export with a longer max position")
        inter = bool(ctx.attr("rotary_interleaved", 0))
        if share:
            pos = past_len_b[:, None] + jnp.arange(s, dtype=jnp.int32)
            cc, ss = cos[pos][:, None], sin[pos][:, None]  # [B,1,S,half]
        else:
            pos = past_len + jnp.arange(s, dtype=jnp.int32)
            cc, ss = cos[pos][None, None], sin[pos][None, None]
        q = _apply_rope(q, cc, ss, inter, rot)
        k = _apply_rope(k, cc, ss, inter, rot)

    if share:
        # in-place scatter at each row's write position — the buffer
        # shape (and with it the compiled program) is step-invariant
        def _scat(buf, new, p):
            return jax.lax.dynamic_update_slice(buf, new, (0, p, 0))

        k = jax.vmap(_scat)(jnp.asarray(past_key, dt), k, past_len_b)
        v = jax.vmap(_scat)(jnp.asarray(past_value, dt), v, past_len_b)
    elif past_key is not None:
        k = jnp.concatenate([jnp.asarray(past_key, dt), k], axis=2)
        v = jnp.concatenate([jnp.asarray(past_value, dt), v], axis=2)
    present_k, present_v = k, v
    t_kv = k.shape[2]

    group = num_heads // kv_heads
    # grouped einsum — K/V stay [B, Hkv, T, D]: a materialized
    # group-repeat would copy the whole KV cache group x per call
    qg = q.reshape(b, kv_heads, group, s, head).astype(jnp.float32)
    scale = ctx.attr("scale", 0.0) or 1.0 / math.sqrt(head)
    logits = jnp.einsum("bkgsd,bktd->bkgst", qg,
                        k.astype(jnp.float32)) * scale
    k_pos = jnp.arange(t_kv)[None, :]
    if share:
        # per-row causal frontier: row b's query j sits at global
        # position past_len_b[b] + j and may attend keys at or before
        # it — junk slots beyond the frontier never enter the softmax
        q_pos = past_len_b[:, None, None] + jnp.arange(s)[None, :, None]
        mask = (k_pos[:, None] <= q_pos)[:, None, None]  # [B,1,1,S,T]
    else:
        q_pos = past_len + jnp.arange(s)[:, None]  # global query positions
        mask = (k_pos <= q_pos)[None, None, None]      # causal   [S, T]
        if seqlens_k is not None:
            # ORT convention: seqlens_k = total valid keys per batch - 1
            lim = (jnp.asarray(seqlens_k).astype(jnp.int32).reshape(b)
                   + 1)
            mask = mask & (k_pos < lim[:, None])[:, None, None, None, :]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgst,bktd->bkgsd", probs, v.astype(jnp.float32))
    out = out.reshape(b, num_heads, s, head)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, num_heads * head)
    out = out.astype(dt)
    if ctx.n_outputs > 1:
        return out, present_k, present_v
    return out


# ---------------------------------------------------------------------------
# com.microsoft transformer-fusion family — what onnxruntime's
# transformer optimizer (fusion passes) rewrites BERT/GPT graphs into.
# The reference scores such optimized exports through ORT unchanged
# (ONNXModel.scala:173-193); here each fused node lowers to the same
# jax it would have lowered to unfused, so XLA re-fuses on its own
# terms (the fusion is a no-op semantically, load-bearing for ORT only).
# ---------------------------------------------------------------------------

@op("FusedMatMul")
def _fused_matmul(ctx, a, b):
    if int(ctx.attr("transBatchA", 0)) or int(ctx.attr("transBatchB", 0)):
        raise NotImplementedError(
            "FusedMatMul transBatchA/transBatchB (batch-axis folding) is "
            "not supported; re-export without batch transpose")
    a, b = jnp.asarray(a), jnp.asarray(b)
    if int(ctx.attr("transA", 0)):
        a = jnp.swapaxes(a, -1, -2)
    if int(ctx.attr("transB", 0)):
        b = jnp.swapaxes(b, -1, -2)
    return float(ctx.attr("alpha", 1.0)) * jnp.matmul(a, b)


@op("BiasGelu")
def _bias_gelu(ctx, x, bias):
    return jax.nn.gelu(jnp.asarray(x) + jnp.asarray(bias),
                       approximate=False)


@op("FastGelu")
def _fast_gelu(ctx, x, bias=None):
    x = jnp.asarray(x)
    if bias is not None:
        x = x + jnp.asarray(bias)
    return jax.nn.gelu(x, approximate=True)


@op("QuickGelu")
def _quick_gelu(ctx, x):
    x = jnp.asarray(x)
    return x * jax.nn.sigmoid(float(ctx.attr("alpha", 1.702)) * x)


def _rms_norm(x, scale, eps, axis):
    x32 = jnp.asarray(x, jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
    inv = lax.rsqrt(ms + eps)
    return (x32 * inv * jnp.asarray(scale, jnp.float32)).astype(
        jnp.asarray(x).dtype), inv


@op("SimplifiedLayerNormalization", "RMSNormalization")
def _simplified_layer_norm(ctx, x, scale):
    """RMSNorm — ORT's contrib name for it (LLaMA-family exports); the
    standard ai.onnx domain added the same op as RMSNormalization in
    opset 23 (identical signature/attrs)."""
    axis = int(ctx.attr("axis", -1)) % np.ndim(x)
    y, inv = _rms_norm(x, scale, ctx.attr("epsilon", 1e-5),
                       tuple(range(axis, np.ndim(x))))
    return (y, inv)[: max(ctx.n_outputs, 1)] if ctx.n_outputs > 1 else y


def _ln_affine(h, gamma, beta, eps):
    """Shared f32-upcast layernorm core for the fusion family (the
    contrib ops normalize in f32 regardless of input dtype, per ORT).
    Returns (y, mean, inv_std) with y cast back to h's dtype."""
    h32 = jnp.asarray(h, jnp.float32)
    mean = jnp.mean(h32, axis=-1, keepdims=True)
    var = jnp.var(h32, axis=-1, keepdims=True)
    inv = lax.rsqrt(var + eps)
    y = (h32 - mean) * inv * jnp.asarray(gamma, jnp.float32)
    if beta is not None:
        y = y + jnp.asarray(beta, jnp.float32)
    return y.astype(jnp.asarray(h).dtype), mean, inv


@op("SkipSimplifiedLayerNormalization")
def _skip_simplified_layer_norm(ctx, x, skip, gamma, bias=None):
    h = jnp.asarray(x) + jnp.asarray(skip)
    if bias is not None:
        h = h + jnp.asarray(bias)
    y, inv = _rms_norm(h, gamma, ctx.attr("epsilon", 1e-5), -1)
    if ctx.n_outputs > 1:
        # slot 2 ("mean") is defined on the summed input even though the
        # RMS normalization itself is mean-free — fill it so a graph
        # naming it never sees a poisoned None
        mean = jnp.mean(jnp.asarray(h, jnp.float32), -1, keepdims=True)
        return (y, mean, inv, h)[: ctx.n_outputs]
    return y


@op("SkipLayerNormalization")
def _skip_layer_norm(ctx, x, skip, gamma, beta=None, bias=None):
    h = jnp.asarray(x) + jnp.asarray(skip)
    if bias is not None:
        h = h + jnp.asarray(bias)
    y, mean, inv = _ln_affine(h, gamma, beta, ctx.attr("epsilon", 1e-5))
    if ctx.n_outputs > 1:
        return (y, mean, inv, h)[: ctx.n_outputs]
    return y


@op("EmbedLayerNormalization")
def _embed_layer_norm(ctx, input_ids, segment_ids=None, word_emb=None,
                      pos_emb=None, seg_emb=None, gamma=None, beta=None,
                      mask=None, position_ids=None):
    """com.microsoft EmbedLayerNormalization: the BERT front-end fusion
    (word + position + segment gather, layernorm, mask length)."""
    ids = jnp.asarray(input_ids).astype(jnp.int32)
    b, s = ids.shape
    emb = jnp.asarray(word_emb)[ids]
    if position_ids is not None:
        pos = jnp.asarray(position_ids).astype(jnp.int32)
        pos = jnp.broadcast_to(pos.reshape(-1, s), (b, s))
    else:
        pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    emb = emb + jnp.asarray(pos_emb)[pos]
    if seg_emb is not None:
        if segment_ids is None:
            raise ValueError(
                "EmbedLayerNormalization has a segment embedding but no "
                "segment_ids input")
        emb = emb + jnp.asarray(seg_emb)[
            jnp.asarray(segment_ids).astype(jnp.int32)]
    y, _, _ = _ln_affine(emb, gamma, beta, ctx.attr("epsilon", 1e-12))
    if mask is not None:
        mask_index = jnp.sum(
            jnp.asarray(mask).astype(jnp.int32), axis=1)
    else:
        mask_index = jnp.zeros((b,), jnp.int32)
    if ctx.n_outputs > 2:
        return y, mask_index, emb
    return y, mask_index


def _standard_attention(ctx, q, k, v, attn_mask=None, past_key=None,
                        past_value=None):
    """Standard ai.onnx Attention (opset 23): scaled dot-product
    attention over separate Q/K/V, 3-D ([B, S, N*D] + q/kv_num_heads
    attrs — torch's opset-23 exporter shape) or 4-D ([B, N, S, D]).
    Grouped-query head counts, is_causal (top-left alignment, the
    spec's tril), additive or boolean masks, scale and softcap are
    lowered; KV cache inputs/outputs and the qk_matmul_output modes
    are rejected loudly."""
    if k is None or v is None:
        raise NotImplementedError(
            "standard Attention needs Q, K and V inputs")
    if past_key is not None or past_value is not None:
        raise NotImplementedError(
            "standard Attention with past_key/past_value (KV cache) is "
            "not supported; re-export the decode step with explicit "
            "Concat of the cache, or use the com.microsoft "
            "GroupQueryAttention form")
    if ctx.n_outputs > 1:
        raise NotImplementedError(
            "standard Attention present_key/present_value (or "
            "qk_matmul_output) outputs are not supported")
    if int(ctx.attr("qk_matmul_output_mode", 0)) != 0:
        raise NotImplementedError(
            "Attention qk_matmul_output_mode != 0 (exposing the raw "
            "QK product) is not supported")
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    three_d = q.ndim == 3
    if three_d:
        nq = int(ctx.attr("q_num_heads", 0))
        nk = int(ctx.attr("kv_num_heads", 0))
        if nq <= 0 or nk <= 0:
            raise ValueError(
                "3-D standard Attention needs q_num_heads/kv_num_heads")
        b, s, dq = q.shape
        q = q.reshape(b, s, nq, dq // nq).transpose(0, 2, 1, 3)
        k = k.reshape(k.shape[0], k.shape[1], nk,
                      k.shape[2] // nk).transpose(0, 2, 1, 3)
        v = v.reshape(v.shape[0], v.shape[1], nk,
                      v.shape[2] // nk).transpose(0, 2, 1, 3)
    b, nq, s, head = q.shape
    nk, t_kv = k.shape[1], k.shape[2]
    dv = v.shape[-1]  # the spec allows V's head size to differ from QK's
    if nq % nk:
        raise ValueError(
            f"Attention q heads {nq} not a multiple of kv heads {nk}")
    group = nq // nk
    dt = q.dtype
    qg = q.reshape(b, nk, group, s, head).astype(jnp.float32)
    scale = ctx.attr("scale", 0.0) or 1.0 / math.sqrt(head)
    logits = jnp.einsum("bkgsd,bktd->bkgst", qg,
                        k.astype(jnp.float32)) * scale
    bool_mask = None
    if attn_mask is not None:
        m = jnp.asarray(attn_mask)
        # right-align onto [B, N, S, T] then add the group axis
        m4 = m.reshape((1,) * (4 - m.ndim) + m.shape)
        if m4.shape[1] == 1:        # broadcast over heads
            m5 = m4[:, :, None]
        else:                       # per-q-head mask: split (nk, group)
            m5 = m4.reshape(m4.shape[0], nk, group,
                            m4.shape[2], m4.shape[3])
        if m.dtype == jnp.bool_ or m.dtype == np.bool_:
            bool_mask = m5
        else:
            # additive float mask ADDS BEFORE softcap (the spec's
            # Add -> softcap -> Softmax node order)
            logits = logits + m5.astype(jnp.float32)
    softcap = float(ctx.attr("softcap", 0.0))
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    # hard masking applies AFTER softcap: folding a -inf into the tanh
    # would collapse it to -softcap and silently unmask the position
    if bool_mask is not None:
        logits = jnp.where(bool_mask, logits, -jnp.inf)
    if bool(ctx.attr("is_causal", 0)):
        # top-left alignment: query i attends keys j <= i (the spec's
        # tril(ones(S, T)) and torch SDPA's is_causal)
        causal = jnp.arange(t_kv)[None, :] <= jnp.arange(s)[:, None]
        logits = jnp.where(causal[None, None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)  # fully-masked rows
    out = jnp.einsum("bkgst,bktd->bkgsd", probs, v.astype(jnp.float32))
    out = out.reshape(b, nq, s, dv).astype(dt)
    if three_d:
        out = out.transpose(0, 2, 1, 3).reshape(b, s, nq * dv)
    return out


@op("MultiHeadAttention")
def _multi_head_attention(ctx, query, key=None, value=None, bias=None,
                          key_padding_mask=None, attention_bias=None,
                          past_key=None, past_value=None):
    """com.microsoft MultiHeadAttention — the post-projection attention
    fusion newer ORT transformer-optimizer versions emit (cross- and
    self-attention with already-projected Q/K/V). Supported surface:
    3-D [B, S, N*D] Q/K/V (+ num_heads attr), combined QKV bias (split
    at the actual q/k/v widths — v_hidden_size may differ), [B] or
    [B, T_kv] key padding masks, additive attention_bias, past/present
    KV cache. The 5-D packed-QKV and 4-D past-format K/V layouts are
    rejected loudly. The projection-fused form is `Attention`; the
    standard-domain form is `_standard_attention` — three ops, one
    einsum chain each."""
    num_heads = int(ctx.attr("num_heads", 0))
    if num_heads <= 0:
        raise ValueError("MultiHeadAttention needs num_heads")
    q = jnp.asarray(query)
    if q.ndim != 3:
        raise NotImplementedError(
            "MultiHeadAttention supports 3-D [B, S, N*D] inputs; the "
            "5-D packed-QKV form is not supported — re-export unpacked")
    b, s, _ = q.shape
    if key is None or (hasattr(key, "size") and np.size(key) == 0):
        raise NotImplementedError(
            "MultiHeadAttention needs separate 3-D key/value inputs "
            "(the packed-QKV layout is 5-D and unsupported — re-export "
            "unpacked)")
    k, v = jnp.asarray(key), jnp.asarray(value)
    if k.ndim != 3:
        raise NotImplementedError(
            "MultiHeadAttention past-format (4-D) K/V inputs are "
            "not supported; re-export with 3-D K/V + past_key/"
            "past_value cache inputs")
    if bias is not None:
        # ORT layout: (q_hidden | k_hidden | v_hidden) — v may differ
        bias = jnp.asarray(bias)
        bq, bk, bv = jnp.split(
            bias, [q.shape[-1], q.shape[-1] + k.shape[-1]])
        q, k, v = q + bq, k + bk, v + bv
    head = q.shape[-1] // num_heads

    def heads(t):
        return t.reshape(t.shape[0], t.shape[1], num_heads,
                         -1).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)          # [B, N, S|T, D]
    if past_key is not None:
        k = jnp.concatenate([jnp.asarray(past_key, k.dtype), k], axis=2)
        v = jnp.concatenate([jnp.asarray(past_value, v.dtype), v],
                            axis=2)
    present_k, present_v = k, v
    t_kv = k.shape[2]
    scale = ctx.attr("scale", 0.0) or 1.0 / math.sqrt(head)
    logits = jnp.einsum("bnsd,bntd->bnst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if attention_bias is not None:
        ab = jnp.asarray(attention_bias, jnp.float32)
        logits = logits + ab.reshape((1,) * (4 - ab.ndim) + ab.shape)
    neg = jnp.float32(ctx.attr("mask_filter_value", -10000.0))
    if key_padding_mask is not None:
        m = jnp.asarray(key_padding_mask)
        if m.ndim == 1:                             # [B] valid lengths
            ok = jnp.arange(t_kv)[None, :] < m.astype(jnp.int32)[:, None]
        elif m.ndim == 2:                           # [B, T_kv] 0/1
            ok = m != 0
        else:
            raise NotImplementedError(
                "MultiHeadAttention key_padding_mask must be [B] "
                "lengths or a [B, T_kv] 0/1 mask")
        logits = logits + jnp.where(ok[:, None, None, :], 0.0, neg)
    if bool(ctx.attr("unidirectional", 0)):
        q_pos = (t_kv - s) + jnp.arange(s)[:, None]
        causal = jnp.arange(t_kv)[None, :] <= q_pos
        logits = logits + jnp.where(causal[None, None], 0.0, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnst,bntd->bnsd", probs, v.astype(jnp.float32))
    out = out.transpose(0, 2, 1, 3).reshape(b, s, -1).astype(
        jnp.asarray(query).dtype)
    if ctx.n_outputs > 1:
        return out, present_k, present_v
    return out


@op("Attention")
def _contrib_attention(ctx, x, weights, bias=None, mask_index=None,
                       past=None, attention_bias=None,
                       past_sequence_length=None):
    """com.microsoft Attention: the fused BERT self-attention block
    (input projection + multi-head SDPA). Supported surface: equal
    Q/K/V hidden sizes, raw [B] lengths or [B, T] / broadcastable 0/1
    key masks, additive attention_bias, unidirectional (causal) mode,
    and the stacked [2, B, N, P, D] past/present KV cache. Asymmetric
    qkv_hidden_sizes and packed-KV pasts are rejected loudly."""
    if weights is None or np.ndim(weights) != 2:
        # the standard ai.onnx opset-23 Attention shares this op_type
        # but not this signature: its first three inputs are separate
        # Q/K/V tensors (3-D or 4-D), not (input, [H,3H] weights)
        return _standard_attention(ctx, x, weights, bias, mask_index,
                                   past, attention_bias)
    num_heads = int(ctx.attr("num_heads", 0))
    if num_heads <= 0:
        raise ValueError("Attention needs the num_heads attribute")
    sizes = ctx.attr("qkv_hidden_sizes")
    if sizes and len(set(int(v) for v in sizes)) != 1:
        raise NotImplementedError(
            "Attention with asymmetric qkv_hidden_sizes is not "
            "supported; re-export with equal Q/K/V widths")
    if int(ctx.attr("past_present_share_buffer", 0)) \
            or past_sequence_length is not None:
        raise NotImplementedError(
            "Attention with past_present_share_buffer (max-length cache "
            "buffer + past_sequence_length) is not supported: the cached "
            "length would be read from the buffer dimension and attend "
            "uninitialized rows; re-export with a dense (unshared) past")
    x = jnp.asarray(x)
    b, s, _ = x.shape
    w = jnp.asarray(weights)
    hidden = w.shape[1] // 3
    head = hidden // num_heads
    qkv = jnp.matmul(x, w)
    if bias is not None:
        qkv = qkv + jnp.asarray(bias)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, head).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)          # [B, N, S, D]
    past_len = 0
    if past is not None:
        p = jnp.asarray(past)                       # [2, B, N, P, D]
        past_len = p.shape[3]
        k = jnp.concatenate([p[0].astype(k.dtype), k], axis=2)
        v = jnp.concatenate([p[1].astype(v.dtype), v], axis=2)
    t_kv = k.shape[2]
    scale = ctx.attr("scale", 0.0) or 1.0 / math.sqrt(head)
    logits = jnp.einsum("bnsd,bntd->bnst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if attention_bias is not None:
        logits = logits + jnp.asarray(attention_bias, jnp.float32)
    # ORT masking is ADDITIVE: masked logits get logit + filter value
    # (default -1e4), which preserves relative order — load-bearing for
    # exporters that tune a small mask_filter_value (soft masking)
    neg = jnp.float32(ctx.attr("mask_filter_value", -10000.0))
    if mask_index is not None:
        m = jnp.asarray(mask_index)
        if m.ndim == 1 and m.shape[0] != b:
            raise NotImplementedError(
                f"Attention 1-D mask_index has {m.shape[0]} entries for "
                f"batch {b}: the (2*batch,) end/start left-padding format "
                "is not supported; re-export with a [batch] lengths "
                "vector or a [batch, seq] key mask")
        if m.ndim == 1:                             # [B] valid-key lengths
            key_ok = jnp.arange(t_kv)[None, :] < m.astype(
                jnp.int32)[:, None]
            logits = logits + jnp.where(
                key_ok[:, None, None, :], 0.0, neg)
        else:                                       # 0/1 key mask
            # right-align onto [B, N, S, T]: [B,T] -> [B,1,1,T],
            # [B,S,T] -> [B,1,S,T], 4-D passes through
            m2 = m.reshape((b,) + (1,) * (4 - m.ndim) + m.shape[1:])
            logits = logits + jnp.where(m2 != 0, 0.0, neg)
    if bool(ctx.attr("unidirectional", 0)):
        q_pos = past_len + jnp.arange(s)[:, None]
        causal = jnp.arange(t_kv)[None, :] <= q_pos
        logits = logits + jnp.where(causal[None, None], 0.0, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnst,bntd->bnsd", probs, v.astype(jnp.float32))
    out = out.transpose(0, 2, 1, 3).reshape(b, s, hidden).astype(x.dtype)
    if ctx.n_outputs > 1:
        return out, jnp.stack([k, v], axis=0)
    return out


# ---------------------------------------------------------------------------
# Detection ops (SSD / YOLO / Faster-RCNN export families)
# ---------------------------------------------------------------------------

def _nms_iou_corners(boxes, center_point_box):
    """[N, 4] -> (y1, x1, y2, x2) normalized corners + areas, per ONNX
    NMS conventions (corner coords may arrive in either diagonal order;
    center format is [x_c, y_c, w, h])."""
    xp = jnp if not _is_host(boxes) else np
    if center_point_box:
        xc, yc, w, h = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
        y1, y2 = yc - h / 2, yc + h / 2
        x1, x2 = xc - w / 2, xc + w / 2
    else:
        y1 = xp.minimum(boxes[:, 0], boxes[:, 2])
        y2 = xp.maximum(boxes[:, 0], boxes[:, 2])
        x1 = xp.minimum(boxes[:, 1], boxes[:, 3])
        x2 = xp.maximum(boxes[:, 1], boxes[:, 3])
    area = (y2 - y1) * (x2 - x1)
    return y1, x1, y2, x2, area


def _nms_host(boxes, scores, max_out, iou_th, score_th, center):
    """Exact ONNX semantics on host data: [num_selected, 3] int64 rows of
    (batch, class, box), per-class score-descending selection order."""
    nb, nc, n = scores.shape
    rows = []
    for bi in range(nb):
        y1, x1, y2, x2, area = _nms_iou_corners(boxes[bi], center)
        for ci in range(nc):
            s = scores[bi, ci]
            cand = np.argsort(-s, kind="stable")
            if score_th is not None:
                cand = cand[s[cand] > score_th]
            chosen: List[int] = []
            for i in cand:
                if len(chosen) >= max_out:
                    break
                ok = True
                for j in chosen:
                    yy1 = max(y1[i], y1[j]); xx1 = max(x1[i], x1[j])
                    yy2 = min(y2[i], y2[j]); xx2 = min(x2[i], x2[j])
                    inter = max(0.0, yy2 - yy1) * max(0.0, xx2 - xx1)
                    union = area[i] + area[j] - inter
                    if union > 0 and inter / union > iou_th:
                        ok = False
                        break
                if ok:
                    chosen.append(int(i))
            rows.extend([bi, ci, i] for i in chosen)
    return (np.asarray(rows, np.int64).reshape(-1, 3) if rows
            else np.zeros((0, 3), np.int64))


@op("NonMaxSuppression")
def _non_max_suppression(ctx, boxes, scores, max_out=None, iou_th=None,
                         score_th=None):
    """ONNX NMS (ref ONNXModel.scala:173-193 — the reference scores every
    ORT-runnable detection export). Host inputs get the exact
    data-dependent [num_selected, 3] result. Traced inputs get the
    TPU-native fixed-capacity formulation: XLA cannot emit data-dependent
    shapes, so the result is [num_batches*num_classes*max_out, 3] in the
    same (batch, class, score-descending) order with unused slots as
    [-1, -1, -1] rows — consumers mask/compact on the first column.
    The selection itself is a lax.scan of argmax+IoU-suppression steps
    vmapped over (batch, class): O(max_out * N) vector work, no
    per-box host loop."""
    center = ctx.attr("center_point_box", 0)
    n_max = 0 if max_out is None else int(np.asarray(max_out).reshape(()))
    iou = 0.0 if iou_th is None else float(np.asarray(iou_th).reshape(()))
    sth = (None if score_th is None
           else float(np.asarray(score_th).reshape(())))
    if n_max <= 0:
        return np.zeros((0, 3), np.int64)
    if _all_host((boxes, scores)):
        return _nms_host(np.asarray(boxes, np.float32),
                         np.asarray(scores, np.float32),
                         n_max, iou, sth, center)

    boxes = jnp.asarray(boxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    nb, nc, n = scores.shape

    def one_class(box_b, s):
        y1, x1, y2, x2, area = _nms_iou_corners(box_b, center)
        alive0 = (s > sth) if sth is not None else jnp.ones(n, bool)

        def step(alive, _):
            cand = jnp.where(alive, s, -jnp.inf)
            i = jnp.argmax(cand)
            valid = cand[i] > -jnp.inf
            yy1 = jnp.maximum(y1, y1[i]); xx1 = jnp.maximum(x1, x1[i])
            yy2 = jnp.minimum(y2, y2[i]); xx2 = jnp.minimum(x2, x2[i])
            inter = (jnp.maximum(yy2 - yy1, 0.0)
                     * jnp.maximum(xx2 - xx1, 0.0))
            union = area + area[i] - inter
            sup = (inter > iou * union) & (union > 0)
            alive = alive & ~sup & (jnp.arange(n) != i)
            return jnp.where(valid, alive, jnp.zeros_like(alive)), \
                jnp.where(valid, i, -1).astype(jnp.int64)

        _, sel = lax.scan(step, alive0, None, length=n_max)
        return sel                                        # [n_max]

    sel = jax.vmap(lambda bb, sb: jax.vmap(
        lambda sc: one_class(bb, sc))(sb))(boxes, scores)  # [B, C, n_max]
    bi = jnp.broadcast_to(jnp.arange(nb, dtype=jnp.int64)[:, None, None],
                          sel.shape)
    ci = jnp.broadcast_to(jnp.arange(nc, dtype=jnp.int64)[None, :, None],
                          sel.shape)
    out = jnp.stack([bi, ci, sel], axis=-1).reshape(-1, 3)
    invalid = out[:, 2] < 0
    return jnp.where(invalid[:, None], jnp.int64(-1), out)


@op("RoiAlign")
def _roi_align(ctx, x, rois, batch_indices):
    """ONNX RoiAlign: bilinear-sampled pooling of roi bins over a
    [N, C, H, W] feature map -> [num_rois, C, oh, ow] (the Faster-RCNN
    head op). Gather-based bilinear sampling vmapped over rois — every
    shape static, so XLA tiles the [C, samples] contractions.

    ``sampling_ratio=0`` (adaptive per-roi grid) is data-dependent under
    jit and rejected with a recipe; real detectron/torchvision exports
    set it explicitly (usually 2)."""
    mode = ctx.attr("mode", "avg")
    oh, ow = ctx.attr("output_height", 1), ctx.attr("output_width", 1)
    sr = int(ctx.attr("sampling_ratio", 0))
    scale = ctx.attr("spatial_scale", 1.0)
    ctm = ctx.attr("coordinate_transformation_mode",
                   "half_pixel" if ctx.opset >= 16 else "output_half_pixel")
    if sr <= 0:
        raise NotImplementedError(
            "RoiAlign with sampling_ratio=0 sizes its sampling grid from "
            "roi extents (data-dependent shapes); re-export with an "
            "explicit sampling_ratio (torchvision/detectron2 use 2)")
    x = jnp.asarray(x, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    bidx = jnp.asarray(batch_indices).astype(jnp.int32)
    H, W = x.shape[2], x.shape[3]
    off = 0.5 if ctm == "half_pixel" else 0.0

    def one_roi(roi, bi):
        x1 = roi[0] * scale - off
        y1 = roi[1] * scale - off
        x2 = roi[2] * scale - off
        y2 = roi[3] * scale - off
        rw, rh = x2 - x1, y2 - y1
        if ctm != "half_pixel":  # legacy mode clamps tiny rois to 1px
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w, bin_h = rw / ow, rh / oh
        # sample grid: sr x sr points per bin, evenly inset
        gy = (y1 + (jnp.arange(oh)[:, None] + (jnp.arange(sr) + 0.5)
                    / sr) * bin_h).reshape(-1)              # [oh*sr]
        gx = (x1 + (jnp.arange(ow)[:, None] + (jnp.arange(sr) + 0.5)
                    / sr) * bin_w).reshape(-1)              # [ow*sr]

        def axis_weights(g, size):
            outside = (g < -1.0) | (g > size)
            gc = jnp.clip(g, 0.0, size - 1)
            lo = jnp.floor(gc).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, size - 1)
            frac = gc - lo
            return lo, hi, frac, outside

        ylo, yhi, fy, oy = axis_weights(gy, H)
        xlo, xhi, fx, ox = axis_weights(gx, W)
        fmap = x[bi]                                        # [C, H, W]
        # bilinear = lerp along y of lerps along x, via 4 gathers
        def g2(yy, xx):
            return fmap[:, yy][:, :, xx]                    # [C, oh*sr, ow*sr]
        top = g2(ylo, xlo) * (1 - fx) + g2(ylo, xhi) * fx
        bot = g2(yhi, xlo) * (1 - fx) + g2(yhi, xhi) * fx
        val = top * (1 - fy)[None, :, None] + bot * fy[None, :, None]
        val = jnp.where(oy[None, :, None] | ox[None, None, :], 0.0, val)
        val = val.reshape(-1, oh, sr, ow, sr)
        if mode == "max":
            return val.max(axis=(2, 4))
        return val.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois, bidx)                    # [R, C, oh, ow]


@op("MaxRoiPool")
def _max_roi_pool(ctx, x, rois):
    """MaxRoiPool (the Caffe/Fast-RCNN ROIPooling): hard-quantized roi
    bins, max-pooled. Rectangular bins make the 2-D max separable, so
    the lowering is two masked per-axis maxes (no [R,C,ph,pw,H,W]
    blow-up); empty bins emit 0 as the Caffe semantics require."""
    ph, pw = [int(v) for v in ctx.attr("pooled_shape")]
    scale = float(ctx.attr("spatial_scale", 1.0))
    x = jnp.asarray(x, jnp.float32)
    rois = jnp.asarray(rois, jnp.float32)
    H, W = x.shape[2], x.shape[3]
    bidx = jnp.round(rois[:, 0]).astype(jnp.int32)
    x1 = jnp.round(rois[:, 1] * scale)
    y1 = jnp.round(rois[:, 2] * scale)
    x2 = jnp.round(rois[:, 3] * scale)
    y2 = jnp.round(rois[:, 4] * scale)
    roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)

    def axis_masks(lo, extent, n_bins, size):
        # [R, n_bins, size] membership of each pixel in each quantized bin
        b = jnp.arange(n_bins, dtype=jnp.float32)
        bin_sz = (extent / n_bins)[:, None]
        start = jnp.clip(jnp.floor(b[None, :] * bin_sz) + lo[:, None],
                         0, size)
        end = jnp.clip(jnp.ceil((b[None, :] + 1) * bin_sz) + lo[:, None],
                       0, size)
        pix = jnp.arange(size, dtype=jnp.float32)
        return ((pix[None, None, :] >= start[..., None])
                & (pix[None, None, :] < end[..., None]))

    mh = axis_masks(y1, roi_h, ph, H)                       # [R, ph, H]
    mw = axis_masks(x1, roi_w, pw, W)                       # [R, pw, W]
    fmap = x[bidx]                                          # [R, C, H, W]
    t = jnp.where(mh[:, None, :, :, None], fmap[:, :, None, :, :],
                  -jnp.inf).max(axis=3)                     # [R, C, ph, W]
    out = jnp.where(mw[:, None, None, :, :], t[:, :, :, None, :],
                    -jnp.inf).max(axis=4)                   # [R, C, ph, pw]
    return jnp.where(jnp.isneginf(out), 0.0, out)


# ---------------------------------------------------------------------------
# Graph import
# ---------------------------------------------------------------------------

class ImportedGraph:
    """An ONNX graph lowered to a pure jax function.

    ``params`` is the initializer pytree (host numpy until first use);
    ``apply(params, *inputs)`` is jit-compatible and returns outputs in
    graph-output order.
    """

    def __init__(self, graph: Msg, opset: int, optimize: bool = False):
        if optimize:
            from synapseml_tpu.onnx.optimize import optimize_graph

            graph = optimize_graph(graph, opset)
        self.graph = graph
        self.opset = opset
        all_inits = {t.name: tensor_to_numpy(t) for t in graph.initializer}
        # Shape-consuming initializers (Reshape targets, Slice starts,
        # Resize scales, masks...) must stay STATIC: when params ride as
        # jit arguments (BatchedExecutor bound_args) a traced shape tensor
        # breaks those ops at trace time. Static = every non-float
        # initializer, plus any initializer (float included — Resize
        # scales/roi) feeding a shape-position input slot. Float weights
        # stay in the donated/castable params pytree.
        shape_consumers = {
            "Reshape": (1,), "Expand": (1,), "Tile": (1,),
            "Slice": (1, 2, 3, 4), "Resize": (1, 2, 3), "Upsample": (1,),
            "ConstantOfShape": (0,), "Range": (0, 1, 2), "TopK": (1,),
            "OneHot": (1,), "Pad": (1, 2, 3), "Unsqueeze": (1,),
            "Squeeze": (1,), "Split": (1,), "Trilu": (1,),
            "ReduceSum": (1,), "ReduceMean": (1,), "ReduceMax": (1,),
            "ReduceMin": (1,), "ReduceProd": (1,), "CenterCropPad": (1,),
            "ReduceSumSquare": (1,), "ReduceL1": (1,), "ReduceL2": (1,),
            "ReduceLogSum": (1,), "ReduceLogSumExp": (1,),
            # every MelWeightMatrix input is filterbank GEOMETRY (incl.
            # the float hz edges); STFT's step/length are frame geometry
            "MelWeightMatrix": (0, 1, 2, 3, 4), "STFT": (1, 3),
            "HannWindow": (0,), "HammingWindow": (0,),
            "BlackmanWindow": (0,), "MaxUnpool": (2,),
            # NMS capacity + thresholds select the compiled program's
            # shape/constants (incl. the float iou/score thresholds)
            "NonMaxSuppression": (2, 3, 4),
            "DFT": (1, 2), "Col2Im": (1, 2), "AffineGrid": (1,),
            # host-only data-dependent ops: their float data must not
            # ride the jit params pytree as tracers
            "Unique": (0,), "Compress": (0, 1),
        }
        # ...while packed-integer WEIGHT slots must stay in the donated
        # params pytree even though they are non-float: a quantized LLM's
        # MatMulNBits B matrices are the model's dominant bytes, and
        # baking them in as XLA constants would bloat the program and
        # defeat device-resident weights/donation for exactly that case
        weight_consumers = {"MatMulNBits": (1, 3)}
        shape_fed = set()
        weight_fed = set()
        for node in graph.node:
            for target, slots in ((shape_fed,
                                   shape_consumers.get(node.op_type)),
                                  (weight_fed,
                                   weight_consumers.get(node.op_type))):
                for i in slots or ():
                    if i < len(node.input) and node.input[i]:
                        target.add(node.input[i])
        weight_fed -= shape_fed  # shape use wins: it needs host values
        self.static_params: Dict[str, np.ndarray] = {
            k: v for k, v in all_inits.items()
            if (not np.issubdtype(v.dtype, np.floating) or k in shape_fed)
            and k not in weight_fed
        }
        self.params: Dict[str, np.ndarray] = {
            k: v for k, v in all_inits.items() if k not in self.static_params
        }
        init_names = set(all_inits)
        self.input_names: List[str] = [
            vi.name for vi in graph.input if vi.name not in init_names
        ]
        self.output_names: List[str] = [vi.name for vi in graph.output]
        self.input_info: Dict[str, Tuple[Optional[Any], List[Optional[int]]]] = {}
        for vi in graph.input:
            if vi.name in init_names or vi.type is None or vi.type.tensor_type is None:
                continue
            tt = vi.type.tensor_type
            dtype = proto.TENSOR_DTYPES.get(int(tt.elem_type or 0))
            shape: List[Optional[int]] = []
            if tt.shape is not None:
                for d in tt.shape.dim:
                    shape.append(int(d.dim_value) if d.dim_value else None)
            self.input_info[vi.name] = (dtype, shape)
        # pre-extract node metadata so apply() does no proto work per trace
        self._nodes = _lower_nodes(graph.node, opset)

    def apply(self, params: Dict[str, Any], *inputs, **named_inputs):
        """Run the graph. Inputs positional (graph order) or by name."""
        env: Dict[str, Any] = dict(self.static_params)
        env.update(params)
        for name, val in zip(self.input_names, inputs):
            env[name] = val
        env.update(named_inputs)
        missing = [n for n in self.input_names if n not in env]
        if missing:
            raise ValueError(f"missing graph inputs: {missing}")
        _run_nodes(self._nodes, env)
        return tuple(env[n] for n in self.output_names)

    def bind(self, cast_dtype=None):
        """Return ``fn(*inputs)`` with params closed over (optionally cast)."""
        params = self.params
        if cast_dtype is not None:
            params = {
                k: (v.astype(cast_dtype)
                    if isinstance(v, np.ndarray) and np.issubdtype(v.dtype, np.floating)
                    else v)
                for k, v in params.items()
            }

        def fn(*inputs):
            return self.apply(params, *inputs)
        return fn

    def param_bytes(self) -> int:
        return (sum(v.nbytes for v in self.params.values())
                + sum(v.nbytes for v in self.static_params.values()))

    def truncated(self, cut_layers: int = 1) -> "ImportedGraph":
        """Headless copy with the last ``cut_layers`` nodes removed — the
        transfer-learning hook (ref: deep-learning/.../cntk/ImageFeaturizer.scala:100
        ``cutOutputLayers``). The new graph's output is the last surviving
        node's first output; unused initializers are dropped."""
        if not 0 <= cut_layers < len(self._nodes):
            raise ValueError(f"cut_layers={cut_layers} out of range "
                             f"(graph has {len(self._nodes)} nodes)")
        out = ImportedGraph.__new__(ImportedGraph)
        out.graph = self.graph
        out.opset = self.opset
        out._nodes = self._nodes[: len(self._nodes) - cut_layers]
        out.input_names = list(self.input_names)
        out.input_info = dict(self.input_info)
        out.output_names = [out._nodes[-1][3][0]] if cut_layers else list(self.output_names)
        used = set()
        for _, ctx, in_names, _ in out._nodes:
            used.update(in_names)
            # If/Loop subgraphs capture outer names beyond their node's
            # declared inputs — dropping those params breaks apply()
            for sub in _subgraphs_of(ctx):
                used |= sub.captured_names()
        out.params = {k: v for k, v in self.params.items() if k in used}
        out.static_params = {
            k: v for k, v in self.static_params.items() if k in used
        }
        return out

    def __repr__(self):
        return (f"ImportedGraph(inputs={self.input_names}, "
                f"outputs={self.output_names}, nodes={len(self._nodes)}, "
                f"params={len(self.params)}, opset={self.opset})")


def import_model(path_or_bytes, optimize: bool = False,
                 base_dir: Optional[str] = None) -> ImportedGraph:
    """Parse a ``.onnx`` file/bytes and lower it to an :class:`ImportedGraph`.

    ``optimize`` applies proto-level graph rewrites (parallel-MatMul/QKV
    packing — see :mod:`synapseml_tpu.onnx.optimize`) before lowering.
    Off by default: on v5e, XLA schedules the unpacked projections as
    well or better (docs/perf.md measures packing at -8% on BERT-base
    bs=128); the pass exists for exporters/backends where it wins.

    Models saved with external data (``save_as_external_data`` — the
    default for >2GB exports) resolve their sidecar files relative to the
    model's directory; pass ``base_dir`` when supplying raw bytes."""
    model = proto.load_model(path_or_bytes, base_dir=base_dir)
    if model.graph is None:
        raise ValueError("ONNX model has no graph")
    opset = 13
    for osi in model.opset_import:
        if not osi.domain:  # default ai.onnx domain
            opset = int(osi.version or opset)
    return ImportedGraph(model.graph, opset, optimize=optimize)


def supported_ops() -> List[str]:
    return sorted(_REGISTRY)


# ai.onnx.ml domain ops register themselves on import (bottom import keeps
# the circular edge harmless: everything ml_ops needs is defined above)
from synapseml_tpu.onnx import ml_ops  # noqa: E402,F401
