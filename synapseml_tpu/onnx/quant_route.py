"""Measured routing for the true-int8 QOperator execution lane.

The QOperator family (``QLinearConv`` / ``QLinearMatMul`` /
``ConvInteger`` / ``MatMulInteger``) historically widened its operands
to int32 before the dot/conv — correct, but the MXU never saw the
integers natively. Round 15 adds a TRUE int8 lane
(importer._matmul_int8_core / _conv_int8_core): operands stay int8
into ``dot_general`` / ``conv_general_dilated`` with
``preferred_element_type=int32``, zero points handled as exact integer
correction terms AFTER the contraction (row/column sums for matmul, a
ones-conv term for conv), so the accumulator is bit-identical to the
widened path and the existing integer requantization applies
unchanged.

This module is the ``cached_hist_route``-style prober in front of it:
on first sight of an (op kind, dtypes, zero-point structure, bucketed
shape) class on a TPU backend, compile BOTH lanes, verify the int8
accumulator matches the widened reference EXACTLY, time both, persist
the winner. Any mismatch, failure, or timing regression silently lands
the "dequant" verdict (the widened fallback path — which itself
degrades to dequantize-to-f32 semantics for the non-contraction
QLinear ops). ``SYNAPSEML_ONNX_INT8=0`` kills the lane. Decisions are
counted in ``onnx_int8_route_total{backend=}``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.runtime import autotune
from synapseml_tpu.runtime.proberoute import RouteTable
from synapseml_tpu.runtime.proberoute import best_of as _best_of

_TABLE = RouteTable("onnx_int8_routing.json")

# probe shape clamps: verify+time at a bounded stand-in for the real
# shape class (same dtypes/zp structure) so a first sight of a huge
# conv does not pay a huge probe
_PROBE_ROWS_CAP = 256
_PROBE_SPATIAL_CAP = 32
_PROBE_BATCH_CAP = 2


def enabled() -> bool:
    import os

    return os.environ.get("SYNAPSEML_ONNX_INT8", "1") != "0"


def _count(backend: str) -> None:
    try:
        from synapseml_tpu.runtime import telemetry

        telemetry.counter("onnx_int8_route_total",
                          backend=backend).inc()
    except Exception:  # noqa: BLE001 - telemetry must never gate scoring
        pass


def concrete_zero(zp) -> bool:
    """True when ``zp`` is absent or a CONCRETE all-zero array — the
    eligibility test the conv lane's weight zero point needs (a traced
    zp can't be inspected; route to the widened path)."""
    if zp is None:
        return True
    try:
        return not np.any(np.asarray(zp))
    except Exception:  # noqa: BLE001 - tracer: value unknowable
        return False


def _zp_tag(zp) -> str:
    if zp is None:
        return "none"
    nd = getattr(zp, "ndim", 0)
    return f"v{nd}" if nd else "s"


def _bucket(v: int, lo: int = 1, hi: int = 65536) -> int:
    return 1 << (int(min(max(v, lo), hi)) - 1).bit_length()


def _key(kind: str, parts) -> str:
    knd = jax.devices()[0].device_kind
    import synapseml_tpu as _pkg

    pkg_v = getattr(_pkg, "__version__", "0")
    return (f"q1|jax{jax.__version__}|pkg{pkg_v}|{knd}|{kind}|"
            + "|".join(str(p) for p in parts))


def count(backend: str) -> None:
    """Count one served decision in onnx_int8_route_total — the op
    dispatchers route with ``count=False`` and report the lane whose
    ops actually landed in the traced program AFTER the int8 leg's
    trace-time outcome is known (a leg that raises at trace time is
    served by the widened path and must count dequant)."""
    _count(backend)


def _matmul_parts_p(a_dt, b_dt, a_zp, b_zp, n: int, k: int, m: int):
    """Key parts from primitives — the same tuple route args carry to
    the probe, so one lane rargs list serves key_fn AND probe_hook."""
    return (str(a_dt), str(b_dt), _zp_tag(a_zp), _zp_tag(b_zp),
            f"n{_bucket(n)}", f"k{_bucket(k)}", f"m{_bucket(m)}")


def _matmul_parts(a, b, a_zp, b_zp):
    n, k = a.shape
    return _matmul_parts_p(a.dtype, b.dtype, a_zp, b_zp,
                           n, k, b.shape[1])


def _conv_parts_p(x_dt, x_zp, x_shape, w_shape, attrs: str):
    spatial = "x".join(str(_bucket(s, hi=4096)) for s in x_shape[2:])
    return (str(x_dt), _zp_tag(x_zp), f"b{_bucket(x_shape[0])}",
            f"ci{x_shape[1]}", f"co{w_shape[0]}",
            "k" + "x".join(str(s) for s in w_shape[2:]),
            f"s{spatial}", attrs)


def _conv_parts(x, w, x_zp, attrs: str):
    return _conv_parts_p(x.dtype, x_zp, tuple(x.shape),
                         tuple(w.shape), attrs)


def route_matmul(a, b, a_zp, b_zp, do_count: bool = True) -> str:
    """Route one MatMulInteger/QLinearMatMul contraction. Eligibility:
    2-D x 2-D, int8/uint8 operands (uint8 rides an exact -128 shift)."""
    if not (a.ndim == 2 and b.ndim == 2
            and a.dtype in (jnp.int8, jnp.uint8)
            and b.dtype in (jnp.int8, jnp.uint8)):
        if do_count:
            _count("dequant")
        return "dequant"
    backend = "dequant"
    if enabled() and jax.default_backend() == "tpu":
        n, k = a.shape
        backend = _MM_LANE.route(a.dtype, b.dtype, a_zp, b_zp,
                                 n, k, b.shape[1])
    if do_count:
        _count(backend)
    return backend


def route_conv(x, w, x_zp, w_zp, attrs: str,
               do_count: bool = True) -> str:
    """Route one ConvInteger/QLinearConv. Eligibility: int8/uint8
    activations, int8 weights with a zero (or absent) weight zero
    point — the ORT static-quantizer's symmetric-weight default; any
    other layout takes the widened path."""
    if not (x.dtype in (jnp.int8, jnp.uint8) and w.dtype == jnp.int8
            and concrete_zero(w_zp)):
        if do_count:
            _count("dequant")
        return "dequant"
    backend = "dequant"
    if enabled() and jax.default_backend() == "tpu":
        backend = _CONV_LANE.route(x.dtype, x_zp, tuple(x.shape),
                                   tuple(w.shape), attrs)
    if do_count:
        _count(backend)
    return backend


def poison_matmul(a, b, a_zp, b_zp) -> None:
    """Demote ONE matmul shape class to the widened path after a
    runtime failure of its int8 leg — persisted, so a verdict the
    clamped probe landed but the real shape cannot run is not
    re-trusted on the next trace (or after restart)."""
    n, k = a.shape
    _MM_LANE.poison(a.dtype, b.dtype, a_zp, b_zp, n, k, b.shape[1])


def poison_conv(x, w, x_zp, attrs: str) -> None:
    """Conv twin of :func:`poison_matmul`."""
    _CONV_LANE.poison(x.dtype, x_zp, tuple(x.shape), tuple(w.shape),
                      attrs)


class _Attrs:
    """Minimal ctx stand-in for probing the conv cores outside a real
    graph: attribute dict with the onnx defaulting convention."""

    def __init__(self, **attrs):
        self._attrs = attrs
        self.opset = 21

    def attr(self, name, default=None):
        got = self._attrs.get(name)
        return default if got is None else got


def _aot(fn, *args):
    """Concrete numpy in, compiled executable out — escapes any
    ambient trace (the pallas_kernels.available pattern)."""
    return jax.jit(fn).lower(*args).compile()


def _verify_exact(got, want) -> bool:
    """The int8 accumulator must be EXACT — same dtype, same bits."""
    return got.dtype == want.dtype and np.array_equal(got, want)


def _verify_and_time(int8_fn, wide_fn, args) -> str:
    return autotune.verify_then_time(
        {"int8": _aot(int8_fn, *args), "dequant": _aot(wide_fn, *args)},
        args, "dequant", verify_fn=_verify_exact,
        time_fn=lambda fn, a, reps: _best_of(fn, a))


def _rand_q(rng, shape, dtype):
    dt = np.dtype(dtype)
    info = np.iinfo(dt)
    return rng.integers(info.min, info.max + 1, shape).astype(dt)


def _probe_zp(rng, zp, dtype, length: int):
    """Synthetic zero point with the SAME structure (none / scalar /
    vector) and dtype as the real one."""
    if zp is None:
        return None
    dt = np.dtype(getattr(zp, "dtype", dtype))
    info = np.iinfo(dt)
    if getattr(zp, "ndim", 0):
        return rng.integers(info.min, info.max + 1, length).astype(dt)
    return dt.type(rng.integers(info.min, info.max + 1))


def _probe_matmul(a_dt, b_dt, a_zp, b_zp, n: int, k: int,
                  m: int) -> str:
    from synapseml_tpu.onnx import importer

    rng = np.random.default_rng(0)
    n_p = min(n, _PROBE_ROWS_CAP)
    a = _rand_q(rng, (n_p, k), a_dt)
    b = _rand_q(rng, (k, m), b_dt)
    za = _probe_zp(rng, a_zp, a_dt, n_p)
    zb = _probe_zp(rng, b_zp, b_dt, m)
    args = tuple(v for v in (a, b, za, zb) if v is not None)
    has_za, has_zb = za is not None, zb is not None

    def unpack(vals):
        it = iter(vals)
        aa, bb = next(it), next(it)
        return (aa, bb, next(it) if has_za else None,
                next(it) if has_zb else None)

    return _verify_and_time(
        lambda *v: importer._matmul_int8_core(*unpack(v)),
        lambda *v: importer._matmul_wide_core(*unpack(v)), args)


def _probe_conv(x_dt, x_zp, x_shape, w_shape, attrs: str) -> str:
    import json

    from synapseml_tpu.onnx import importer

    rng = np.random.default_rng(0)
    parsed = json.loads(attrs)
    ctx = _Attrs(**parsed)
    xs = (min(x_shape[0], _PROBE_BATCH_CAP), x_shape[1]) + tuple(
        min(s, _PROBE_SPATIAL_CAP) for s in x_shape[2:])
    # spatial extent must still cover the EFFECTIVE kernel under the
    # probe clamp — (k-1)*dilation+1, not the raw tap count
    dil = parsed.get("dilations") or [1] * len(w_shape[2:])
    xs = xs[:2] + tuple(max(s, (kk - 1) * dd + 1) for s, kk, dd
                        in zip(xs[2:], w_shape[2:], dil))
    x = _rand_q(rng, xs, x_dt)
    w = _rand_q(rng, w_shape, np.int8)
    zx = _probe_zp(rng, x_zp, x_dt, 1)
    args = (x, w) if zx is None else (x, w, zx)

    def unpack(vals):
        return (vals[0], vals[1],
                vals[2] if len(vals) > 2 else None, None)

    return _verify_and_time(
        lambda *v: importer._conv_int8_core(ctx, *unpack(v)),
        lambda *v: importer._conv_wide_core(ctx, *unpack(v)), args)


# Lane registrations: both share onnx_int8_routing.json and the q1|
# key schema, so PR-15 fleet verdicts stay valid. _probe_matmul /
# _probe_conv stay the monkeypatchable whole-probe seams (tests stub
# or call them directly), riding the autotuner's legacy probe_hook
# adapter via late-bound lambdas.
_MM_LANE = autotune.register_lane(
    "onnx_int8_matmul",
    key_fn=lambda *r: _key("matmul", _matmul_parts_p(*r)),
    candidates=("dequant", "int8"),
    reference="dequant",
    probe_hook=lambda *r: _probe_matmul(*r),
    table=_TABLE,
    groups=("onnx_int8",),
)
_CONV_LANE = autotune.register_lane(
    "onnx_int8_conv",
    key_fn=lambda *r: _key("conv", _conv_parts_p(*r)),
    candidates=("dequant", "int8"),
    reference="dequant",
    probe_hook=lambda *r: _probe_conv(*r),
    table=_TABLE,
    groups=("onnx_int8",),
)


def clear_cache() -> None:
    """Test hook: drop the in-process memo + negative memo."""
    _MM_LANE.reset()
    _CONV_LANE.reset()
