"""ONNX graph builder: programmatic construction of ``.onnx`` models.

Used three ways:
- test fixtures (build a graph, serialize through real protobuf bytes, then
  re-import via :mod:`synapseml_tpu.onnx.importer` and compare against an
  independent runtime),
- the bundled model zoo (:mod:`synapseml_tpu.onnx.zoo` builds ResNet-family
  graphs in the exact node layout standard exporters emit),
- an export path for models trained in this framework, so they can be consumed
  by any ONNX runtime (the reverse of the reference's import-only ONNXModel,
  ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala:422-427).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from synapseml_tpu.onnx import proto
from synapseml_tpu.onnx.proto import Msg, make_attr, numpy_to_tensor

_ONNX_DTYPE = proto.NP_TO_ONNX  # single source of truth for dtype codes


def _value_info(name: str, dtype, shape: Sequence[Optional[Union[int, str]]]) -> Msg:
    vi = Msg("ValueInfoProto")
    vi.name = name
    tp = Msg("TypeProto")
    tt = Msg("TypeProto.Tensor")
    tt.elem_type = _ONNX_DTYPE[np.dtype(dtype)]
    shp = Msg("TensorShapeProto")
    dims = []
    for d in shape:
        dim = Msg("TensorShapeProto.Dimension")
        if isinstance(d, str) or d is None:
            dim.dim_param = d or "N"
        else:
            dim.dim_value = int(d)
        dims.append(dim)
    shp.dim = dims
    tt.shape = shp
    tp.tensor_type = tt
    vi.type = tp
    return vi


class GraphBuilder:
    """Accumulates nodes/initializers and emits a ModelProto."""

    def __init__(self, name: str = "graph", opset: int = 17,
                 name_prefix: str = ""):
        """``name_prefix`` namespaces every ``fresh`` tensor name —
        REQUIRED for subgraph bodies, whose names would otherwise collide
        with outer-scope tensors they capture (ONNX name resolution is
        lexical: a body-local name shadows the outer one)."""
        self.name = name
        self.opset = opset
        self.name_prefix = name_prefix
        self._nodes: List[Msg] = []
        self._initializers: List[Msg] = []
        self._inputs: List[Msg] = []
        self._outputs: List[Msg] = []
        self._domains: set = set()
        self._counter = 0

    def fresh(self, prefix: str = "t") -> str:
        self._counter += 1
        return f"{self.name_prefix}{prefix}_{self._counter}"

    def add_input(self, name: str, dtype=None, shape=None) -> str:
        """``dtype=None`` emits a bare ValueInfo (name only) — the form
        subgraph bodies use, where types flow in from the outer scope."""
        if dtype is None:
            vi = Msg("ValueInfoProto")
            vi.name = name
            self._inputs.append(vi)
            return name
        self._inputs.append(_value_info(name, dtype, shape))
        return name

    def add_output(self, name: str, dtype, shape) -> str:
        """``shape=None`` emits an untyped ValueInfo (legal ONNX: shape
        inference fills it; converters without shape propagation use it)."""
        if shape is None:
            vi = Msg("ValueInfoProto")
            vi.name = name
            self._outputs.append(vi)
        else:
            self._outputs.append(_value_info(name, dtype, shape))
        return name

    def add_initializer(self, name: str, array: np.ndarray) -> str:
        self._initializers.append(numpy_to_tensor(np.asarray(array), name))
        return name

    def add_node(self, op_type: str, inputs: Sequence[str],
                 outputs: Optional[Sequence[str]] = None,
                 name: Optional[str] = None, domain: str = "",
                 **attrs) -> Union[str, List[str]]:
        """Append a node; returns its (single) output name or list of names.
        ``domain="ai.onnx.ml"`` marks classical-ML ops; the matching
        opset_import entry is added at build()."""
        if outputs is None:
            outputs = [self.fresh(op_type.lower())]
        node = Msg("NodeProto")
        node.input = list(inputs)
        node.output = list(outputs)
        node.op_type = op_type
        node.name = name or self.fresh(f"n_{op_type.lower()}")
        if domain:
            node.domain = domain
            self._domains.add(domain)
        node.attribute = [make_attr(k, v) for k, v in attrs.items()
                          if v is not None]
        self._nodes.append(node)
        return outputs[0] if len(outputs) == 1 else list(outputs)

    # convenience wrappers for the common layers ------------------------
    def conv(self, x: str, w: np.ndarray, b: Optional[np.ndarray] = None,
             strides=(1, 1), pads=(0, 0, 0, 0), group: int = 1,
             dilations=(1, 1), prefix: str = "conv") -> str:
        wn = self.add_initializer(self.fresh(f"{prefix}_w"), w)
        ins = [x, wn]
        if b is not None:
            ins.append(self.add_initializer(self.fresh(f"{prefix}_b"), b))
        return self.add_node(
            "Conv", ins, strides=list(strides), pads=list(pads),
            group=group, dilations=list(dilations),
            kernel_shape=list(w.shape[2:]))

    def batch_norm(self, x: str, scale, bias, mean, var,
                   epsilon: float = 1e-5, prefix: str = "bn") -> str:
        names = [self.add_initializer(self.fresh(f"{prefix}_{s}"), np.asarray(v))
                 for s, v in [("scale", scale), ("bias", bias),
                              ("mean", mean), ("var", var)]]
        return self.add_node("BatchNormalization", [x] + names, epsilon=epsilon)

    def gemm(self, x: str, w: np.ndarray, b: Optional[np.ndarray] = None,
             trans_b: int = 1, prefix: str = "fc") -> str:
        wn = self.add_initializer(self.fresh(f"{prefix}_w"), w)
        ins = [x, wn]
        if b is not None:
            ins.append(self.add_initializer(self.fresh(f"{prefix}_b"), b))
        return self.add_node("Gemm", ins, transB=trans_b)

    def relu(self, x: str) -> str:
        return self.add_node("Relu", [x])

    def build(self, producer: str = "synapseml_tpu") -> Msg:
        g = Msg("GraphProto")
        g.name = self.name
        g.node = self._nodes
        g.initializer = self._initializers
        g.input = self._inputs
        g.output = self._outputs
        m = Msg("ModelProto")
        m.ir_version = 8
        m.producer_name = producer
        osi = Msg("OperatorSetIdProto")
        osi.domain = ""
        osi.version = self.opset
        m.opset_import = [osi]
        for dom in sorted(getattr(self, "_domains", ())):
            extra = Msg("OperatorSetIdProto")
            extra.domain = dom
            extra.version = 3 if dom == "ai.onnx.ml" else 1
            m.opset_import.append(extra)
        m.graph = g
        return m

    def to_bytes(self, producer: str = "synapseml_tpu") -> bytes:
        return proto.encode(self.build(producer))

    def save(self, path: str, producer: str = "synapseml_tpu"):
        with open(path, "wb") as fh:
            fh.write(self.to_bytes(producer))
