"""ONNXModel transformer: batched TPU inference over an imported ONNX graph.

TPU-native rebuild of the reference's ONNXModel
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala:422-684):
the reference minibatches the DataFrame, coerces columns to tensor dtypes,
opens a per-partition onnxruntime session and marshals NIO buffers per row
(:564, :173-193, :357-402). Here the graph is lowered once to a jax function
(:mod:`synapseml_tpu.onnx.importer`) and run through the
:class:`~synapseml_tpu.runtime.executor.BatchedExecutor` — shape-bucketed jit
cache, single contiguous host->device transfer per batch, optional bf16
compute. Softmax/argmax post-processing columns mirror the reference
(:519-562), and feed/fetch dicts mirror ``setFeedDict``/``setFetchDict``.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional

import logging

import jax
import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.onnx.importer import ImportedGraph, import_model
from synapseml_tpu.runtime import autotune
from synapseml_tpu.runtime.executor import BatchedExecutor

_DTYPES = {"float32": np.float32, "bfloat16": "bfloat16", "float16": np.float16}

log = logging.getLogger(__name__)


# -- autotuned lanes ------------------------------------------------------
#
# Lane "onnx_compute_dtype": compute_dtype="auto" resolves to a MEASURED
# f32-vs-bf16 verdict per (model content, batch bucket) — the roofline
# report's top signatures are the ResNet conv/matmul stack, and whether
# bf16 helps is a property of the box (MXU: yes; an AVX host emulating
# bf16: emphatically no), so it must be probed, not hardcoded. Params are
# cast once at executor build (never per batch); the bf16 candidate casts
# floating inputs ON DEVICE inside the compiled probe so the verdict
# prices the full formulation. Verification is reference-relative under a
# measured tolerance: 5% of the f32 output span absorbs bf16 rounding
# drift through a deep stack while still failing a genuinely broken cast.

def _dtype_probe_args(g, token, batch):
    rng = np.random.default_rng(0)
    bp = max(1, min(int(batch), 8))
    args = []
    for name in g.input_names:
        want, shape = g.input_info.get(name, (None, None))
        row = list(shape)[1:] if shape is not None else None
        if row is None or any(not isinstance(d, int) or d <= 0
                              for d in row):
            # crash semantics: dynamic inputs fall back to the f32
            # reference, memoized in-process only
            raise ValueError(
                f"graph input {name!r} has dynamic non-batch dims {shape}")
        dt = np.dtype(want) if want is not None else np.dtype(np.float32)
        if np.issubdtype(dt, np.floating):
            args.append(rng.standard_normal((bp, *row)).astype(dt))
        else:
            args.append(rng.integers(0, 2, (bp, *row)).astype(dt))
    return tuple(args)


def _dtype_candidate(cast):
    def make(rargs, args):
        g = rargs[0]
        fn = g.bind(cast_dtype=cast)
        if cast is None:
            return autotune.aot(fn, *args)
        import jax.numpy as jnp

        def run(*a):
            staged = [x.astype(cast)
                      if jnp.issubdtype(x.dtype, jnp.floating) else x
                      for x in a]
            return fn(*staged)
        return autotune.aot(run, *args)
    return make


def _dtype_verify(got, want):
    gs = got if isinstance(got, tuple) else (got,)
    ws = want if isinstance(want, tuple) else (want,)
    if len(gs) != len(ws):
        return False
    for g_, w_ in zip(gs, ws):
        if tuple(g_.shape) != tuple(w_.shape):
            return False
        if np.issubdtype(np.asarray(w_).dtype, np.floating):
            g64 = np.asarray(g_, np.float64)
            w64 = np.asarray(w_, np.float64)
            if not w64.size:
                continue
            span = max(1e-6, float(np.max(np.abs(w64))))
            if float(np.max(np.abs(g64 - w64))) > 0.05 * span:
                return False
        elif not np.array_equal(np.asarray(g_), np.asarray(w_)):
            return False
    return True


_DTYPE_LANE = autotune.register_lane(
    "onnx_compute_dtype",
    key_fn=lambda g, token, batch: (
        autotune.key_prefix("onnx_dtype")
        + f"|{token}|b{autotune.pow2(int(batch), 1, 4096)}"),
    candidates={"float32": _dtype_candidate(None),
                "bfloat16": _dtype_candidate("bfloat16")},
    verify_fn=_dtype_verify,
    reference="float32",
    args_fn=_dtype_probe_args,
    groups=("resnet50", "resnet50_fast"),
)


def routed_compute_dtype(graph, payload, batch: int) -> str:
    """Measured compute-dtype verdict ("float32" | "bfloat16") for this
    graph at this batch bucket — what ``compute_dtype="auto"`` resolves
    to, and what bench's device leg consults instead of hardcoding
    bf16. Persisted fleet-wide like every lane verdict."""
    from synapseml_tpu.runtime import compile_cache as _cc
    token = _cc.content_hash(payload or b"", len(graph._nodes),
                             tuple(graph.output_names))[:12]
    return _DTYPE_LANE.route(graph, token, int(batch))


# Lane "onnx_hostfeed_wire": which side of the wire dequantizes uint8
# pixels. The uint8 wire (1 byte/px + on-device (x-mean)*scale) won in
# BENCH_r05 detail and is the reference; the float wire (host dequant,
# compute-dtype bytes over the wire) can win where H2D is not the
# bottleneck. The former hardcode in bench.py is now this routed
# verdict. Candidates move REAL bytes: the uint8 leg's timed region is
# device_put(u8) + the compiled dequant (device-resident result — the
# best_of block_until_ready fix is what keeps this honest), the float
# leg's is host dequant + the wider device_put.

def _wire_uint8(rargs, args):
    mean, scale, _row, _b, compute = rargs
    import jax.numpy as jnp
    tgt = jnp.dtype(_DTYPES[compute])
    dq = autotune.aot(
        lambda x: (x.astype(tgt) - jnp.asarray(mean, tgt))
        * jnp.asarray(scale, tgt), args[0])
    return lambda u8: dq(jax.device_put(u8))


def _wire_float(rargs, args):
    mean, scale, _row, _b, compute = rargs
    np_tgt = np.dtype(_DTYPES[compute])

    def run(u8):
        v = (u8.astype(np.float32) - mean) * scale
        return jax.device_put(v.astype(np_tgt))
    return run


def _wire_verify(got, want):
    g64 = np.asarray(got, np.float64)
    w64 = np.asarray(want, np.float64)
    if g64.shape != w64.shape:
        return False
    span = max(1e-6, float(np.max(np.abs(w64))))
    return float(np.max(np.abs(g64 - w64))) <= 0.02 * span


def _wire_key(mean, scale, row, b, compute):
    import hashlib
    tok = hashlib.sha1(np.asarray(mean, np.float32).tobytes()
                       + np.asarray(scale, np.float32).tobytes()
                       ).hexdigest()[:8]
    return (autotune.key_prefix("onnx_wire")
            + f"|{tok}|r{'x'.join(str(d) for d in row)}"
            + f"|b{autotune.pow2(int(b), 1, 4096)}|{compute}")


_WIRE_LANE = autotune.register_lane(
    "onnx_hostfeed_wire",
    key_fn=_wire_key,
    candidates={"uint8": _wire_uint8, "float": _wire_float},
    verify_fn=_wire_verify,
    reference="uint8",
    args_fn=lambda mean, scale, row, b, compute: (
        np.random.default_rng(0).integers(
            0, 256, (max(1, min(int(b), 32)), *row), dtype=np.uint8),),
    groups=("resnet50", "resnet50_fast"),
)


class ONNXModel(Transformer):
    """Runs a (user-supplied) ONNX graph as a pipeline transformer.

    Parameters mirror the reference's surface: ``model_payload`` (the raw
    ``.onnx`` bytes), ``feed_dict`` mapping graph input name -> table column,
    ``fetch_dict`` mapping output column -> graph output name, minibatch size,
    and optional ``softmax_output_col`` / ``argmax_output_col`` post-columns.
    """

    model_payload = ComplexParam("raw .onnx protobuf bytes")
    feed_dict = Param("graph input name -> input column", default=None)
    fetch_dict = Param("output column -> graph output name", default=None)
    mini_batch_size = Param("max rows per device batch", default=128)
    compute_dtype = Param(
        "device compute dtype: float32|bfloat16|float16, or 'auto' for "
        "the autotuner's measured f32-vs-bf16 verdict (routed per model "
        "content + batch bucket, persisted fleet-wide — "
        "runtime/autotune.py lane 'onnx_compute_dtype')",
        default="float32")
    softmax_output_col = Param("column for softmax of first output", default=None)
    argmax_output_col = Param("column for argmax of first output", default=None)
    input_norm = Param(
        "graph input name -> {'mean':..., 'scale':...} applied ON DEVICE "
        "after casting an integer feed to the compute dtype: the wire "
        "carries uint8 pixels (1 byte/px vs 2 for bf16) and the fused "
        "(x - mean) * scale runs where bandwidth is free", default=None)
    devices = Param(
        "data-parallel device spec: None (single default device), 'all', "
        "an int N (first N local devices), or a device sequence — each "
        "mini-batch bucket is dp-sharded across them by the executor "
        "(runtime/executor.py), bit-identical to single-device",
        default=None)
    tensor_parallel = Param(
        "tensor-parallel ways: >1 splits `devices` into a 2-axis dp×tp "
        "mesh (dp = len(devices)//tp) — the batch still shards over dp "
        "while the weights are placed over tp by the partition-rule "
        "registry (parallel/partition_rules.py), so the model no longer "
        "needs to fit one device's HBM. The default rule set is the "
        "reduction-free column layout: replies stay byte-identical to "
        "tensor_parallel=1 (the capture/replay digest contract). Must "
        "divide the device count; requires devices",
        default=1)
    partition_rules = Param(
        "per-model partition-rule overrides, matched ahead of the "
        "default reduction-free column layout: a list of (regex, axes) "
        "pairs — axes a PartitionSpec-like tuple such as (None, 'tp'), "
        "None to replicate — or the string 'megatron' for the full "
        "Megatron column preset (max memory savings; ~1e-6 cross-shard "
        "psum wobble breaks digest stability across reshardings). Only "
        "consulted when tensor_parallel > 1", default=None)
    compile_cache_dir = Param(
        "persistent compile-cache directory (default: the "
        "SYNAPSEML_COMPILE_CACHE env var; unset = off) — wires JAX's "
        "persistent compilation cache and the serialized-executable "
        "store warmup() persists into, so a restarted process "
        "deserializes instead of recompiling "
        "(runtime/compile_cache.py)", default=None)

    def __init__(self, model_path: Optional[str] = None,
                 model_bytes: Optional[bytes] = None, **kw):
        super().__init__(**kw)
        if model_path is not None:
            # keep the user's bytes verbatim (re-encoding through the
            # mini-schema would drop fields it doesn't model, e.g.
            # metadata_props) — re-encode ONLY when external-data
            # sidecars had to be inlined to make the payload
            # self-contained for transformer save/load
            import os

            from synapseml_tpu.onnx import proto as _proto
            with open(model_path, "rb") as fh:
                raw = fh.read()
            model = _proto.decode("ModelProto", raw)
            if model.graph is not None and _proto.resolve_external_data(
                    model, os.path.dirname(os.path.abspath(model_path))) > 0:
                model_bytes = _proto.encode(model)
            else:
                model_bytes = raw
        if model_bytes is not None:
            self.set(model_payload=bytes(model_bytes))
        self._graph_cache: Optional[ImportedGraph] = None
        self._executor_cache: Dict[Any, BatchedExecutor] = {}

    # -- graph access ---------------------------------------------------
    @property
    def graph(self) -> ImportedGraph:
        payload = self.model_payload
        if payload is None:
            raise ValueError("ONNXModel has no model_payload set")
        cache = self.__dict__.get("_graph_cache")
        # payload identity via `is` with the object retained in the cache
        # tuple: set(model_payload=...) must not keep serving the previous
        # graph, and holding the reference rules out CPython id reuse
        if cache is not None and cache[0] is payload:
            return cache[1]
        g = import_model(payload)
        self.__dict__["_graph_cache"] = (payload, g)
        return g

    def model_metadata(self) -> Dict[str, Any]:
        g = self.graph
        return {
            "inputs": {n: g.input_info.get(n) for n in g.input_names},
            "outputs": list(g.output_names),
            "n_nodes": len(g._nodes),
            "param_bytes": g.param_bytes(),
            "opset": g.opset,
        }

    def decode_scheduler(self, **kw) -> "Any":
        """Build a continuous-batching decode scheduler over this
        model's graph (runtime/decode.py) — the decode-mode entry the
        serving CLI's ``--decode`` wraps. The payload must be a
        share-buffer decoder graph (``past_key``/``past_value`` +
        ``seqlens_k`` inputs, e.g. an ORT-GenAI export or
        ``zoo.tiny_decoder``); plain feed-forward graphs raise. The
        scheduler inherits this model's compile-cache wiring (same
        content-hash key, so a restarted replica deserializes its
        decode signatures); geometry and KV capacity default from the
        ``SYNAPSEML_DECODE_*`` / ``SYNAPSEML_KV_*`` env knobs
        (docs/knobs.md) with keyword overrides. Caller owns
        ``warmup()`` + ``start()``."""
        from synapseml_tpu.runtime import compile_cache as _cc
        from synapseml_tpu.runtime.decode import DecodeScheduler

        kw.setdefault("cache_dir", self.compile_cache_dir)
        kw.setdefault("cache_key",
                      _cc.content_hash(self.model_payload or b""))
        return DecodeScheduler(self.graph, **kw)

    def _post_copy(self, src):
        super()._post_copy(src)
        self._graph_cache = None
        self._executor_cache = {}

    # -- execution ------------------------------------------------------
    def _resolve_feeds(self, table: Table) -> List[np.ndarray]:
        g = self.graph
        feed = self.feed_dict or {}
        arrays = []
        for name in g.input_names:
            col = feed.get(name, name)
            if col not in table:
                raise KeyError(
                    f"graph input {name!r}: column {col!r} not in table "
                    f"(columns: {table.columns})")
            arr = np.asarray(table[col])
            if arr.dtype == object:
                arr = np.stack([np.asarray(v) for v in arr])
            want_dtype, _ = g.input_info.get(name, (None, None))
            if want_dtype is not None and np.issubdtype(np.dtype(want_dtype),
                                                        np.integer):
                arr = arr.astype(want_dtype)
            arrays.append(arr)
        return arrays

    def _executor(self) -> BatchedExecutor:
        cache = self.__dict__.setdefault("_executor_cache", {})
        g = self.graph
        # graph identity in the key: subclasses (CNTKModel cut_layers) can
        # swap the graph under us; a stale executor would run the old one
        norm = self.input_norm or {}
        unknown = set(norm) - set(g.input_names)
        if unknown:
            raise KeyError(
                f"input_norm names {sorted(unknown)} are not graph inputs "
                f"(inputs: {list(g.input_names)})")
        for name, spec in norm.items():
            bad = set(spec) - {"mean", "scale"}
            if bad:
                raise KeyError(
                    f"input_norm[{name!r}]: unknown keys {sorted(bad)} "
                    "(supported: 'mean', 'scale')")
            want, _ = g.input_info.get(name, (None, None))
            if want is not None and np.issubdtype(np.dtype(want), np.integer):
                raise TypeError(
                    f"input_norm[{name!r}]: graph declares an integer "
                    f"input ({np.dtype(want).name}) — normalizing token "
                    "ids is almost certainly a misconfiguration")
        # canonical, content-based key: dict order must not recompile,
        # array-valued mean/scale must not collide via summarized repr
        norm_key = tuple(
            (name, tuple(sorted(
                (k, np.asarray(v).tobytes(), np.asarray(v).shape)
                for k, v in spec.items())))
            for name, spec in sorted(norm.items()))
        from synapseml_tpu.runtime.executor import resolve_devices
        devs = resolve_devices(self.devices)
        dev_key = None if devs is None else tuple(d.id for d in devs)
        cd = self.compute_dtype
        if cd == "auto":
            # measured verdict (probed once per content+batch class,
            # then a cache-table hit); the resolved dtype keys the
            # executor cache so a verdict flip cannot serve stale
            # weight copies
            cd = routed_compute_dtype(g, self.model_payload,
                                      self.mini_batch_size)
        tp = int(self.tensor_parallel or 1)
        if tp < 1:
            raise ValueError(f"tensor_parallel must be >= 1, got {tp}")
        rules = self.partition_rules
        if tp > 1:
            if devs is None:
                raise ValueError(
                    "tensor_parallel > 1 requires an explicit `devices` "
                    "spec (the dp×tp mesh needs a device list)")
            if len(devs) % tp:
                raise ValueError(
                    f"tensor_parallel={tp} does not divide the "
                    f"{len(devs)}-device pool")
        # canonical rules key: JSON-ish override lists and the
        # 'megatron' preset string must key distinctly and hashably
        if rules is None or rules == []:
            rules_key = None
        elif isinstance(rules, str):
            rules_key = (rules,)
        else:
            rules_key = tuple(
                (str(p), tuple(s) if isinstance(s, (list, tuple)) else s)
                for p, s in rules)
        key = (id(g), self.mini_batch_size, cd, norm_key,
               dev_key, self.compile_cache_dir, tp, rules_key)
        if key not in cache:
            dtype = _DTYPES[cd]
            params = g.params
            if cd != "float32":
                # the one-time cast: params land on device in the routed
                # dtype at executor build (warmup), never per batch
                params = {
                    k: (v.astype(dtype) if np.issubdtype(v.dtype, np.floating)
                        else v)
                    for k, v in params.items()
                }
            compute = None if cd == "float32" else dtype

            # Integer feeds bound for float graph inputs are cast (and
            # optionally normalized) ON DEVICE: the host->device wire then
            # carries 1-byte uint8 pixels instead of 2-byte bf16 — the
            # usual bottleneck for co-located (PCIe) and tunneled feeds
            # alike. Mirrors the reference's marshalling stage, where ORT
            # converts on the accelerator side of PCIe
            # (ref: ONNXModel.scala:357-402).
            import jax.numpy as jnp
            names = list(g.input_names)
            info = g.input_info
            tgt = jnp.dtype(dtype) if compute is not None else jnp.float32

            def apply_fn(p, *args, _names=names, _norm=norm, _tgt=tgt):
                staged = []
                for name, a in zip(_names, args):
                    spec = _norm.get(name)
                    if not jnp.issubdtype(a.dtype, jnp.floating):
                        want, _ = info.get(name, (None, None))
                        # jnp.issubdtype: bf16-declared inputs count as
                        # floating too (np.issubdtype says False for them)
                        wants_float = want is not None and jnp.issubdtype(
                            jnp.dtype(want), jnp.floating)
                        if spec is not None or wants_float:
                            a = a.astype(_tgt)
                    if spec is not None:
                        a = ((a - jnp.asarray(spec.get("mean", 0.0), _tgt))
                             * jnp.asarray(spec.get("scale", 1.0), _tgt))
                    staged.append(a)
                return g.apply(p, *staged)
            # params ride as a bound argument pytree: device-resident once,
            # shared by every shape bucket (vs baked-in jit constants)
            # each executor pins a device copy of the weights: evict the
            # ones built for graphs that are no longer current (payload or
            # cut_layers swaps), and cap live-graph configs (a batch-size
            # sweep must not accumulate unbounded weight copies)
            for stale in [kk for kk in cache if kk[0] != id(g)]:
                del cache[stale]
            while len(cache) >= 4:
                cache.pop(next(iter(cache)))
            # content hash over graph+weights config: the persistent-
            # executable key ingredient that invalidates on changed model
            # bytes. The graph's node count + outputs disambiguate
            # truncated subgraphs (CNTKModel cut_layers) sharing a payload
            from synapseml_tpu.runtime import compile_cache as _cc
            # pre-tp content hashes keep their exact ingredient list so
            # existing persistent executables stay warm at tp=1
            extra = () if (tp == 1 and rules_key is None) \
                else (tp, repr(rules_key))
            cache_key = _cc.content_hash(
                self.model_payload or b"", len(g._nodes),
                tuple(g.output_names), cd, norm_key, *extra)
            bound_specs = None
            if tp > 1:
                # match against the ORIGINAL float32 params: shapes are
                # what the registry keys on, and np.issubdtype treats
                # bf16 as non-floating (would skew the 2-D fallback)
                from jax.sharding import Mesh
                from synapseml_tpu.parallel.partition_rules import (
                    match_partition_rules, megatron_rules)
                dp = len(devs) // tp
                mesh = Mesh(np.asarray(devs).reshape(dp, tp),
                            ("dp", "tp"))
                ovr = megatron_rules() if rules == "megatron" else rules
                specs, report = match_partition_rules(
                    g.params, mesh, overrides=ovr)
                self.__dict__["_partition_report"] = report
                log.info("tensor_parallel=%d partition coverage: %s",
                         tp, report.summary())
                bound_specs = (specs,)
            # the megatron preset opts into true sharded compute (max
            # memory headroom, documented ~1e-6 psum drift); every other
            # layout keeps the gather formulation so replies stay
            # byte-identical to tp=1 — the capture/replay digest contract
            cache[key] = BatchedExecutor(
                apply_fn, compute_dtype=compute,
                max_bucket=self.mini_batch_size, bound_args=(params,),
                devices=devs, cache_key=cache_key,
                cache_dir=self.compile_cache_dir,
                tensor_parallel=tp, bound_specs=bound_specs,
                tp_compute="sharded" if rules == "megatron" else "gather")
        return cache[key]

    def partition_coverage(self) -> Optional[dict]:
        """Coverage report from the last tensor-parallel executor build:
        which partition rule claimed each parameter and why (see
        parallel/partition_rules.py). None until an executor has been
        built with ``tensor_parallel > 1``."""
        report = self.__dict__.get("_partition_report")
        return None if report is None else report.as_dict()

    def preferred_wire(self, input_name: str,
                       batch: Optional[int] = None) -> str:
        """Routed hostfeed wire for ``input_name``: "uint8" (ship raw
        pixels, dequantize on device via ``input_norm`` — the
        reference) or "float" (dequantize on host, ship the compute
        dtype). A measured verdict from the "onnx_hostfeed_wire" lane,
        persisted per (norm content, row shape, batch bucket, compute
        dtype); "float" unconditionally when the input has no
        ``input_norm`` spec (there is no uint8 wire without one)."""
        g = self.graph
        norm = (self.input_norm or {}).get(input_name)
        if norm is None:
            return "float"
        _want, shape = g.input_info.get(input_name, (None, None))
        row = list(shape)[1:] if shape is not None else None
        if row is None or any(not isinstance(d, int) or d <= 0
                              for d in row):
            return "uint8"
        b = int(batch or self.mini_batch_size)
        compute = self.compute_dtype
        if compute == "auto":
            compute = routed_compute_dtype(g, self.model_payload, b)
        mean = np.asarray(norm.get("mean", 0.0), np.float32)
        scale = np.asarray(norm.get("scale", 1.0), np.float32)
        return _WIRE_LANE.route(mean, scale, tuple(int(d) for d in row),
                                b, compute)

    def warmup(self, buckets=None, example_feeds=None):
        """AOT-compile (and persist, when a compile-cache dir is
        configured) every mini-batch bucket signature BEFORE traffic
        arrives — the serving cold-start path then deserializes or reuses
        executables instead of paying XLA compilation per bucket
        (runtime/compile_cache.py; the reference ships prebuilt engines
        in its jars, ONNXModel.scala:173-193).

        Input shapes/dtypes come from the graph's declared inputs; pass
        ``example_feeds`` (graph input name -> example array with a batch
        dim) for inputs with dynamic non-batch dims or a different wire
        dtype (e.g. the uint8-pixel wire under ``input_norm``). Returns a
        :class:`~synapseml_tpu.runtime.compile_cache.WarmupReport`."""
        g = self.graph
        example_feeds = example_feeds or {}
        args = []
        for name in g.input_names:
            if name in example_feeds:
                a = np.asarray(example_feeds[name])
                args.append((tuple(a.shape[1:]), a.dtype))
                continue
            want_dtype, shape = g.input_info.get(name, (None, None))
            row = list(shape)[1:] if shape is not None else None
            if row is None or any(not isinstance(d, int) or d <= 0
                                  for d in row):
                raise ValueError(
                    f"graph input {name!r} has dynamic non-batch dims "
                    f"{shape}: pass example_feeds[{name!r}] with the "
                    "concrete serving shape")
            args.append((tuple(int(d) for d in row),
                         np.dtype(want_dtype) if want_dtype is not None
                         else np.dtype(np.float32)))
        return self._executor().warmup(args, buckets=buckets)

    def _transform(self, table: Table) -> Table:
        # ride the executor's shared submit/drain pipeline: concurrent
        # _transform callers (serving scoring workers) overlap their host
        # staging, H2D, compute, and D2H instead of each serializing a
        # private dispatch->fetch loop
        feeds = self._resolve_feeds(table)
        # keep a strong ref across result(): a concurrent config change
        # may evict this executor from the cache, and the pipeline holds
        # it only weakly — dropping it mid-flight would fail the future
        ex = self._executor()
        outs = ex.submit(*feeds).result()
        return self._attach_outputs(table, outs)

    def transform_stream(self, tables: Iterable[Table]) -> Iterator[Table]:
        """Score an iterable of tables with ``pipeline_depth`` mini-batches
        in flight, yielding transformed tables in order — batch k+1's host
        staging and H2D copy overlap batch k's compute and D2H fetch
        (the cross-call counterpart of the reference's IOBinding overlap,
        ref: ONNXModel.scala:357-402)."""
        from collections import deque

        ex = self._executor()
        pending: "deque" = deque()
        for table in tables:
            pending.append((table, ex.submit(*self._resolve_feeds(table))))
            while len(pending) > ex.pipeline_depth:
                t, fut = pending.popleft()
                yield self._attach_outputs(t, fut.result())
        while pending:
            t, fut = pending.popleft()
            yield self._attach_outputs(t, fut.result())

    def _attach_outputs(self, table: Table, outs) -> Table:
        g = self.graph
        fetch = self.fetch_dict or {n: n for n in g.output_names}
        by_name = dict(zip(g.output_names, outs))
        new_cols: Dict[str, np.ndarray] = {}
        for col, out_name in fetch.items():
            if out_name not in by_name:
                raise KeyError(f"fetch_dict: no graph output {out_name!r}")
            new_cols[col] = np.asarray(by_name[out_name], dtype=np.float32) \
                if np.issubdtype(np.asarray(by_name[out_name]).dtype, np.floating) \
                else np.asarray(by_name[out_name])
        first = np.asarray(outs[0])
        if self.softmax_output_col:
            x = first.astype(np.float64)
            x = x - x.max(axis=-1, keepdims=True)
            e = np.exp(x)
            new_cols[self.softmax_output_col] = (
                e / e.sum(axis=-1, keepdims=True)).astype(np.float32)
        if self.argmax_output_col:
            new_cols[self.argmax_output_col] = first.argmax(axis=-1).astype(np.int64)
        return table.with_columns(new_cols)

    def _load_extra(self, path: str):
        self._graph_cache = None
        self._executor_cache = {}
