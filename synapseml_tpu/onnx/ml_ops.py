"""``ai.onnx.ml`` domain ops — the classical-ML opset.

The reference's flagship ONNX workload is NOT a neural net: the
"ONNX - Inference on Spark" notebook converts a trained LightGBM model
with onnxmltools and scores it through ONNXModel
(ref: notebooks/ONNX - Inference on Spark.ipynb — convert_lightgbm ->
setModelPayload -> transform; ONNXModel.scala:156-171 maps the
sequence-of-maps ZipMap output back to vectors). Those converted graphs
are built from ``ai.onnx.ml`` ops: TreeEnsembleClassifier/Regressor,
ZipMap, Scaler, and friends. This module lowers them to jax:

- Tree ensembles run as a vectorized gather-based traversal (the same
  fixed-depth ``fori_loop`` pattern as the GBDT engine's
  ``predict_tree``) — [N, T] node cursors, one gather per level, MXU/VPU
  friendly, no per-row Python.
- ZipMap's seq<map<label, prob>> output is lowered to the dense
  probability tensor itself; the reference flattens it back to a vector
  anyway (ONNXModel.scala:255-263), so the table-native output contract
  is identical.

String label maps (classlabels_strings, CategoryMapper/LabelEncoder
string sides) work on host (object-array) inputs only — device tensors
cannot hold strings.
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from synapseml_tpu.onnx.importer import _all_host, _is_host, op

# branch-mode codes for the vectorized comparator
_MODES = {"BRANCH_LEQ": 0, "BRANCH_LT": 1, "BRANCH_GTE": 2, "BRANCH_GT": 3,
          "BRANCH_EQ": 4, "BRANCH_NEQ": 5, "LEAF": 6}

# the dense GEMM formulation trades memory for MXU throughput; these bound
# the [T, M, n_leaves] path tensor (see _TreeTables)
_PATH_WARN_BYTES = 256 << 20
_PATH_GUARD_BYTES = 2 << 30


def _cached(ctx, key: str, build):
    """Host-side preprocessing cached on the node's attr dict — runs once
    per imported graph, not once per trace."""
    got = ctx.attrs.get(key)
    if got is None:
        got = build()
        ctx.attrs[key] = got
    return got


class _TreeTables:
    """GEMM-ified ensemble from the flat (treeid, nodeid) attributes.

    Pointer-chasing traversal is gather-bound — catastrophic on TPU
    (measured ~1.2s for 5k rows x 100 trees). Instead every node's test
    evaluates as one elementwise pass, and leaf membership becomes a
    batched matmul (the well-known GEMM tree-inference formulation):
    a sample reaches leaf l of tree t iff its path-consistent decision
    count equals the path length, i.e.
    ``einsum(decisions, P) + c0 == plen`` with P[t,m,l] in {+1,-1,0};
    leaf values then apply through a second einsum. All MXU work, the
    only gather is ``x[:, feat_ids]`` with compile-time-constant indices.
    """

    def __init__(self, ctx, weight_prefix: str, n_out: int):
        a = ctx.attrs
        tree_ids = np.asarray(a["nodes_treeids"], np.int64)
        node_ids = np.asarray(a["nodes_nodeids"], np.int64)
        modes = [str(m) for m in a["nodes_modes"]]
        trees = np.unique(tree_ids)
        t_index = {t: i for i, t in enumerate(trees)}
        tn = self.n_trees = len(trees)
        m = int(node_ids.max()) + 1 if len(node_ids) else 1

        feat = np.zeros((tn, m), np.int64)
        thresh = np.full((tn, m), np.inf, np.float32)
        left = np.zeros((tn, m), np.int32)
        right = np.zeros((tn, m), np.int32)
        mode = np.full((tn, m), _MODES["LEAF"], np.int8)
        miss_true = np.zeros((tn, m), np.bool_)

        missing = a.get("nodes_missing_value_tracks_true") or []
        feats_attr = np.asarray(a["nodes_featureids"], np.int64)
        vals_attr = np.asarray(a["nodes_values"], np.float64)
        true_ids = np.asarray(a["nodes_truenodeids"], np.int64)
        false_ids = np.asarray(a["nodes_falsenodeids"], np.int64)

        referenced = [set() for _ in range(tn)]
        present = [set() for _ in range(tn)]
        for i in range(len(tree_ids)):
            t = t_index[tree_ids[i]]
            n = node_ids[i]
            md = _MODES.get(modes[i])
            if md is None:
                raise NotImplementedError(
                    f"TreeEnsemble node mode {modes[i]!r} not supported")
            mode[t, n] = md
            present[t].add(int(n))
            if md != _MODES["LEAF"]:
                feat[t, n] = feats_attr[i]
                thresh[t, n] = vals_attr[i]
                left[t, n] = true_ids[i]
                right[t, n] = false_ids[i]
                referenced[t].add(int(true_ids[i]))
                referenced[t].add(int(false_ids[i]))
                if i < len(missing):
                    miss_true[t, n] = bool(missing[i])

        # leaf -> output weights, scattered at (tree, node, out_id)
        w_tree = np.asarray(a[f"{weight_prefix}_treeids"], np.int64)
        w_node = np.asarray(a[f"{weight_prefix}_nodeids"], np.int64)
        w_id = np.asarray(a[f"{weight_prefix}_ids"], np.int64)
        w_val = np.asarray(a[f"{weight_prefix}_weights"], np.float64)
        uniq_ids = np.unique(w_id) if len(w_id) else np.array([], np.int64)
        self.distinct_out_ids = len(uniq_ids)
        # the single accumulated column for binary one-score ensembles —
        # spec-valid graphs may scatter into id 1, not 0
        self.single_out_id = int(uniq_ids[0]) if len(uniq_ids) == 1 else None
        node_weights = np.zeros((tn, m, n_out), np.float64)
        for i in range(len(w_tree)):
            node_weights[t_index[w_tree[i]], w_node[i], w_id[i]] += w_val[i]

        # per-tree DFS from the root: collect each leaf's (must-true,
        # must-false) ancestor sets
        leaves_per_tree: List[List] = []
        for t in range(tn):
            root_cand = sorted(present[t] - referenced[t])
            root = root_cand[0] if root_cand else 0
            leaves = []  # (leaf_node, pos_nodes, neg_nodes)
            stack = [(root, [], [])]
            while stack:
                n, pos, neg = stack.pop()
                if mode[t, n] == _MODES["LEAF"]:
                    leaves.append((n, pos, neg))
                else:
                    stack.append((int(left[t, n]), pos + [n], neg))
                    stack.append((int(right[t, n]), pos, neg + [n]))
            leaves_per_tree.append(leaves)
        n_leaves = max(len(lv) for lv in leaves_per_tree)

        # the dense [T, M, n_leaves] path tensor scales as trees x nodes x
        # leaves: fine at notebook scale, but a 1000-tree deep ensemble
        # would allocate gigabytes at import — surface that before numpy
        # does it silently
        path_bytes = tn * m * n_leaves * 4
        if path_bytes > _PATH_GUARD_BYTES:
            raise MemoryError(
                f"tree-ensemble path tensor would allocate "
                f"{path_bytes / (1 << 30):.1f} GiB "
                f"({tn} trees x {m} nodes x {n_leaves} leaves); this "
                f"GEMM formulation targets notebook-scale ensembles — "
                f"score via the native GBDT predictor instead")
        if path_bytes > _PATH_WARN_BYTES:
            warnings.warn(
                f"tree-ensemble path tensor allocates "
                f"{path_bytes / (1 << 20):.0f} MiB "
                f"({tn} trees x {m} nodes x {n_leaves} leaves)",
                RuntimeWarning, stacklevel=2)

        path = np.zeros((tn, m, n_leaves), np.float32)   # +1 / -1 / 0
        c0 = np.zeros((tn, n_leaves), np.float32)        # sum of negatives
        plen = np.full((tn, n_leaves), -1.0, np.float32)  # pad: unreachable
        leaf_w = np.zeros((tn, n_leaves, n_out), np.float32)
        for t, leaves in enumerate(leaves_per_tree):
            for li, (n, pos, neg) in enumerate(leaves):
                path[t, pos, li] = 1.0
                path[t, neg, li] = -1.0
                c0[t, li] = len(neg)
                plen[t, li] = len(pos) + len(neg)
                leaf_w[t, li] = node_weights[t, n]

        self.feat_flat = feat.reshape(-1)                # [T*M] constant
        self.thresh_flat = thresh.reshape(-1)
        self.mode_flat = mode.reshape(-1)
        self.miss_flat = miss_true.reshape(-1)
        self.all_leq = bool(np.all(
            (mode == _MODES["LEAF"]) | (mode == _MODES["BRANCH_LEQ"])))
        self.any_missing_true = bool(miss_true.any())
        self.path, self.c0, self.plen = path, c0, plen
        self.weights = leaf_w
        self.m = m

    def scores(self, x) -> jnp.ndarray:
        """[N, n_out] summed leaf weights, two einsums + elementwise."""
        x = jnp.asarray(x)
        if x.ndim == 1:
            x = x[None, :]
        n = x.shape[0]
        xv = x[:, self.feat_flat].astype(jnp.float32)    # [N, T*M]
        thr = jnp.asarray(self.thresh_flat)
        if self.all_leq:
            cond = xv <= thr
        else:
            md = self.mode_flat
            conds = [xv <= thr, xv < thr, xv >= thr, xv > thr,
                     xv == thr, xv != thr]
            cond = jnp.zeros_like(xv, dtype=bool)
            for code in range(6):
                sel = md == code
                if sel.any():  # host-side: md is a numpy constant
                    cond = jnp.where(jnp.asarray(sel), conds[code], cond)
        if self.any_missing_true:
            cond = jnp.where(jnp.isnan(xv), jnp.asarray(self.miss_flat),
                             cond)
        else:
            cond = jnp.where(jnp.isnan(xv), False, cond)
        d = cond.astype(jnp.float32).reshape(n, self.n_trees, self.m)
        count = jnp.einsum("ntm,tml->ntl", d, jnp.asarray(self.path),
                           preferred_element_type=jnp.float32)
        reached = (count + jnp.asarray(self.c0)[None]
                   == jnp.asarray(self.plen)[None]).astype(jnp.float32)
        return jnp.einsum("ntl,tlk->nk", reached,
                          jnp.asarray(self.weights),
                          preferred_element_type=jnp.float32)


def _post_transform(scores, kind: str):
    if kind in ("NONE", ""):
        return scores
    if kind == "LOGISTIC":
        return jax.nn.sigmoid(scores)
    if kind == "SOFTMAX":
        return jax.nn.softmax(scores, axis=-1)
    if kind == "SOFTMAX_ZERO":
        # softmax over nonzero entries; zeros stay zero
        nz = scores != 0
        e = jnp.where(nz, jnp.exp(scores - jnp.max(
            jnp.where(nz, scores, -jnp.inf), axis=-1, keepdims=True)), 0.0)
        return e / jnp.maximum(e.sum(-1, keepdims=True), 1e-30)
    raise NotImplementedError(f"post_transform {kind!r} not supported")


def _classifier_outputs(ctx, scores):
    """(label, probabilities) with the single-score binary expansion
    (onnxruntime's binary_case: one accumulated score, two labels)."""
    labels_i = ctx.attr("classlabels_int64s")
    labels_s = ctx.attr("classlabels_strings")
    if labels_s:
        raise NotImplementedError(
            "string class labels need host-side mapping; use int64 labels")
    labels = np.asarray(labels_i if labels_i else [0, 1], np.int64)
    pt = str(ctx.attr("post_transform", "NONE"))
    binary_single = (len(labels) == 2 and scores.shape[-1] == 1)
    if binary_single:
        p = _post_transform(scores[..., 0], pt if pt != "SOFTMAX" else "NONE")
        probs = jnp.stack([1.0 - p, p], axis=-1)
    else:
        probs = _post_transform(scores, pt)
    label = jnp.asarray(labels)[jnp.argmax(probs, axis=-1)]
    return label, probs


@op("TreeEnsembleClassifier")
def _tree_classifier(ctx, x):
    labels = ctx.attr("classlabels_int64s") or ctx.attr(
        "classlabels_strings") or [0, 1]
    k = len(labels)

    def build():
        t = _TreeTables(ctx, "class", k)
        # single-output binary ensembles accumulate one score column
        # (whichever out_id it was scattered into)
        if k == 2 and t.distinct_out_ids <= 1:
            col = t.single_out_id or 0
            t.weights = t.weights[..., col:col + 1]
        return t
    tables = _cached(ctx, "__tables__", build)
    scores = tables.scores(x)
    base = ctx.attr("base_values")
    if base:
        scores = scores + jnp.asarray(
            np.asarray(base, np.float32)[: scores.shape[-1]])
    return _classifier_outputs(ctx, scores)


@op("TreeEnsembleRegressor")
def _tree_regressor(ctx, x):
    n_targets = int(ctx.attr("n_targets", 1))
    tables = _cached(ctx, "__tables__",
                     lambda: _TreeTables(ctx, "target", n_targets))
    agg = str(ctx.attr("aggregate_function", "SUM"))
    if agg == "AVERAGE":
        scores = tables.scores(x) / max(tables.n_trees, 1)
    elif agg == "SUM":
        scores = tables.scores(x)
    else:
        raise NotImplementedError(f"aggregate_function {agg!r}")
    base = ctx.attr("base_values")
    if base:
        scores = scores + jnp.asarray(np.asarray(base, np.float32))
    return _post_transform(scores, str(ctx.attr("post_transform", "NONE")))


# new-style TreeEnsemble (ai.onnx.ml opset 5) integer codes
_V5_MODES = {0: "BRANCH_LEQ", 1: "BRANCH_LT", 2: "BRANCH_GTE",
             3: "BRANCH_GT", 4: "BRANCH_EQ", 5: "BRANCH_NEQ"}
_V5_POST = {0: "NONE", 1: "SOFTMAX", 2: "LOGISTIC", 3: "SOFTMAX_ZERO"}


@op("TreeEnsemble")
def _tree_ensemble_v5(ctx, x):
    """ai.onnx.ml opset-5 TreeEnsemble (the regressor/classifier merger
    that new converters emit). The compact encoding — internal nodes and
    leaves in separate arrays, child pointers tagged by
    nodes_trueleafs/falseleafs flags — is unrolled into the flat
    (treeid, nodeid) form and reuses the GEMM-ified _TreeTables path, so
    the lowering stays all-MXU."""
    def build():
        import types

        a = ctx.attrs
        roots = [int(r) for r in a["tree_roots"]]
        modes = np.asarray(a["nodes_modes"]).reshape(-1)
        splits = np.asarray(a["nodes_splits"], np.float64).reshape(-1)
        feats = [int(v) for v in a["nodes_featureids"]]
        tru = [int(v) for v in a["nodes_truenodeids"]]
        fal = [int(v) for v in a["nodes_falsenodeids"]]
        tru_leaf = [int(v) for v in a["nodes_trueleafs"]]
        fal_leaf = [int(v) for v in a["nodes_falseleafs"]]
        miss = a.get("nodes_missing_value_tracks_true") or []
        leaf_tid = [int(v) for v in a["leaf_targetids"]]
        leaf_w = np.asarray(a["leaf_weights"], np.float64).reshape(-1)
        if any(int(m) == 6 for m in modes):
            raise NotImplementedError(
                "TreeEnsemble BRANCH_MEMBER (set membership via "
                "membership_values) is not supported; re-export with "
                "equality splits")
        old: Dict[str, list] = {k: [] for k in (
            "nodes_treeids", "nodes_nodeids", "nodes_modes",
            "nodes_featureids", "nodes_values", "nodes_truenodeids",
            "nodes_falsenodeids", "nodes_missing_value_tracks_true",
            "target_treeids", "target_nodeids", "target_ids",
            "target_weights")}

        for t, root in enumerate(roots):
            # explicit-stack unroll (deep unpruned trees must not hit
            # Python's recursion limit at import); children patch their
            # parent's child-pointer slot once their own id is assigned
            nid = 0
            stack = [(root, False, None, None)]
            while stack:
                idx, is_leaf, patch_pos, child_slot = stack.pop()
                if patch_pos is not None:
                    old[child_slot][patch_pos] = nid
                old["nodes_treeids"].append(t)
                old["nodes_nodeids"].append(nid)
                if is_leaf:
                    old["nodes_modes"].append("LEAF")
                    old["nodes_featureids"].append(0)
                    old["nodes_values"].append(0.0)
                    old["nodes_missing_value_tracks_true"].append(0)
                    old["nodes_truenodeids"].append(0)
                    old["nodes_falsenodeids"].append(0)
                    old["target_treeids"].append(t)
                    old["target_nodeids"].append(nid)
                    old["target_ids"].append(leaf_tid[idx])
                    old["target_weights"].append(float(leaf_w[idx]))
                else:
                    old["nodes_modes"].append(_V5_MODES[int(modes[idx])])
                    old["nodes_featureids"].append(feats[idx])
                    old["nodes_values"].append(float(splits[idx]))
                    old["nodes_missing_value_tracks_true"].append(
                        int(miss[idx]) if idx < len(miss) else 0)
                    pos = len(old["nodes_truenodeids"])
                    old["nodes_truenodeids"].append(-1)  # patched above
                    old["nodes_falsenodeids"].append(-1)
                    stack.append((fal[idx], bool(fal_leaf[idx]), pos,
                                  "nodes_falsenodeids"))
                    stack.append((tru[idx], bool(tru_leaf[idx]), pos,
                                  "nodes_truenodeids"))
                nid += 1
        n_out = int(ctx.attr("n_targets", 0)) or (max(leaf_tid) + 1)
        return _TreeTables(
            types.SimpleNamespace(attrs=old), "target", n_out)

    tables = _cached(ctx, "__tables__", build)
    agg = int(ctx.attr("aggregate_function", 1))
    if agg == 0:
        scores = tables.scores(x) / max(tables.n_trees, 1)
    elif agg == 1:
        scores = tables.scores(x)
    else:
        raise NotImplementedError(
            f"TreeEnsemble aggregate_function={agg} (MIN/MAX) is not "
            "supported; converters emit SUM/AVERAGE")
    pt = int(ctx.attr("post_transform", 0))
    if pt not in _V5_POST:
        raise NotImplementedError(f"TreeEnsemble post_transform={pt}")
    return _post_transform(scores, _V5_POST[pt])


@op("ZipMap")
def _zipmap(ctx, probs):
    # seq<map<label, score>> lowered to the dense tensor: the reference
    # flattens the maps back into a vector column immediately
    # (ONNXModel.scala:156-171,255-263), so downstream semantics match.
    return probs


@op("CastMap")
def _cast_map(ctx, x):
    """CastMap: map<int64, T> -> tensor. Two runtime forms arrive here:
    a python dict (a genuine map value, e.g. from DictVectorizer-style
    feeds) gets densified per map_form/max_map; the ZipMap lowering's
    dense vector (see _zipmap) just casts — the reference's scala side
    does the same flatten-then-cast (ONNXModel.scala:156-171)."""
    cast_to = str(ctx.attr("cast_to", "TO_FLOAT"))
    if isinstance(x, dict):
        keys = sorted(int(k) for k in x)
        if str(ctx.attr("map_form", "DENSE")) == "DENSE":
            arr = np.asarray([x[k] for k in keys])
        else:
            max_map = int(ctx.attr("max_map", 0))
            arr = np.zeros(max_map)
            for k in keys:
                if 0 <= k < max_map:
                    arr[k] = x[k]
        arr = arr.reshape(1, -1)  # spec output is [1, C] per map
    else:
        arr = np.asarray(x) if _is_host(x) else x
    if cast_to == "TO_FLOAT":
        return (np.asarray(arr, np.float32) if _is_host(arr)
                else arr.astype(jnp.float32))
    if cast_to == "TO_INT64":
        return (np.asarray(arr, np.int64) if _is_host(arr)
                else arr.astype(jnp.int64))
    if cast_to == "TO_STRING":
        if not _is_host(arr):
            raise NotImplementedError(
                "CastMap TO_STRING needs host values (strings cannot be "
                "device-traced)")
        return np.asarray([str(v) for v in
                           np.asarray(arr).reshape(-1)],
                          dtype=object).reshape(np.shape(arr))
    raise ValueError(f"CastMap cast_to {cast_to!r}")


@op("Scaler")
def _scaler(ctx, x):
    offset = np.asarray(ctx.attr("offset", [0.0]), np.float32)
    scale = np.asarray(ctx.attr("scale", [1.0]), np.float32)
    if _is_host(x):
        return (np.asarray(x, np.float32) - offset) * scale
    return (x - jnp.asarray(offset)) * jnp.asarray(scale)


@op("Normalizer")
def _normalizer(ctx, x):
    kind = str(ctx.attr("norm", "MAX"))
    x = jnp.asarray(x)
    if kind == "MAX":
        d = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    elif kind == "L1":
        d = jnp.sum(jnp.abs(x), axis=-1, keepdims=True)
    elif kind == "L2":
        d = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    else:
        raise NotImplementedError(f"Normalizer norm {kind!r}")
    return x / jnp.maximum(d, 1e-30)


@op("LinearClassifier")
def _linear_classifier(ctx, x):
    labels = ctx.attr("classlabels_ints") or ctx.attr(
        "classlabels_int64s") or [0, 1]
    coeff = np.asarray(ctx.attr("coefficients"), np.float32)
    inter = np.asarray(ctx.attr("intercepts", [0.0]), np.float32)
    k = coeff.size // max(1, np.asarray(x).shape[-1]) if _is_host(x) else \
        coeff.size // int(x.shape[-1])
    w = coeff.reshape(k, -1)
    scores = jnp.asarray(x) @ jnp.asarray(w.T) + jnp.asarray(inter)
    # reuse the shared binary expansion by aliasing the label attr
    ctx.attrs.setdefault("classlabels_int64s", list(labels))
    return _classifier_outputs(ctx, scores)


@op("LinearRegressor")
def _linear_regressor(ctx, x):
    coeff = np.asarray(ctx.attr("coefficients"), np.float32)
    inter = np.asarray(ctx.attr("intercepts", [0.0]), np.float32)
    targets = int(ctx.attr("targets", 1))
    w = coeff.reshape(targets, -1)
    y = jnp.asarray(x) @ jnp.asarray(w.T) + jnp.asarray(inter)
    return _post_transform(y, str(ctx.attr("post_transform", "NONE")))


@op("Imputer")
def _imputer(ctx, x):
    imputed = ctx.attr("imputed_value_floats")
    if imputed is None:
        imputed = ctx.attr("imputed_value_int64s")
    imputed = np.asarray(imputed, np.float32)
    replaced = ctx.attr("replaced_value_float",
                        ctx.attr("replaced_value_int64"))
    x = jnp.asarray(x)
    fill = jnp.asarray(imputed if imputed.size > 1 else imputed[0])
    if replaced is None or (isinstance(replaced, float)
                            and np.isnan(replaced)):
        bad = jnp.isnan(x)
    else:
        # a concrete replaced_value leaves NaNs untouched (ORT semantics)
        bad = x == replaced
    return jnp.where(bad, fill, x)


@op("Binarizer")
def _binarizer(ctx, x):
    thr = float(ctx.attr("threshold", 0.0))
    x = jnp.asarray(x)
    return (x > thr).astype(x.dtype)


@op("ArrayFeatureExtractor")
def _array_feature_extractor(ctx, x, idx):
    idx_np = np.asarray(idx, np.int64).reshape(-1)
    if _is_host(x):
        return np.asarray(x)[..., idx_np]
    return jnp.asarray(x)[..., jnp.asarray(idx_np)]


@op("FeatureVectorizer")
def _feature_vectorizer(ctx, *xs):
    cols = [jnp.asarray(x) for x in xs if x is not None]
    cols = [c[:, None] if c.ndim == 1 else c.reshape(c.shape[0], -1)
            for c in cols]
    return jnp.concatenate(cols, axis=1)


@op("LabelEncoder")
def _label_encoder(ctx, x):
    # int->int / int->float lookup runs on device; string sides are
    # host-only (device tensors cannot hold strings)
    keys_i = ctx.attr("keys_int64s")
    vals_i = ctx.attr("values_int64s")
    vals_f = ctx.attr("values_floats")
    if keys_i is not None and (vals_i is not None or vals_f is not None):
        keys = np.asarray(keys_i, np.int64)
        vals = np.asarray(vals_i if vals_i is not None else vals_f)
        default = ctx.attr("default_int64", ctx.attr("default_float", -1))
        lut = {int(k): v for k, v in zip(keys, vals)}
        if _is_host(x):
            flat = np.asarray(
                [lut.get(int(v), default)
                 for v in np.asarray(x).reshape(-1)])
            return flat.reshape(np.asarray(x).shape).astype(vals.dtype)
        # device path: searchsorted over sorted keys
        order = np.argsort(keys)
        sk = jnp.asarray(keys[order])
        sv = jnp.asarray(vals[order])
        pos = jnp.clip(jnp.searchsorted(sk, x), 0, len(keys) - 1)
        hit = sk[pos] == x
        return jnp.where(hit, sv[pos], jnp.asarray(default, sv.dtype))
    # string maps: host-only object arrays
    keys_s = ctx.attr("keys_strings")
    if keys_s is not None and _is_host(x):
        vals = (ctx.attr("values_int64s") or ctx.attr("values_floats")
                or ctx.attr("values_strings"))
        default = ctx.attr(
            "default_int64",
            ctx.attr("default_float", ctx.attr("default_string", "_Unused")))
        lut = dict(zip(keys_s, vals))
        arr = np.asarray(x, dtype=object).reshape(-1)
        out = np.asarray([lut.get(str(v), default) for v in arr])
        return out.reshape(np.asarray(x, dtype=object).shape)
    raise NotImplementedError(
        "LabelEncoder: string-keyed maps need host-side (object) inputs")


@op("CategoryMapper")
def _category_mapper(ctx, x):
    cats_i = np.asarray(ctx.attr("cats_int64s", []), np.int64)
    cats_s = ctx.attr("cats_strings", [])
    if _is_host(x) and np.asarray(x).dtype == object:
        lut = {str(s): int(i) for s, i in zip(cats_s, cats_i)}
        default = int(ctx.attr("default_int64", -1))
        arr = np.asarray(x, dtype=object).reshape(-1)
        return np.asarray([lut.get(str(v), default) for v in arr],
                          np.int64).reshape(np.asarray(x, object).shape)
    # int -> string direction is host-only as well
    lut_rev = {int(i): s for i, s in zip(cats_i, cats_s)}
    default_s = str(ctx.attr("default_string", "_Unused"))
    arr = np.asarray(x).reshape(-1)
    out = np.empty(arr.shape, dtype=object)
    for j, v in enumerate(arr):
        out[j] = lut_rev.get(int(v), default_s)
    return out.reshape(np.asarray(x).shape)


@op("OneHotEncoder")
def _ml_one_hot(ctx, x):
    cats = ctx.attr("cats_int64s")
    if cats is None:
        raise NotImplementedError(
            "OneHotEncoder: only cats_int64s is supported")
    cats = jnp.asarray(np.asarray(cats, np.int64))
    x = jnp.asarray(x)
    hot = (x[..., None] == cats).astype(jnp.float32)
    if not int(ctx.attr("zeros", 1)):
        pass  # zeros=0 would demand an error on unknown; keep permissive
    return hot


# -- SVM family (sklearn-converted exports) --------------------------------

def _svm_kernel(x, sv, kind: str, gamma: float, coef0: float,
                degree: float):
    """Batched kernel matrix [N, S] — one MXU gram matmul per call."""
    x = jnp.asarray(x, jnp.float32)
    sv = jnp.asarray(sv, jnp.float32)
    dot = x @ sv.T
    if kind == "LINEAR":
        return dot
    if kind == "POLY":
        return (gamma * dot + coef0) ** degree
    if kind == "RBF":
        d2 = ((x * x).sum(-1)[:, None] - 2.0 * dot
              + (sv * sv).sum(-1)[None, :])
        return jnp.exp(-gamma * d2)
    if kind == "SIGMOID":
        return jnp.tanh(gamma * dot + coef0)
    raise NotImplementedError(f"SVM kernel_type {kind!r}")


def _svm_params(ctx):
    kp = [float(v) for v in (ctx.attr("kernel_params") or [])]
    gamma = kp[0] if len(kp) > 0 else 1.0
    coef0 = kp[1] if len(kp) > 1 else 0.0
    degree = kp[2] if len(kp) > 2 else 3.0
    return str(ctx.attr("kernel_type", "LINEAR")), gamma, coef0, degree


@op("SVMClassifier")
def _svm_classifier(ctx, x):
    """One-vs-one SVC (support-vector mode) or linear-weight mode.
    Outputs (label, scores): scores are the k*(k-1)/2 ovo decision
    values in (0,1),(0,2),..,(1,2).. order, with the libsvm/onnxruntime
    sign convention — positive votes the FIRST class of the pair.
    (sklearn's BINARY decision_function is the negation of libsvm's
    (0,1) value; skl2onnx compensates by negating binary dual coefs +
    rho at export, and the parity tests mirror that.)"""
    if ctx.attr("prob_a"):
        raise NotImplementedError(
            "SVMClassifier Platt-scaled probabilities (prob_a/prob_b) "
            "are not supported; re-export without probability=True")
    labels_i = ctx.attr("classlabels_int64s")
    if ctx.attr("classlabels_strings"):
        raise NotImplementedError(
            "string class labels need host-side mapping; use int64 labels")
    kind, gamma, coef0, degree = _svm_params(ctx)
    sv = np.asarray(ctx.attr("support_vectors") or [], np.float32)
    coefs = np.asarray(ctx.attr("coefficients"), np.float32)
    rho = np.asarray(ctx.attr("rho"), np.float32)
    x = jnp.asarray(x, jnp.float32)

    if sv.size == 0:
        # linear-weight mode: row count comes from the coefficient size;
        # a binary export carries ONE weight row whose RAW decision
        # thresholds at 0 — expand to (-s, s) so argmax is that
        # threshold (the 0.5-probability expansion would misclassify
        # raw-margin scores)
        k_rows = max(1, coefs.size // int(x.shape[-1]))
        labels = np.asarray(labels_i if labels_i else [0, 1], np.int64)
        w = coefs.reshape(k_rows, -1)
        scores = x @ jnp.asarray(w.T) + jnp.asarray(rho)
        if k_rows == 1 and len(labels) == 2:
            scores = jnp.concatenate([-scores, scores], axis=-1)
        label = jnp.asarray(labels)[jnp.argmax(scores, axis=-1)]
        return label, _post_transform(
            scores, str(ctx.attr("post_transform", "NONE")))

    vpc = np.asarray(ctx.attr("vectors_per_class"), np.int64)
    k = len(vpc)
    labels = np.asarray(labels_i if labels_i else list(range(k)), np.int64)
    n_sv = int(vpc.sum())
    sv = sv.reshape(n_sv, -1)
    dual = coefs.reshape(k - 1, n_sv)          # [k-1, n_sv] dual coefs
    starts = np.concatenate([[0], np.cumsum(vpc)])
    K = _svm_kernel(x, sv, kind, gamma, coef0, degree)   # [N, n_sv]

    decisions = []
    votes = jnp.zeros((x.shape[0], k), jnp.int32)
    p = 0
    for i in range(k):
        for j in range(i + 1, k):
            si, ei = int(starts[i]), int(starts[i + 1])
            sj, ej = int(starts[j]), int(starts[j + 1])
            dec = (K[:, si:ei] @ jnp.asarray(dual[j - 1, si:ei])
                   + K[:, sj:ej] @ jnp.asarray(dual[i, sj:ej])
                   + float(rho[p]))
            decisions.append(dec)
            win = (dec > 0)
            votes = votes.at[:, i].add(win.astype(jnp.int32))
            votes = votes.at[:, j].add((~win).astype(jnp.int32))
            p += 1
    scores = jnp.stack(decisions, axis=-1)       # [N, k*(k-1)/2]
    label = jnp.asarray(labels)[jnp.argmax(votes, axis=-1)]
    return label, _post_transform(
        scores, str(ctx.attr("post_transform", "NONE")))


@op("SVMRegressor")
def _svm_regressor(ctx, x):
    kind, gamma, coef0, degree = _svm_params(ctx)
    coefs = np.asarray(ctx.attr("coefficients"), np.float32)
    rho = float(np.asarray(ctx.attr("rho"), np.float32).reshape(-1)[0])
    n_sup = int(ctx.attr("n_supports", 0))
    x = jnp.asarray(x, jnp.float32)
    if n_sup == 0:
        y = x @ jnp.asarray(coefs.reshape(-1)) + rho
    else:
        sv = np.asarray(ctx.attr("support_vectors"),
                        np.float32).reshape(n_sup, -1)
        K = _svm_kernel(x, sv, kind, gamma, coef0, degree)
        y = K @ jnp.asarray(coefs.reshape(-1)) + rho
    if int(ctx.attr("one_class", 0)):
        # OneClassSVM exports: onnxruntime maps the score to +/-1
        y = jnp.where(y > 0, 1.0, -1.0)
    y = _post_transform(y, str(ctx.attr("post_transform", "NONE")))
    return y[:, None]


@op("DictVectorizer")
def _dict_vectorizer(ctx, x):
    """map<key, value> rows -> dense columns per the vocabulary order.
    Maps only exist host-side (object arrays of dicts)."""
    vocab = (ctx.attr("string_vocabulary")
             or ctx.attr("int64_vocabulary"))
    if vocab is None:
        raise ValueError("DictVectorizer needs a vocabulary attribute")
    if not _is_host(x):
        raise NotImplementedError(
            "DictVectorizer consumes map values, which only exist "
            "host-side; feed object rows of dicts")
    rows = np.asarray(x, dtype=object).reshape(-1)
    out = np.zeros((len(rows), len(vocab)), np.float32)
    index = {k: i for i, k in enumerate(vocab)}
    for r, d in enumerate(rows):
        for key, val in dict(d).items():
            i = index.get(key)
            if i is not None:
                out[r, i] = val
    return out
