"""Model -> ONNX converters (the onnxmltools role, in-framework).

The reference's ONNX notebook converts a trained LightGBM booster with
``onnxmltools.convert.convert_lightgbm`` and scores the result through
ONNXModel (ref: notebooks/ONNX - Inference on Spark.ipynb). This
environment has no onnxmltools/onnx, so the converter is native: it
walks the Booster's stacked tree arrays and emits an ``ai.onnx.ml``
TreeEnsembleClassifier/Regressor graph (consumed back by
:mod:`synapseml_tpu.onnx.ml_ops`, or by onnxruntime anywhere else —
the output is standard ONNX).

Semantics map 1:1: every split is ``BRANCH_LEQ`` with the false branch
taken on missing values (NaN comparisons are False in the engine —
see gbdt/grower.py predict_tree), leaf weights carry the tree weight
(rf averaging / dart renormalization folded in), ``base_values`` carries
the init score, and the LightGBM ``sigmoid`` parameter is folded into
weights so the standard LOGISTIC post-transform reproduces
``Booster.predict`` exactly.
"""
from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

from synapseml_tpu.gbdt.boosting import Booster
from synapseml_tpu.onnx.builder import GraphBuilder


def _booster_of(model) -> Booster:
    if isinstance(model, Booster):
        return model
    booster = getattr(model, "booster", None)
    if booster is None:
        raise TypeError(
            f"expected a Booster or fitted LightGBM model, got {type(model)}")
    return booster


def convert_lightgbm(model, input_size: Optional[int] = None,
                     name: str = "lightgbm") -> bytes:
    """Serialize a trained GBDT model as an ONNX tree-ensemble graph.

    Matches ``Booster.predict`` (sigmoid/softmax probabilities for
    classifiers, raw scores for regressors — link functions like
    poisson's exp are not expressible in the ONNX tree ops and raise).
    Respects ``best_iteration`` the way ``predict`` does.
    """
    b = _booster_of(model)
    if getattr(b, "trees_cat", None) is not None:
        raise NotImplementedError(
            "ONNX tree ensembles cannot express LightGBM set-membership "
            "categorical splits; convert a numerically-split model")
    k = max(1, b.num_class)
    t_total = b.num_trees
    if b.best_iteration >= 0:
        t_total = min(t_total, (b.best_iteration + 1) * k)
    n_features = b.num_features if b.num_features > 0 else int(input_size or 0)
    if n_features <= 0:
        raise ValueError("input_size is required when the booster does not "
                         "record num_features")

    objective = b.params.objective
    is_classifier = objective in (
        "binary", "binary_logloss", "multiclass", "softmax")
    if objective in ("poisson", "tweedie"):
        raise NotImplementedError(
            f"{objective}: the exp link is not expressible in ONNX tree "
            f"ensembles; export raw scores via a regression objective")
    if objective == "multiclassova":
        raise NotImplementedError(
            "multiclassova: per-class sigmoid + renormalization has no "
            "ONNX post_transform equivalent (LOGISTIC does not renormalize)")
    sigmoid = float(getattr(b.params, "sigmoid", 1.0) or 1.0)
    scale = sigmoid if objective in ("binary", "binary_logloss") else 1.0

    nodes_treeids, nodes_nodeids, nodes_featureids = [], [], []
    nodes_modes, nodes_values = [], []
    nodes_true, nodes_false = [], []
    w_tree, w_node, w_id, w_val = [], [], [], []

    feat = np.asarray(b.trees_feature)
    thr = np.asarray(b.trees_threshold)
    left = np.asarray(b.trees_left)
    right = np.asarray(b.trees_right)
    value = np.asarray(b.trees_value)
    tw = np.asarray(b.tree_weights, dtype=np.float64).copy()
    if b.params.boosting_type == "rf" and t_total > 0:
        # rf margins average over the trees actually exported; a model
        # truncated at best_iteration must renormalize from 1/T_total to
        # 1/T_kept, exactly as Booster._raw_scores does
        tw[:] = 1.0 / max(t_total // k, 1)
    m = feat.shape[1]

    for t in range(t_total):
        out_id = (t % k) if (is_classifier and k > 1) else 0
        for n in range(m):
            nodes_treeids.append(t)
            nodes_nodeids.append(n)
            if feat[t, n] < 0:  # leaf
                nodes_featureids.append(0)
                nodes_modes.append("LEAF")
                nodes_values.append(0.0)
                nodes_true.append(n)
                nodes_false.append(n)
                w_tree.append(t)
                w_node.append(n)
                w_id.append(out_id)
                w_val.append(float(value[t, n]) * float(tw[t]) * scale)
            else:
                nodes_featureids.append(int(feat[t, n]))
                nodes_modes.append("BRANCH_LEQ")
                nodes_values.append(float(thr[t, n]))
                nodes_true.append(int(left[t, n]))
                nodes_false.append(int(right[t, n]))

    g = GraphBuilder(name=name, opset=17)
    x = g.add_input("input", np.float32, ["N", n_features])
    common = dict(
        nodes_treeids=nodes_treeids, nodes_nodeids=nodes_nodeids,
        nodes_featureids=nodes_featureids, nodes_modes=nodes_modes,
        nodes_values=[float(v) for v in nodes_values],
        nodes_truenodeids=nodes_true, nodes_falsenodeids=nodes_false,
        nodes_missing_value_tracks_true=[0] * len(nodes_treeids),
    )
    init = float(b.init_score)
    if is_classifier:
        n_labels = k if k > 1 else 2
        post = "SOFTMAX" if k > 1 else "LOGISTIC"
        base = [init] * k if k > 1 else [init * scale]
        # a fitted classification model remembers the original labels it
        # remapped to dense ids; export those so the ONNX 'label' output
        # agrees with model.transform's prediction column
        labels = list(range(n_labels))
        lv = getattr(model, "label_values", None)
        if lv is not None and len(lv) >= n_labels:
            if all(float(v) == int(v) for v in lv[:n_labels]):
                labels = [int(v) for v in lv[:n_labels]]
            else:
                warnings.warn(
                    f"label_values {list(lv[:n_labels])} are not integral; "
                    f"classlabels_int64s cannot express them, so the ONNX "
                    f"'label' output speaks dense indices 0..{n_labels - 1} "
                    f"instead of the original labels",
                    RuntimeWarning, stacklevel=2)
        g.add_node(
            "TreeEnsembleClassifier", [x],
            outputs=["label", "probabilities"], domain="ai.onnx.ml",
            class_treeids=w_tree, class_nodeids=w_node, class_ids=w_id,
            class_weights=[float(v) for v in w_val],
            classlabels_int64s=labels,
            post_transform=post, base_values=[float(v) for v in base],
            **common)
        g.add_output("label", np.int64, ["N"])
        g.add_output("probabilities", np.float32, ["N", n_labels])
    else:
        g.add_node(
            "TreeEnsembleRegressor", [x],
            outputs=["variable"], domain="ai.onnx.ml",
            target_treeids=w_tree, target_nodeids=w_node, target_ids=w_id,
            target_weights=[float(v) for v in w_val], n_targets=1,
            aggregate_function="SUM", post_transform="NONE",
            base_values=[init], **common)
        g.add_output("variable", np.float32, ["N", 1])
    return g.to_bytes(producer="synapseml_tpu.onnx.convert")
