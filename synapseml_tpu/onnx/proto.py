"""Self-contained ONNX protobuf wire codec (no ``onnx``/``protobuf`` deps).

The reference executes ONNX graphs through the onnxruntime JNI
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala:173-193);
this framework instead *imports* the graph and re-lowers it to XLA
(see :mod:`synapseml_tpu.onnx.importer`). That requires parsing the ``.onnx``
protobuf container, which this module does with a hand-rolled wire-format
codec: protobuf field numbers are frozen forever by compatibility rules, so the
small schema below (ModelProto / GraphProto / NodeProto / TensorProto /
AttributeProto / ValueInfoProto and friends) is stable across every ONNX
release. Both directions (decode for import, encode for export/test fixtures)
are supported.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Wire-format primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_LEN = 2
_WIRE_FIXED32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _write_varint(out: bytearray, value: int):
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit, 10-byte encoding
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag_signed(value: int) -> int:
    """Interpret an up-to-64-bit varint as a signed int64 (not zigzag —
    protobuf int64 fields use plain two's complement)."""
    value &= (1 << 64) - 1
    if value >= 1 << 63:
        value -= 1 << 64
    return value


# ---------------------------------------------------------------------------
# Declarative message schema
# ---------------------------------------------------------------------------

class F:
    """Field spec: wire number -> (python name, kind, repeated)."""

    __slots__ = ("num", "name", "kind", "repeated", "message")

    def __init__(self, num: int, name: str, kind: str, repeated: bool = False,
                 message: Optional[str] = None):
        self.num = num
        self.name = name
        self.kind = kind  # int64 | float | double | bytes | string | message
        self.repeated = repeated
        self.message = message  # schema key for kind == "message"


_SCHEMAS: Dict[str, List[F]] = {
    "ModelProto": [
        F(1, "ir_version", "int64"),
        F(2, "producer_name", "string"),
        F(3, "producer_version", "string"),
        F(4, "domain", "string"),
        F(5, "model_version", "int64"),
        F(6, "doc_string", "string"),
        F(7, "graph", "message", message="GraphProto"),
        F(8, "opset_import", "message", repeated=True, message="OperatorSetIdProto"),
    ],
    "OperatorSetIdProto": [
        F(1, "domain", "string"),
        F(2, "version", "int64"),
    ],
    "GraphProto": [
        F(1, "node", "message", repeated=True, message="NodeProto"),
        F(2, "name", "string"),
        F(5, "initializer", "message", repeated=True, message="TensorProto"),
        F(10, "doc_string", "string"),
        F(11, "input", "message", repeated=True, message="ValueInfoProto"),
        F(12, "output", "message", repeated=True, message="ValueInfoProto"),
        F(13, "value_info", "message", repeated=True, message="ValueInfoProto"),
    ],
    "NodeProto": [
        F(1, "input", "string", repeated=True),
        F(2, "output", "string", repeated=True),
        F(3, "name", "string"),
        F(4, "op_type", "string"),
        F(5, "attribute", "message", repeated=True, message="AttributeProto"),
        F(6, "doc_string", "string"),
        F(7, "domain", "string"),
    ],
    "AttributeProto": [
        F(1, "name", "string"),
        F(2, "f", "float"),
        F(3, "i", "int64"),
        F(4, "s", "bytes"),
        F(5, "t", "message", message="TensorProto"),
        F(6, "g", "message", message="GraphProto"),
        F(7, "floats", "float", repeated=True),
        F(8, "ints", "int64", repeated=True),
        F(9, "strings", "bytes", repeated=True),
        F(10, "tensors", "message", repeated=True, message="TensorProto"),
        F(11, "graphs", "message", repeated=True, message="GraphProto"),
        F(20, "type", "int64"),
    ],
    "TensorProto": [
        F(1, "dims", "int64", repeated=True),
        F(2, "data_type", "int64"),
        F(4, "float_data", "float", repeated=True),
        F(5, "int32_data", "int64", repeated=True),
        F(6, "string_data", "bytes", repeated=True),
        F(7, "int64_data", "int64", repeated=True),
        F(8, "name", "string"),
        F(9, "raw_data", "bytes"),
        F(10, "double_data", "double", repeated=True),
        F(11, "uint64_data", "int64", repeated=True),
        F(12, "doc_string", "string"),
        F(13, "external_data", "message", repeated=True,
          message="StringStringEntryProto"),
        F(14, "data_location", "int64"),  # 0 DEFAULT, 1 EXTERNAL
    ],
    "StringStringEntryProto": [
        F(1, "key", "string"),
        F(2, "value", "string"),
    ],
    "ValueInfoProto": [
        F(1, "name", "string"),
        F(2, "type", "message", message="TypeProto"),
        F(3, "doc_string", "string"),
    ],
    "TypeProto": [
        F(1, "tensor_type", "message", message="TypeProto.Tensor"),
    ],
    "TypeProto.Tensor": [
        F(1, "elem_type", "int64"),
        F(2, "shape", "message", message="TensorShapeProto"),
    ],
    "TensorShapeProto": [
        F(1, "dim", "message", repeated=True, message="TensorShapeProto.Dimension"),
    ],
    "TensorShapeProto.Dimension": [
        F(1, "dim_value", "int64"),
        F(2, "dim_param", "string"),
    ],
}

# AttributeProto.type enum values (onnx AttributeProto.AttributeType)
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS, ATTR_TENSORS, ATTR_GRAPHS = 6, 7, 8, 9, 10


class Msg:
    """Generic decoded protobuf message; fields become attributes."""

    __slots__ = ("_schema", "__dict__")

    def __init__(self, schema: str, **kwargs):
        self._schema = schema
        for f in _SCHEMAS[schema]:
            setattr(self, f.name, [] if f.repeated else None)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        body = {f.name: getattr(self, f.name) for f in _SCHEMAS[self._schema]
                if getattr(self, f.name) not in (None, [])}
        return f"{self._schema}({body})"


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def decode(schema: str, buf: bytes) -> Msg:
    fields = {f.num: f for f in _SCHEMAS[schema]}
    msg = Msg(schema)
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        num, wire = tag >> 3, tag & 7
        f = fields.get(num)
        if f is None:  # unknown field: skip
            pos = _skip(buf, pos, wire)
            continue
        if f.kind == "message":
            assert wire == _WIRE_LEN
            ln, pos = _read_varint(buf, pos)
            sub = decode(f.message, buf[pos:pos + ln])
            pos += ln
            _store(msg, f, sub)
        elif f.kind in ("bytes", "string"):
            assert wire == _WIRE_LEN
            ln, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + ln]
            pos += ln
            _store(msg, f, raw.decode("utf-8", "replace") if f.kind == "string" else bytes(raw))
        elif f.kind == "int64":
            if wire == _WIRE_LEN:  # packed repeated
                ln, pos = _read_varint(buf, pos)
                stop = pos + ln
                while pos < stop:
                    v, pos = _read_varint(buf, pos)
                    _store(msg, f, _zigzag_signed(v))
            else:
                v, pos = _read_varint(buf, pos)
                _store(msg, f, _zigzag_signed(v))
        elif f.kind == "float":
            if wire == _WIRE_LEN:
                ln, pos = _read_varint(buf, pos)
                vals = struct.unpack_from(f"<{ln // 4}f", buf, pos)
                pos += ln
                for v in vals:
                    _store(msg, f, v)
            else:
                (v,) = struct.unpack_from("<f", buf, pos)
                pos += 4
                _store(msg, f, v)
        elif f.kind == "double":
            if wire == _WIRE_LEN:
                ln, pos = _read_varint(buf, pos)
                vals = struct.unpack_from(f"<{ln // 8}d", buf, pos)
                pos += ln
                for v in vals:
                    _store(msg, f, v)
            else:
                (v,) = struct.unpack_from("<d", buf, pos)
                pos += 8
                _store(msg, f, v)
        else:
            raise ValueError(f"unhandled kind {f.kind}")
    return msg


def _store(msg: Msg, f: F, value: Any):
    if f.repeated:
        getattr(msg, f.name).append(value)
    else:
        setattr(msg, f.name, value)


def _skip(buf: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(buf, pos)
    elif wire == _WIRE_FIXED64:
        pos += 8
    elif wire == _WIRE_FIXED32:
        pos += 4
    elif wire == _WIRE_LEN:
        ln, pos = _read_varint(buf, pos)
        pos += ln
    else:
        raise ValueError(f"cannot skip wire type {wire}")
    return pos


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------

def encode(msg: Msg) -> bytes:
    out = bytearray()
    for f in _SCHEMAS[msg._schema]:
        val = getattr(msg, f.name)
        if val is None or (f.repeated and not val):
            continue
        values = val if f.repeated else [val]
        if f.kind == "message":
            for v in values:
                payload = encode(v)
                _write_varint(out, (f.num << 3) | _WIRE_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
        elif f.kind in ("bytes", "string"):
            for v in values:
                raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                _write_varint(out, (f.num << 3) | _WIRE_LEN)
                _write_varint(out, len(raw))
                out.extend(raw)
        elif f.kind == "int64":
            if f.repeated and len(values) > 1:
                payload = bytearray()
                for v in values:
                    _write_varint(payload, int(v))
                _write_varint(out, (f.num << 3) | _WIRE_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
            else:
                for v in values:
                    _write_varint(out, (f.num << 3) | _WIRE_VARINT)
                    _write_varint(out, int(v))
        elif f.kind == "float":
            if f.repeated:
                payload = struct.pack(f"<{len(values)}f", *values)
                _write_varint(out, (f.num << 3) | _WIRE_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
            else:
                _write_varint(out, (f.num << 3) | _WIRE_FIXED32)
                out.extend(struct.pack("<f", values[0]))
        elif f.kind == "double":
            if f.repeated:
                payload = struct.pack(f"<{len(values)}d", *values)
                _write_varint(out, (f.num << 3) | _WIRE_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
            else:
                _write_varint(out, (f.num << 3) | _WIRE_FIXED64)
                out.extend(struct.pack("<d", values[0]))
    return bytes(out)


# ---------------------------------------------------------------------------
# TensorProto <-> numpy
# ---------------------------------------------------------------------------

# onnx TensorProto.DataType enum
TENSOR_DTYPES: Dict[int, Any] = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}
NP_TO_ONNX = {np.dtype(v): k for k, v in TENSOR_DTYPES.items()}
_NP_TO_ONNX = NP_TO_ONNX  # back-compat alias
DTYPE_STRING = 8
DTYPE_BFLOAT16 = 16

try:  # bfloat16 comes with jax's ml_dtypes (always present in this env)
    import ml_dtypes
    TENSOR_DTYPES[DTYPE_BFLOAT16] = ml_dtypes.bfloat16
    _NP_TO_ONNX[np.dtype(ml_dtypes.bfloat16)] = DTYPE_BFLOAT16
except ImportError:  # pragma: no cover
    pass


def tensor_to_numpy(t: Msg) -> np.ndarray:
    if int(t.data_location or 0) == 1:  # EXTERNAL, unresolved
        raise ValueError(
            f"tensor {t.name!r} stores its data in an external file "
            f"({dict((e.key, e.value) for e in t.external_data)}); load the "
            "model via import_model(path)/load_model(path) so sidecar files "
            "resolve relative to the model directory, or pass base_dir=")
    dims = tuple(int(d) for d in t.dims)
    dt = int(t.data_type or 0)
    if dt == DTYPE_STRING:
        arr = np.array([s.decode("utf-8", "replace") for s in t.string_data],
                       dtype=object)
        return arr.reshape(dims)
    np_dtype = TENSOR_DTYPES.get(dt)
    if np_dtype is None:
        raise ValueError(f"unsupported tensor data_type {dt}")
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=np_dtype).reshape(dims).copy()
    # typed repeated fields
    if dt == 1:
        data = np.asarray(t.float_data, dtype=np.float32)
    elif dt == 11:
        data = np.asarray(t.double_data, dtype=np.float64)
    elif dt == 7:
        data = np.asarray(t.int64_data, dtype=np.int64)
    elif dt in (12, 13):
        data = np.asarray(t.uint64_data, dtype=np.uint64).astype(np_dtype)
    elif dt == 10:  # float16 stored bit-cast in int32_data
        data = np.asarray(t.int32_data, dtype=np.uint16).view(np.float16)
    elif dt == DTYPE_BFLOAT16:
        data = np.asarray(t.int32_data, dtype=np.uint16).view(np_dtype)
    else:  # int32/int16/int8/uint8/uint16/bool ride int32_data
        data = np.asarray(t.int32_data, dtype=np.int64).astype(np_dtype)
    return data.reshape(dims)


def numpy_to_tensor(arr: np.ndarray, name: str = "") -> Msg:
    arr = np.asarray(arr)  # NOT ascontiguousarray: that promotes 0-d to 1-d
    t = Msg("TensorProto")
    t.name = name
    t.dims = list(arr.shape)
    if arr.dtype == object or arr.dtype.kind in ("U", "S"):
        t.data_type = DTYPE_STRING
        t.string_data = [str(s).encode("utf-8") for s in arr.reshape(-1)]
        return t
    dt = _NP_TO_ONNX.get(arr.dtype)
    if dt is None:
        raise ValueError(f"unsupported numpy dtype {arr.dtype}")
    t.data_type = dt
    t.raw_data = arr.tobytes()
    return t


# ---------------------------------------------------------------------------
# Attribute helpers
# ---------------------------------------------------------------------------

def attr_value(a: Msg) -> Any:
    """Extract the python value of an AttributeProto."""
    # proto3 serializers omit zero-valued scalar fields entirely, so a typed
    # attribute may arrive with its value field unset: default, don't crash.
    ty = int(a.type or 0)
    if ty == ATTR_FLOAT:
        return float(a.f or 0.0)
    if ty == ATTR_INT:
        return int(a.i or 0)
    if ty == ATTR_STRING:
        return (a.s or b"").decode("utf-8", "replace")
    if ty == ATTR_TENSOR:
        return tensor_to_numpy(a.t)
    if ty == ATTR_GRAPH:
        return a.g
    if ty == ATTR_FLOATS:
        return [float(v) for v in a.floats]
    if ty == ATTR_INTS:
        return [int(v) for v in a.ints]
    if ty == ATTR_STRINGS:
        return [s.decode("utf-8", "replace") for s in a.strings]
    if ty == ATTR_TENSORS:
        return [tensor_to_numpy(t) for t in a.tensors]
    # untyped (some emitters omit .type): best effort
    if a.floats:
        return list(a.floats)
    if a.ints:
        return list(a.ints)
    if a.s:
        return a.s.decode("utf-8", "replace")
    if a.t is not None:
        return tensor_to_numpy(a.t)
    if a.i is not None:
        return int(a.i)
    if a.f is not None:
        return float(a.f)
    return None


def node_attrs(node: Msg) -> Dict[str, Any]:
    return {a.name: attr_value(a) for a in node.attribute}


def make_attr(name: str, value: Any) -> Msg:
    a = Msg("AttributeProto")
    a.name = name
    if isinstance(value, bool):
        a.type, a.i = ATTR_INT, int(value)
    elif isinstance(value, int):
        a.type, a.i = ATTR_INT, value
    elif isinstance(value, float):
        a.type, a.f = ATTR_FLOAT, value
    elif isinstance(value, str):
        a.type, a.s = ATTR_STRING, value.encode("utf-8")
    elif isinstance(value, np.ndarray):
        a.type, a.t = ATTR_TENSOR, numpy_to_tensor(value)
    elif isinstance(value, Msg) and value._schema == "GraphProto":
        a.type, a.g = ATTR_GRAPH, value
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            a.type, a.floats = ATTR_FLOATS, [float(v) for v in value]
        elif value and isinstance(value[0], str):
            a.type, a.strings = ATTR_STRINGS, [v.encode() for v in value]
        else:
            a.type, a.ints = ATTR_INTS, [int(v) for v in value]
    else:
        raise TypeError(f"cannot encode attribute {name}={value!r}")
    return a


# ---------------------------------------------------------------------------
# Model container helpers
# ---------------------------------------------------------------------------

def _walk_tensors(graph: Msg):
    """Yield every TensorProto reachable from ``graph`` (initializers and
    attribute tensors, recursing through subgraphs)."""
    for t in graph.initializer:
        yield t
    for node in graph.node:
        for a in node.attribute or []:
            if a.t is not None:
                yield a.t
            for t in a.tensors or []:
                yield t
            if a.g is not None:
                yield from _walk_tensors(a.g)
            for sg in a.graphs or []:
                yield from _walk_tensors(sg)


def resolve_external_data(model: Msg, base_dir: str) -> int:
    """Load ``data_location: EXTERNAL`` tensor payloads from their sidecar
    files into ``raw_data`` in place (the layout ``onnx.save_model(...,
    save_as_external_data=True)`` and large torch exports produce: per-tensor
    ``location``/``offset``/``length`` entries naming a file relative to the
    model directory). Returns the number of tensors resolved. Parity target:
    the reference hands arbitrary user model files to onnxruntime, which
    resolves sidecars natively (deep-learning/.../onnx/ONNXModel.scala:173-193).
    """
    import os

    # realpath: a symlink inside the model dir must not smuggle reads out
    base_dir = os.path.realpath(base_dir or ".")
    handles: Dict[str, Any] = {}
    resolved = 0
    try:
        for t in _walk_tensors(model.graph) if model.graph is not None else ():
            if int(t.data_location or 0) != 1:
                continue
            info = {e.key: e.value for e in t.external_data}
            loc = info.get("location")
            if not loc:
                raise ValueError(
                    f"external tensor {t.name!r} has no location entry")
            full = os.path.realpath(os.path.join(base_dir, loc))
            if not (full == base_dir
                    or full.startswith(base_dir + os.sep)):
                raise ValueError(
                    f"external tensor {t.name!r} location {loc!r} escapes "
                    f"the model directory {base_dir!r}")
            fh = handles.get(full)
            if fh is None:
                fh = handles[full] = open(full, "rb")
            offset = int(info.get("offset", 0) or 0)
            length = info.get("length")
            fh.seek(offset)
            data = fh.read(int(length)) if length is not None else fh.read()
            if length is not None and len(data) != int(length):
                raise ValueError(
                    f"external tensor {t.name!r}: wanted {length} bytes at "
                    f"offset {offset} of {loc!r}, file had {len(data)}")
            t.raw_data = data
            t.data_location = 0
            t.external_data = []
            resolved += 1
    finally:
        for fh in handles.values():
            fh.close()
    return resolved


def load_model(path_or_bytes, base_dir: Optional[str] = None) -> Msg:
    """Parse a ``.onnx`` file (or raw bytes) into a ModelProto Msg.

    External-data tensors are resolved against the model's own directory
    (or ``base_dir`` when raw bytes are given)."""
    import os

    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as fh:
            data = fh.read()
        if base_dir is None:
            base_dir = os.path.dirname(os.path.abspath(path_or_bytes))
    model = decode("ModelProto", data)
    if base_dir is not None:
        resolve_external_data(model, base_dir)
    return model


def save_model(model: Msg, path: str, external_data_threshold: Optional[int] = None):
    """Serialize ``model`` to ``path``. With ``external_data_threshold``,
    initializers of at least that many payload bytes move to one sidecar
    ``<model>.data`` file (the standard ``save_as_external_data`` layout:
    location/offset/length entries, 64-byte-aligned offsets)."""
    import os

    undo = []  # (tensor, raw, external_data, data_location) — the caller's
    # in-memory model must come back untouched after serialization
    try:
        if external_data_threshold is not None and model.graph is not None:
            loc = os.path.basename(path) + ".data"
            sidecar = os.path.join(os.path.dirname(os.path.abspath(path)), loc)
            offset = 0
            chunks = []
            for t in _walk_tensors(model.graph):
                if not t.raw_data or len(t.raw_data) < external_data_threshold:
                    continue
                offset = (offset + 63) & ~63  # align like onnx's writer
                entries = []
                for k, v in (("location", loc), ("offset", str(offset)),
                             ("length", str(len(t.raw_data)))):
                    e = Msg("StringStringEntryProto")
                    e.key, e.value = k, v
                    entries.append(e)
                chunks.append((offset, t.raw_data))
                offset += len(t.raw_data)
                undo.append((t, t.raw_data, t.external_data, t.data_location))
                t.external_data = entries
                t.data_location = 1
                t.raw_data = b""
            if chunks:
                with open(sidecar, "wb") as fh:
                    for off, payload in chunks:
                        fh.seek(off)
                        fh.write(payload)
        with open(path, "wb") as fh:
            fh.write(encode(model))
    finally:
        for t, raw, ext, dl in undo:
            t.raw_data, t.external_data, t.data_location = raw, ext, dl
