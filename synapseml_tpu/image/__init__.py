"""Image subsystem: jax op pipeline, stages, IO, transfer-learning featurizer.

TPU-native rebuild of the reference's opencv module + core image stages
(SURVEY.md §2.4 image stages, §2.8 ImageTransformer/ImageSetAugmenter,
§2.6 ImageFeaturizer).
"""
from synapseml_tpu.image import ops  # noqa: F401
from synapseml_tpu.image.featurizer import ImageFeaturizer  # noqa: F401
from synapseml_tpu.image.reader import (  # noqa: F401
    decode_image,
    from_spark_layout,
    read_image_files,
    to_spark_layout,
)
from synapseml_tpu.image.transformer import (  # noqa: F401
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)
