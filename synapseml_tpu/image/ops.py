"""Image ops: jax implementations of the reference's OpenCV stage set.

The reference pipelines OpenCV ``Mat`` operations described by parameter maps
(ref: opencv/src/main/scala/com/microsoft/ml/spark/opencv/ImageTransformer.scala:38-275).
Here each op is a pure function on an HWC float32 array, so a stage pipeline
composes into one jit-compiled XLA program per input shape — filters lower to
depthwise convolutions that XLA fuses, instead of per-image native calls.

Stage names and parameter keys are kept byte-compatible with the reference
("resize", "crop", "centercrop", "colorformat", "blur", "threshold",
"gaussiankernel", "flip" with the same keys), so reference pipelines translate
unmodified.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# OpenCV constant parity (the reference exposes raw cv2 enums)
COLOR_BGR2GRAY = 6
COLOR_RGB2GRAY = 7
COLOR_BGR2RGB = 4
COLOR_RGB2BGR = 4
COLOR_GRAY2BGR = 8

THRESH_BINARY = 0
THRESH_BINARY_INV = 1
THRESH_TRUNC = 2
THRESH_TOZERO = 3
THRESH_TOZERO_INV = 4

FLIP_UP_DOWN = 0
FLIP_LEFT_RIGHT = 1
FLIP_BOTH = -1


def resize(img: jnp.ndarray, height: int = None, width: int = None,
           size: int = None, keep_aspect_ratio: bool = False) -> jnp.ndarray:
    """Bilinear resize; ``size`` + keepAspectRatio resizes the shorter side
    (ref: ImageTransformer.scala:64-92)."""
    h, w = img.shape[0], img.shape[1]
    if size is not None:
        if keep_aspect_ratio:
            ratio = size / min(h, w)
            th, tw = int(round(ratio * h)), int(round(ratio * w))
        else:
            th = tw = int(size)
    else:
        th, tw = int(height), int(width)
    out_shape = (th, tw) + img.shape[2:]
    return jax.image.resize(img, out_shape, method="linear")


def crop(img: jnp.ndarray, x: int, y: int, height: int, width: int) -> jnp.ndarray:
    # reference Rect(x, y, width, height): x = column offset, y = row offset
    return img[y:y + height, x:x + width]


def center_crop(img: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    h, w = img.shape[0], img.shape[1]
    ch, cw = min(height, h), min(width, w)
    mid_y, mid_x = h // 2, w // 2
    y0, x0 = mid_y - ch // 2, mid_x - cw // 2
    return img[y0:y0 + ch, x0:x0 + cw]


def color_format(img: jnp.ndarray, format: int) -> jnp.ndarray:
    if format in (COLOR_BGR2GRAY, COLOR_RGB2GRAY):
        # ITU-R BT.601 luma (what OpenCV uses)
        wts = jnp.array([0.114, 0.587, 0.299]) if format == COLOR_BGR2GRAY \
            else jnp.array([0.299, 0.587, 0.114])
        gray = jnp.tensordot(img[..., :3], wts.astype(img.dtype), axes=[[-1], [0]])
        return gray[..., None]
    if format == COLOR_BGR2RGB:  # == RGB2BGR: channel reversal
        return img[..., ::-1]
    if format == COLOR_GRAY2BGR:
        return jnp.repeat(img[..., :1], 3, axis=-1)
    raise ValueError(f"unsupported colorformat code {format}")


def flip(img: jnp.ndarray, flip_code: int) -> jnp.ndarray:
    if flip_code == FLIP_UP_DOWN:
        return img[::-1]
    if flip_code == FLIP_LEFT_RIGHT:
        return img[:, ::-1]
    return img[::-1, ::-1]


def _depthwise_filter(img: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """filter2D analogue: same-padding depthwise conv over HWC."""
    c = img.shape[-1]
    x = img.astype(jnp.float32)[None]  # NHWC
    k = jnp.broadcast_to(kernel[:, :, None, None].astype(jnp.float32),
                         kernel.shape + (1, c))
    y = lax.conv_general_dilated(
        x, k, window_strides=(1, 1), padding="SAME",
        feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y[0].astype(img.dtype)


def blur(img: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Box blur (ref Blur stage -> Imgproc.blur)."""
    kh, kw = int(height), int(width)
    kernel = jnp.full((kh, kw), 1.0 / (kh * kw))
    return _depthwise_filter(img, kernel)


def gaussian_kernel_1d(aperture_size: int, sigma: float) -> np.ndarray:
    """OpenCV getGaussianKernel: Nx1 column vector."""
    if sigma <= 0:
        sigma = 0.3 * ((aperture_size - 1) * 0.5 - 1) + 0.8
    xs = np.arange(aperture_size) - (aperture_size - 1) / 2.0
    k = np.exp(-(xs ** 2) / (2 * sigma ** 2))
    return (k / k.sum()).astype(np.float32)


def gaussian_kernel(img: jnp.ndarray, aperture_size: int, sigma: float) -> jnp.ndarray:
    """The reference applies the Nx1 getGaussianKernel via filter2D — i.e. a
    vertical-only gaussian (ref: ImageTransformer.scala:255-266). Faithful."""
    k = jnp.asarray(gaussian_kernel_1d(aperture_size, sigma))[:, None]
    return _depthwise_filter(img, k)


def threshold(img: jnp.ndarray, threshold: float, max_val: float,
              type: int = THRESH_BINARY) -> jnp.ndarray:
    t = threshold
    if type == THRESH_BINARY:
        return jnp.where(img > t, max_val, 0.0).astype(img.dtype)
    if type == THRESH_BINARY_INV:
        return jnp.where(img > t, 0.0, max_val).astype(img.dtype)
    if type == THRESH_TRUNC:
        return jnp.minimum(img, t)
    if type == THRESH_TOZERO:
        return jnp.where(img > t, img, 0.0).astype(img.dtype)
    if type == THRESH_TOZERO_INV:
        return jnp.where(img > t, 0.0, img).astype(img.dtype)
    raise ValueError(f"unsupported threshold type {type}")


# ---------------------------------------------------------------------------
# Stage dispatch (param-map compatible with the reference)
# ---------------------------------------------------------------------------

def apply_stage(img: jnp.ndarray, stage: Dict[str, Any]) -> jnp.ndarray:
    action = stage["action"]
    if action == "resize":
        return resize(img, height=stage.get("height"), width=stage.get("width"),
                      size=stage.get("size"),
                      keep_aspect_ratio=stage.get("keepAspectRatio", False))
    if action == "crop":
        return crop(img, stage["x"], stage["y"], stage["height"], stage["width"])
    if action == "centercrop":
        return center_crop(img, stage["height"], stage["width"])
    if action == "colorformat":
        return color_format(img, stage["format"])
    if action == "blur":
        return blur(img, stage["height"], stage["width"])
    if action == "threshold":
        return threshold(img, stage["threshold"], stage["maxVal"],
                         stage.get("type", THRESH_BINARY))
    if action == "gaussiankernel":
        return gaussian_kernel(img, stage["apertureSize"], stage["sigma"])
    if action == "flip":
        return flip(img, stage["flipCode"])
    raise ValueError(f"unsupported transformation {action!r}")


def apply_pipeline(img: jnp.ndarray, stages: List[Dict[str, Any]]) -> jnp.ndarray:
    for stage in stages:
        img = apply_stage(img, stage)
    return img


def unroll_chw(img: np.ndarray) -> np.ndarray:
    """Image (HWC, uint8-ish) -> flat float64 vector in C-major (c,h,w) order —
    exactly the reference's UnrollImage layout
    (ref: core/.../image/UnrollImage.scala:31-56)."""
    arr = np.asarray(img, dtype=np.float64)
    if arr.ndim == 2:
        arr = arr[..., None]
    return np.transpose(arr, (2, 0, 1)).reshape(-1)
