"""Image pipeline stages: ImageTransformer, UnrollImage, Resize, Augmenter.

Rebuild of the reference's OpenCV stage layer
(ref: opencv/src/main/scala/com/microsoft/ml/spark/opencv/ImageTransformer.scala:38-275
— a pipeline of Mat ops encoded as ``Map[String, Any]`` stage dicts;
ImageSetAugmenter.scala:18; core/.../image/UnrollImage.scala:31-56,
ResizeImageTransformer.scala).

Images ride in object columns as HWC numpy arrays (uint8 or float32).
Each transform groups rows by input shape and jits one fused XLA program
per (shape, pipeline) — batched device execution instead of the
reference's per-image native calls.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import HasInputCol, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.image import ops


def _as_image(v: Any) -> np.ndarray:
    arr = np.asarray(v)
    if arr.ndim == 2:
        arr = arr[..., None]
    return arr


class _ShapeBatchedImageOp:
    """Group object-column images by shape, apply a jitted batch fn once
    per shape bucket, scatter results back in row order."""

    def __init__(self, fn_builder):
        # fn_builder(shape) -> callable taking [B, *shape] -> [B, ...]
        self._builder = fn_builder
        self._cache: Dict[Any, Any] = {}

    def __call__(self, images: np.ndarray) -> np.ndarray:
        out = np.empty(len(images), dtype=object)
        by_shape: Dict[Any, List[int]] = {}
        for i, v in enumerate(images):
            if v is None:
                out[i] = None
                continue
            arr = _as_image(v)
            by_shape.setdefault(arr.shape, []).append(i)
        for shape, idxs in by_shape.items():
            fn = self._cache.get(shape)
            if fn is None:
                fn = self._cache[shape] = jax.jit(self._builder(shape))
            batch = np.stack([_as_image(images[i]) for i in idxs])
            res = np.asarray(fn(batch.astype(np.float32)))
            for j, i in enumerate(idxs):
                out[i] = res[j]
        return out


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Apply a list of param-map stages to an image column
    (ref: ImageTransformer.scala:38-275; stage dicts are byte-compatible:
    ``{"action": "resize", "height": .., "width": ..}`` etc.).

    Fluent helpers mirror the reference's builder API: ``.resize()``,
    ``.crop()``, ``.center_crop()``, ``.color_format()``, ``.blur()``,
    ``.threshold()``, ``.gaussian_kernel()``, ``.flip()``.
    """

    stages = Param("list of stage param-maps", default=())
    to_uint8 = Param("clip+cast output back to uint8", default=False)

    def _add(self, stage: Dict[str, Any]) -> "ImageTransformer":
        self.set(stages=tuple(self.stages) + (stage,))
        return self

    def resize(self, height: int = None, width: int = None, size: int = None,
               keep_aspect_ratio: bool = False) -> "ImageTransformer":
        return self._add({"action": "resize", "height": height,
                          "width": width, "size": size,
                          "keepAspectRatio": keep_aspect_ratio})

    def crop(self, x: int, y: int, height: int, width: int):
        return self._add({"action": "crop", "x": x, "y": y,
                          "height": height, "width": width})

    def center_crop(self, height: int, width: int):
        return self._add({"action": "centercrop", "height": height,
                          "width": width})

    def color_format(self, format: int):
        return self._add({"action": "colorformat", "format": format})

    def blur(self, height: int, width: int):
        return self._add({"action": "blur", "height": height, "width": width})

    def threshold(self, threshold: float, max_val: float, type: int = 0):
        return self._add({"action": "threshold", "threshold": threshold,
                          "maxVal": max_val, "type": type})

    def gaussian_kernel(self, aperture_size: int, sigma: float):
        return self._add({"action": "gaussiankernel",
                          "apertureSize": aperture_size, "sigma": sigma})

    def flip(self, flip_code: int = ops.FLIP_LEFT_RIGHT):
        return self._add({"action": "flip", "flipCode": flip_code})

    def _op(self) -> _ShapeBatchedImageOp:
        # cached per (stages, to_uint8) so repeated transforms — e.g. every
        # serving micro-batch — reuse the compiled XLA programs
        key = (tuple(tuple(sorted(s.items(), key=str)) for s in self.stages),
               self.to_uint8)
        cached = self.__dict__.get("_op_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
        stages = list(self.stages)
        to_uint8 = self.to_uint8

        def builder(shape):
            def batch_fn(imgs):
                y = jax.vmap(lambda im: ops.apply_pipeline(im, stages))(imgs)
                if to_uint8:
                    y = jnp.clip(jnp.round(y), 0, 255).astype(jnp.uint8)
                return y
            return batch_fn

        op = _ShapeBatchedImageOp(builder)
        self.__dict__["_op_cache"] = (key, op)
        return op

    def _transform(self, table: Table) -> Table:
        return table.with_column(self.output_col,
                                 self._op()(table[self.input_col]))


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Standalone resize stage (ref: core/.../image/ResizeImageTransformer.scala:110)."""

    height = Param("target height", default=None)
    width = Param("target width", default=None)
    size = Param("shorter-side size (keepAspectRatio)", default=None)
    keep_aspect_ratio = Param("preserve aspect ratio", default=False)

    def _transform(self, table: Table) -> Table:
        stage = {"action": "resize", "height": self.height,
                 "width": self.width, "size": self.size,
                 "keepAspectRatio": self.keep_aspect_ratio}
        key = tuple(sorted(stage.items(), key=str))
        cached = self.__dict__.get("_op_cache")
        if cached is None or cached[0] != key:
            op = _ShapeBatchedImageOp(
                lambda shape: jax.vmap(lambda im: ops.apply_stage(im, stage)))
            self.__dict__["_op_cache"] = cached = (key, op)
        return table.with_column(self.output_col,
                                 cached[1](table[self.input_col]))


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image -> flat float vector in channel-major (c, h, w) order — exactly
    the reference's layout (ref: core/.../image/UnrollImage.scala:31-56)."""

    def _transform(self, table: Table) -> Table:
        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            out[i] = None if v is None else ops.unroll_chw(_as_image(v))
        # uniform lengths collapse to a dense [N, D] column
        lens = {o.shape[0] for o in out if o is not None}
        if len(lens) == 1 and not any(o is None for o in out):
            return table.with_column(self.output_col, np.stack(list(out)))
        return table.with_column(self.output_col, out)


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Decode bytes then unroll (ref: core/.../image/UnrollImage.scala
    UnrollBinaryImage variant)."""

    def _transform(self, table: Table) -> Table:
        from synapseml_tpu.image.reader import decode_image

        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            img = None if v is None else decode_image(bytes(v))
            out[i] = None if img is None else ops.unroll_chw(img)
        lens = {o.shape[0] for o in out if o is not None}
        if len(lens) == 1 and not any(o is None for o in out):
            return table.with_column(self.output_col, np.stack(list(out)))
        return table.with_column(self.output_col, out)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by flips: emits the original rows plus one row
    per enabled flip (ref: opencv/.../ImageSetAugmenter.scala:18)."""

    flip_left_right = Param("add left-right flipped copies", default=True)
    flip_up_down = Param("add up-down flipped copies", default=False)

    def _transform(self, table: Table) -> Table:
        base = table.with_column(self.output_col, table[self.input_col])
        parts = [base]
        for enabled, code in [(self.flip_left_right, ops.FLIP_LEFT_RIGHT),
                              (self.flip_up_down, ops.FLIP_UP_DOWN)]:
            if not enabled:
                continue
            vals = table[self.input_col]
            flipped = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                if v is None:
                    flipped[i] = None
                    continue
                arr = _as_image(v)  # pure slicing: numpy, no device round trip
                arr = arr[:, ::-1] if code == ops.FLIP_LEFT_RIGHT else arr[::-1]
                flipped[i] = np.ascontiguousarray(arr)
            parts.append(table.with_column(self.output_col, flipped))
        return parts[0].concat(*parts[1:]) if len(parts) > 1 else parts[0]
