"""Image IO: decode bytes/files into HWC uint8 arrays.

Rebuild of the reference's image source + ImageUtils
(ref: core/src/main/scala/org/apache/spark/ml/source/image/PatchedImageFileFormat.scala:24,
core/.../io/image/ImageUtils.scala — Spark's image rows carry BGR bytes;
here images are HWC **RGB** numpy arrays, with explicit converters for the
Spark-layout interop).

Decoding uses PIL when available; a dependency-free PPM/PGM parser covers
environments without it (and the test fixtures).
"""
from __future__ import annotations

import io
import os
from typing import Optional

import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.binary import read_binary_files

_IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".gif", ".bmp", ".ppm", ".pgm",
                     ".tif", ".tiff", ".webp")


def _decode_pnm(data: bytes) -> Optional[np.ndarray]:
    """Minimal P5 (PGM) / P6 (PPM) binary decoder; None for anything it
    cannot decode exactly (corrupt headers, truncated data, 16-bit)."""
    if not data[:2] in (b"P5", b"P6"):
        return None
    try:
        fields = []
        pos = 2
        while len(fields) < 3:
            while pos < len(data) and data[pos:pos + 1].isspace():
                pos += 1
            if data[pos:pos + 1] == b"#":
                while pos < len(data) and data[pos:pos + 1] != b"\n":
                    pos += 1
                continue
            start = pos
            while pos < len(data) and not data[pos:pos + 1].isspace():
                pos += 1
            if start == pos:
                return None
            fields.append(int(data[start:pos]))
        pos += 1  # single whitespace after maxval
        w, h, maxval = fields
        if maxval != 255:  # 16-bit samples: let PIL handle it
            return None
        c = 3 if data[:2] == b"P6" else 1
        arr = np.frombuffer(data, dtype=np.uint8, count=w * h * c, offset=pos)
        return arr.reshape(h, w, c)
    except (ValueError, IndexError):
        return None


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> HWC uint8 RGB array (None when undecodable — the patched
    format's codec-tolerance, ref: PatchedImageFileFormat.scala)."""
    pnm = _decode_pnm(data)
    if pnm is not None:
        return pnm
    try:
        from PIL import Image
    except ImportError:
        return None
    try:
        img = Image.open(io.BytesIO(data))
        if img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.astype(np.uint8)
    except Exception:  # noqa: BLE001 - undecodable bytes -> null row
        return None


def read_image_files(path: str, recursive: bool = True,
                     sample_ratio: float = 1.0, seed: int = 0,
                     drop_undecodable: bool = True) -> Table:
    """Read a directory (or zip) of images into a Table with columns
    ``path`` and ``image`` (HWC uint8 object column)."""
    raw = read_binary_files(path, recursive=recursive,
                            sample_ratio=sample_ratio, seed=seed)
    keep = [
        i for i, p in enumerate(raw["path"])
        if os.path.splitext(p)[1].lower() in _IMAGE_EXTENSIONS
    ]
    paths, images = [], []
    for i in keep:
        img = decode_image(bytes(raw["bytes"][i]))
        if img is None and drop_undecodable:
            continue
        paths.append(raw["path"][i])
        images.append(img)
    img_col = np.empty(len(images), dtype=object)
    img_col[:] = images
    return Table({"path": np.array(paths, dtype=object), "image": img_col})


def to_spark_layout(img: np.ndarray) -> bytes:
    """HWC RGB -> Spark ImageSchema's BGR row-major bytes
    (ref: ImageUtils.scala toSparkImage)."""
    arr = np.asarray(img, dtype=np.uint8)
    if arr.shape[-1] == 3:
        arr = arr[..., ::-1]
    return arr.tobytes()


def from_spark_layout(data: bytes, height: int, width: int,
                      n_channels: int) -> np.ndarray:
    """Spark ImageSchema BGR bytes -> HWC RGB array."""
    arr = np.frombuffer(data, dtype=np.uint8).reshape(height, width,
                                                      n_channels)
    if n_channels == 3:
        arr = arr[..., ::-1]
    return arr.copy()
