"""ImageFeaturizer: transfer learning via a headless imported network.

Rebuild of the reference's ImageFeaturizer
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/ImageFeaturizer.scala:40-197
— resize -> unroll -> truncated CNTK net via ``cutOutputLayers``:100;
headless featurization or full predictions, image or binary input column).

Here the backbone is an imported ONNX graph (any user ``.onnx`` file or a
``synapseml_tpu.onnx.zoo`` constructor): ``cut_output_layers`` drops the
last N graph nodes (``ImportedGraph.truncated``), images are resized on
device, normalized, and run through the jit-cached BatchedExecutor in NCHW.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import (ComplexParam, HasInputCol,
                                      HasOutputCol, Param)
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.image import ops
from synapseml_tpu.onnx.importer import ImportedGraph, import_model
from synapseml_tpu.runtime.executor import BatchedExecutor

_DTYPES = {"float32": np.float32, "bfloat16": "bfloat16"}


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """Featurize an image column through a truncated deep network.

    ``cut_output_layers=0`` returns the model's full output (predictions);
    ``>=1`` removes that many trailing graph nodes and returns the last
    surviving activation, flattened — the transfer-learning feature vector
    (reference semantics, ImageFeaturizer.scala:100 cutOutputLayers).
    """

    model_payload = ComplexParam("raw .onnx backbone bytes")
    cut_output_layers = Param("trailing graph nodes to drop", default=1)
    image_size = Param("square input side fed to the net", default=224)
    mean = Param("per-channel normalization mean (0-1 scale)",
                 default=(0.485, 0.456, 0.406))
    std = Param("per-channel normalization std", default=(0.229, 0.224, 0.225))
    channels = Param("backbone input channels (3, or 1 for grayscale "
                     "nets like the bundled digits-cnn)", default=3)
    compute_dtype = Param("float32|bfloat16", default="float32")
    mini_batch_size = Param("max rows per device batch", default=64)
    devices = Param(
        "data-parallel device spec: None, 'all', int N, or a device "
        "sequence — buckets are dp-sharded by the executor", default=None)
    compile_cache_dir = Param(
        "persistent compile-cache directory (default: the "
        "SYNAPSEML_COMPILE_CACHE env var; unset = off) — enables "
        "warmup() persistence so a restarted process deserializes "
        "executables instead of recompiling", default=None)

    def __init__(self, model_path: Optional[str] = None,
                 model_bytes: Optional[bytes] = None, **kw):
        super().__init__(**kw)
        if model_path is not None:
            with open(model_path, "rb") as fh:
                model_bytes = fh.read()
        if model_bytes is not None:
            self.set(model_payload=bytes(model_bytes))

    def _post_copy(self, src):
        super()._post_copy(src)
        self.__dict__.pop("_feat_cache", None)

    def _load_extra(self, path: str):
        self.__dict__.pop("_feat_cache", None)

    def _pieces(self):
        from synapseml_tpu.runtime.executor import resolve_devices
        cache = self.__dict__.get("_feat_cache")
        devs = resolve_devices(self.devices)
        dev_key = None if devs is None else tuple(d.id for d in devs)
        key = (self.cut_output_layers, self.compute_dtype,
               self.mini_batch_size, tuple(self.mean), tuple(self.std),
               self.channels, hash(self.model_payload), dev_key,
               self.compile_cache_dir)
        if cache is not None and cache[0] == key:
            return cache[1]
        graph: ImportedGraph = import_model(self.model_payload)
        if self.cut_output_layers > 0:
            graph = graph.truncated(self.cut_output_layers)
        params = graph.params
        if self.compute_dtype != "float32":
            dt = _DTYPES[self.compute_dtype]
            params = {
                k: (v.astype(dt) if np.issubdtype(v.dtype, np.floating)
                    else v)
                for k, v in params.items()
            }
        c = int(self.channels)

        def per_channel(vals, what):
            if len(vals) == c:
                return list(vals)
            if len(vals) == 1:  # scalar stat tiles across channels
                return list(vals) * c
            # no silent truncation: the default ImageNet 3-tuple applied
            # to a channels=1 net would quietly normalize with the RED
            # channel's stats — make the user choose
            raise ValueError(
                f"{what} has {len(vals)} entries but channels={c}; "
                f"provide one value per channel (or a single scalar)")

        mean = jnp.asarray(per_channel(self.mean, "mean"),
                           jnp.float32).reshape(1, -1, 1, 1)
        std = jnp.asarray(per_channel(self.std, "std"),
                          jnp.float32).reshape(1, -1, 1, 1)

        def fn(p, imgs_nchw):
            x = (imgs_nchw.astype(jnp.float32) / 255.0 - mean) / std
            if self.compute_dtype != "float32":
                x = x.astype(jnp.bfloat16)
            (out,) = graph.apply(p, x)
            return out.reshape(out.shape[0], -1).astype(jnp.float32)

        # content hash over backbone bytes + featurization config: the
        # persistent-executable key ingredient (changed weights or a
        # different cut/normalization must miss, never reuse)
        from synapseml_tpu.runtime import compile_cache as _cc
        cache_key = _cc.content_hash(
            self.model_payload, self.cut_output_layers, self.compute_dtype,
            tuple(self.mean), tuple(self.std), c)
        executor = BatchedExecutor(fn, max_bucket=self.mini_batch_size,
                                   bound_args=(params,), devices=devs,
                                   cache_key=cache_key,
                                   cache_dir=self.compile_cache_dir)
        self.__dict__["_feat_cache"] = (key, executor)
        return executor

    def warmup(self, buckets=None):
        """AOT-compile every mini-batch bucket of the NCHW featurization
        signature (and persist it when a compile-cache dir is configured)
        so the first scored image never waits on XLA — see
        :meth:`synapseml_tpu.runtime.executor.BatchedExecutor.warmup`."""
        size = int(self.image_size)
        row = (int(self.channels), size, size)
        return self._pieces().warmup([(row, np.float32)], buckets=buckets)

    def _prepare(self, v: Any) -> Optional[np.ndarray]:
        """Anything image-ish -> [size, size, 3] float32 HWC."""
        if v is None:
            return None
        if isinstance(v, (bytes, bytearray)):
            from synapseml_tpu.image.reader import decode_image
            v = decode_image(bytes(v))
            if v is None:
                return None
        arr = np.asarray(v, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[..., None]
        c = int(self.channels)
        if arr.shape[-1] == 1 and c == 3:
            arr = np.repeat(arr, 3, axis=-1)
        elif arr.shape[-1] == 3 and c == 1:
            # BT.601 luma on host (RGB weights matching ops.color_format
            # COLOR_RGB2GRAY) — a device round trip per image would
            # serialize tiny transfers through the tunnel
            arr = arr[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
            arr = arr[..., None]
        size = self.image_size
        if arr.shape[0] != size or arr.shape[1] != size:
            arr = np.asarray(ops.resize(jnp.asarray(arr), height=size,
                                        width=size))
        return arr

    def _transform(self, table: Table) -> Table:
        executor = self._pieces()
        col = table[self.input_col]
        mbs = max(1, int(self.mini_batch_size))
        valid: list = []

        def chunks():
            # lazy prepare: executor.stream pulls this generator with
            # pipeline_depth chunks in flight, so decode/resize of chunk
            # k+1 runs on the host WHILE chunk k computes on device —
            # the submit/drain overlap a single stacked call can't get
            buf: list = []
            for i, v in enumerate(col):
                arr = self._prepare(v)
                if arr is None:
                    continue
                valid.append(i)
                buf.append(arr)
                if len(buf) >= mbs:
                    yield (np.stack(buf).transpose(0, 3, 1, 2),)
                    buf = []
            if buf:
                yield (np.stack(buf).transpose(0, 3, 1, 2),)

        feat_chunks = [out for (out,) in executor.stream(chunks())]
        if not valid:
            return table.with_column(
                self.output_col, np.empty(table.num_rows, dtype=object))
        feats = np.asarray(
            feat_chunks[0] if len(feat_chunks) == 1
            else np.concatenate(feat_chunks), np.float32)
        if len(valid) == table.num_rows:
            return table.with_column(self.output_col, feats)
        out = np.empty(table.num_rows, dtype=object)
        for j, i in enumerate(valid):
            out[i] = feats[j]
        return table.with_column(self.output_col, out)
