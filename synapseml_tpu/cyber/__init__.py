"""Cyber anomalous-access detection (SURVEY.md §2.4 cyber module —
~1,800 LoC of Python in the reference)."""
from synapseml_tpu.cyber.anomaly import (  # noqa: F401
    AccessAnomaly,
    AccessAnomalyModel,
    ComplementAccessTransformer,
)
from synapseml_tpu.cyber.feature import (  # noqa: F401
    IdIndexer,
    IdIndexerModel,
    LinearScalarScaler,
    LinearScalarScalerModel,
    MultiIndexer,
    MultiIndexerModel,
    StandardScalarScaler,
    StandardScalarScalerModel,
)
