"""Cyber feature engineering: per-tenant indexers and scalers.

Rebuild of the reference's cyber feature module
(ref: core/src/main/python/mmlspark/cyber/feature/indexers.py —
IdIndexerModel:12 (vocab join, unknown -> 0, input col dropped),
IdIndexer:46 (1-based ids, reset_per_partition), MultiIndexer:130;
feature/scalers.py — PerPartitionScalarScalerModel:18,
StandardScalarScaler:189 (per-partition mean/std_pop, std==0 falls back
to centering), LinearScalarScaler:289 (per-partition [min,max] ->
[min_required, max_required], degenerate range -> midpoint)).

Table-native differences: the Spark joins become vectorized dict lookups
over numpy columns; per-group stats persist as plain dicts through
ComplexParam side files instead of cached DataFrames. ``partition_key=None``
means one global group (the reference's unpartitioned mode). Unlike the
reference's unpartitioned standard scaler (which divides by zero
unguarded), std==0 falls back to centering in both modes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.core.param import (ComplexParam, HasInputCol,
                                      HasOutputCol, Param)
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table

_GLOBAL = "__global__"


def _partitions(table: Table, partition_key: Optional[str]) -> np.ndarray:
    if partition_key is None:
        part = np.empty(table.num_rows, dtype=object)
        part[:] = _GLOBAL
        return part
    return np.asarray(table[partition_key])


class IdIndexerModel(Model, HasInputCol, HasOutputCol):
    """Maps (partition, value) to a learned 1-based id; unseen values map
    to 0 (ref: indexers.py IdIndexerModel._transform:31-43)."""

    partition_key = Param("tenant column (None = single tenant)",
                          default=None)
    vocab = ComplexParam("{(partition, value): id} learned at fit")

    def _transform(self, table: Table) -> Table:
        parts = _partitions(table, self.partition_key)
        vals = table[self.input_col]
        lut: Dict[Tuple[Any, Any], int] = self.vocab or {}
        idx = np.fromiter(
            (lut.get((p, v), 0) for p, v in zip(parts, vals)),
            dtype=np.int64, count=len(vals))
        # the reference drops the raw value column after indexing
        out = table.with_column(self.output_col, idx)
        if self.input_col != self.output_col:
            out = out.drop(self.input_col)
        return out

    def undo_transform(self, table: Table) -> Table:
        """(partition, id) back to the original value
        (ref: indexers.py IdIndexerModel.undo_transform:25-29)."""
        parts = _partitions(table, self.partition_key)
        ids = np.asarray(table[self.output_col])
        inv: Dict[Tuple[Any, int], Any] = {
            (p, i): v for (p, v), i in (self.vocab or {}).items()
        }
        vals = np.empty(len(ids), dtype=object)
        for j, (p, i) in enumerate(zip(parts, ids)):
            vals[j] = inv.get((p, int(i)))
        return table.with_column(self.input_col, vals)


class IdIndexer(Estimator, HasInputCol, HasOutputCol):
    """Learns consecutive 1-based ids over distinct (partition, value)
    pairs (ref: indexers.py IdIndexer:46-91; ids restart per partition
    when ``reset_per_partition``)."""

    partition_key = Param("tenant column (None = single tenant)",
                          default=None)
    reset_per_partition = Param(
        "restart ids at 1 within each partition", default=True)

    def _fit(self, table: Table) -> IdIndexerModel:
        parts = _partitions(table, self.partition_key)
        vals = table[self.input_col]
        pairs = sorted(
            {(p, v) for p, v in zip(parts, vals)},
            key=lambda pv: (str(pv[0]), str(pv[1])))
        vocab: Dict[Tuple[Any, Any], int] = {}
        if self.reset_per_partition:
            counters: Dict[Any, int] = {}
            for p, v in pairs:
                counters[p] = counters.get(p, 0) + 1
                vocab[(p, v)] = counters[p]
        else:
            for i, pv in enumerate(pairs, start=1):
                vocab[pv] = i
        return IdIndexerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key, vocab=vocab)


class MultiIndexerModel(Model):
    """Applies several IdIndexerModels in sequence
    (ref: indexers.py MultiIndexerModel:94-127)."""

    models = ComplexParam("list of fitted IdIndexerModels")

    def get_model_by_input_col(self, input_col: str
                               ) -> Optional[IdIndexerModel]:
        for m in self.models or []:
            if m.input_col == input_col:
                return m
        return None

    def get_model_by_output_col(self, output_col: str
                                ) -> Optional[IdIndexerModel]:
        for m in self.models or []:
            if m.output_col == output_col:
                return m
        return None

    def _transform(self, table: Table) -> Table:
        for m in self.models or []:
            table = m.transform(table)
        return table

    def undo_transform(self, table: Table) -> Table:
        for m in self.models or []:
            table = m.undo_transform(table)
        return table


class MultiIndexer(Estimator):
    """Fits a set of IdIndexers on one pass of fit() calls
    (ref: indexers.py MultiIndexer:130-135)."""

    indexers = ComplexParam("list of IdIndexer estimators")

    def _fit(self, table: Table) -> MultiIndexerModel:
        return MultiIndexerModel(
            models=[ix.fit(table) for ix in self.indexers or []])


# ---------------------------------------------------------------------------
# per-partition scalers
# ---------------------------------------------------------------------------

class PerPartitionScalarScalerModel(Model, HasInputCol, HasOutputCol):
    """Shared plumbing: look up this row's group stats, apply the
    subclass's normalization (ref: scalers.py
    PerPartitionScalarScalerModel:18-85). Rows from unseen partitions
    get NaN (the reference's left-join null)."""

    partition_key = Param("tenant column (None = single tenant)",
                          default=None)
    per_group_stats = ComplexParam("{partition: {stat: value}}")

    def _norm(self, x: np.ndarray, stats: Dict[str, float]) -> np.ndarray:
        raise NotImplementedError

    def _transform(self, table: Table) -> Table:
        parts = _partitions(table, self.partition_key)
        x = np.asarray(table[self.input_col], dtype=np.float64)
        out = np.full(len(x), np.nan)
        stats_map: Dict[Any, Dict[str, float]] = self.per_group_stats or {}
        for p in np.unique(parts) if parts.dtype != object else set(parts):
            stats = stats_map.get(p)
            if stats is None:
                continue
            sel = parts == p
            out[sel] = self._norm(x[sel], stats)
        return table.with_column(self.output_col, out)


class PerPartitionScalarScalerEstimator(Estimator, HasInputCol,
                                        HasOutputCol):
    """(ref: scalers.py PerPartitionScalarScalerEstimator:88-124)."""

    partition_key = Param("tenant column (None = single tenant)",
                          default=None)

    def _group_stats(self, x: np.ndarray) -> Dict[str, float]:
        raise NotImplementedError

    def _create_model(self, stats: Dict[Any, Dict[str, float]]
                      ) -> PerPartitionScalarScalerModel:
        raise NotImplementedError

    def _fit(self, table: Table) -> PerPartitionScalarScalerModel:
        parts = _partitions(table, self.partition_key)
        x = np.asarray(table[self.input_col], dtype=np.float64)
        stats: Dict[Any, Dict[str, float]] = {}
        for p in set(parts):
            stats[p] = self._group_stats(x[parts == p])
        return self._create_model(stats)


class StandardScalarScalerModel(PerPartitionScalarScalerModel):
    """coef * (x - mean) / std per group; std == 0 falls back to plain
    centering WITHOUT the coefficient — deliberately matching the
    reference's ``otherwise(x - mean)`` branch (ref: scalers.py
    StandardScalarScalerModel._make_partitioned_stats_method:162-170)."""

    coefficient_factor = Param("post-scale multiplier", default=1.0)

    def _norm(self, x, stats):
        mean, std = stats["mean"], stats["std"]
        if std == 0.0:
            return x - mean
        return self.coefficient_factor * (x - mean) / std


class StandardScalarScaler(PerPartitionScalarScalerEstimator):
    """(ref: scalers.py StandardScalarScaler:189-224 — mean + stddev_pop
    per partition)."""

    coefficient_factor = Param("post-scale multiplier", default=1.0)

    def _group_stats(self, x):
        return {"mean": float(np.mean(x)), "std": float(np.std(x))}

    def _create_model(self, stats):
        return StandardScalarScalerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key, per_group_stats=stats,
            coefficient_factor=self.coefficient_factor)


class LinearScalarScalerModel(PerPartitionScalarScalerModel):
    """Affine map of the group's [min,max] onto [min_required,
    max_required]; a degenerate range maps to the midpoint
    (ref: scalers.py LinearScalarScalerModel:232-286)."""

    min_required_value = Param("output range lower bound", default=0.0)
    max_required_value = Param("output range upper bound", default=1.0)

    def _norm(self, x, stats):
        lo, hi = stats["min"], stats["max"]
        delta = hi - lo
        if delta == 0.0:
            a = 0.0
            b = (self.min_required_value + self.max_required_value) / 2.0
        else:
            a = (self.max_required_value - self.min_required_value) / delta
            b = self.max_required_value - a * hi
        return a * x + b


class LinearScalarScaler(PerPartitionScalarScalerEstimator):
    """(ref: scalers.py LinearScalarScaler:289-325)."""

    min_required_value = Param("output range lower bound", default=0.0)
    max_required_value = Param("output range upper bound", default=1.0)

    def _group_stats(self, x):
        return {"min": float(np.min(x)), "max": float(np.max(x))}

    def _create_model(self, stats):
        return LinearScalarScalerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key, per_group_stats=stats,
            min_required_value=self.min_required_value,
            max_required_value=self.max_required_value)
