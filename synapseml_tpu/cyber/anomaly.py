"""Cyber access-anomaly detection via collaborative filtering.

Rebuild of the reference's Python-only cyber module
(ref: core/src/main/python/mmlspark/cyber/anomaly/collaborative_filtering.py:472
``AccessAnomaly`` — per-tenant ALS over user x resource access likelihoods,
complement-set negative sampling, and a normalization pass so the anomaly
score has mean 0 / std 1 on the training accesses (ModelNormalizeTransformer
:886); complement_access.py:13 ``ComplementAccessTransformer``).

TPU-native differences: ALS runs as dense, batched jax linear solves per
tenant (einsum normal equations + ``jnp.linalg.solve`` — MXU work, no
Spark ALS blocks), and the normalization is stored as per-tenant (mean,
std) instead of bias-augmented vectors — algebraically the same score.
Anomaly score = (mean - u.v) / std: positive = less-expected access.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table


class ComplementAccessTransformer(Transformer):
    """Sample (user, res) pairs NOT present in the input — negative
    sampling from the complement set (ref: complement_access.py:13).

    Emits ~``complementset_factor`` x num_rows rows per tenant.
    """

    partition_key = Param("tenant column (None = single tenant)",
                          default=None)
    indexed_col_names = Param("the (user, res) index columns",
                              default=("user", "res"))
    complementset_factor = Param("complement rows per observed row",
                                 default=2)
    seed = Param("rng seed", default=0)

    def _transform(self, table: Table) -> Table:
        ucol, rcol = self.indexed_col_names
        tcol = self.partition_key
        tenants = (np.asarray(table[tcol]) if tcol
                   else np.zeros(table.num_rows, np.int64))
        users = np.asarray(table[ucol])
        ress = np.asarray(table[rcol])
        rng = np.random.default_rng(int(self.seed))

        out_t: List[Any] = []
        out_u: List[Any] = []
        out_r: List[Any] = []
        for t in np.unique(tenants):
            sel = tenants == t
            tu = np.unique(users[sel])
            tr = np.unique(ress[sel])
            seen = set(zip(users[sel].tolist(), ress[sel].tolist()))
            want = int(self.complementset_factor) * int(sel.sum())
            total = len(tu) * len(tr) - len(seen)
            want = min(want, max(total, 0))
            picked = 0
            attempts = 0
            got = set()
            while picked < want and attempts < 50 * max(want, 1):
                u = tu[rng.integers(0, len(tu))]
                r = tr[rng.integers(0, len(tr))]
                attempts += 1
                if (u, r) in seen or (u, r) in got:
                    continue
                got.add((u, r))
                out_t.append(t)
                out_u.append(u)
                out_r.append(r)
                picked += 1
        cols = {
            ucol: np.asarray(out_u),
            rcol: np.asarray(out_r),
        }
        if tcol:
            cols = {tcol: np.asarray(out_t), **cols}
        return Table(cols)


def _als(ratings: np.ndarray, mask: np.ndarray, rank: int, reg: float,
         iters: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Dense masked explicit ALS: returns (U [nu,k], V [nr,k]).

    Normal equations batched with einsum + jnp.linalg.solve — each half
    update is one MXU-heavy batched solve (the Spark ALS block analogue).
    """
    nu, nr = ratings.shape
    key = jax.random.PRNGKey(seed)
    ku, kv = jax.random.split(key)
    u = jax.random.normal(ku, (nu, rank)) * 0.1
    v = jax.random.normal(kv, (nr, rank)) * 0.1
    r = jnp.asarray(ratings, jnp.float32)
    m = jnp.asarray(mask, jnp.float32)
    eye = jnp.eye(rank) * reg

    def solve_side(fixed, mm, rr):
        # for each row i: (sum_j m_ij f_j f_j^T + reg I) x_i = sum_j m_ij r_ij f_j
        a = jnp.einsum("ij,jk,jl->ikl", mm, fixed, fixed) + eye[None]
        b = jnp.einsum("ij,jk->ik", mm * rr, fixed)
        return jnp.linalg.solve(a, b[..., None])[..., 0]

    def step(_, carry):
        u, v = carry
        u = solve_side(v, m, r)
        v = solve_side(u, m.T, r.T)
        return (u, v)

    u, v = jax.lax.fori_loop(0, iters, step, (u, v))
    return np.asarray(u), np.asarray(v)


class AccessAnomalyModel(Model):
    """(ref: collaborative_filtering.py:161 AccessAnomalyModel)."""

    tenant_col = Param("tenant column", default="tenant")
    user_col = Param("user column", default="user")
    res_col = Param("resource column", default="res")
    output_col = Param("anomaly score column", default="anomaly_score")
    mappings = ComplexParam("per-tenant {users, user_vecs, ress, res_vecs, "
                            "mean, std}")

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        tcol = self.tenant_col
        tenants = (np.asarray(table[tcol]) if tcol and tcol in table
                   else np.zeros(n, np.int64))
        users = np.asarray(table[self.user_col])
        ress = np.asarray(table[self.res_col])
        out = np.full(n, np.nan, np.float64)
        for t, mp in self.mappings.items():
            sel = tenants == t
            if not sel.any():
                continue
            uidx = {u: i for i, u in enumerate(mp["users"])}
            ridx = {r: i for i, r in enumerate(mp["ress"])}
            for i in np.nonzero(sel)[0]:
                ui = uidx.get(users[i])
                ri = ridx.get(ress[i])
                if ui is None or ri is None:
                    continue  # unseen entity -> null score (reference)
                dot = float(mp["user_vecs"][ui] @ mp["res_vecs"][ri])
                out[i] = (mp["mean"] - dot) / mp["std"]
        return table.with_column(self.output_col, out)


class AccessAnomaly(Estimator):
    """Per-tenant ALS anomalous-access estimator
    (ref: collaborative_filtering.py:472; defaults mirror
    AccessAnomalyConfig:44 — rank 10, maxIter 25, regParam 0.1,
    likelihood scaling to [5, 10], complement factor 2).
    """

    tenant_col = Param("tenant column (None = single tenant)",
                       default="tenant")
    user_col = Param("user column", default="user")
    res_col = Param("resource column", default="res")
    likelihood_col = Param("access likelihood/count column (None = 1.0)",
                           default=None)
    output_col = Param("anomaly score column", default="anomaly_score")
    rank_param = Param("latent factors", default=10)
    max_iter = Param("ALS iterations", default=25)
    reg_param = Param("ALS regularization", default=0.1)
    low_value = Param("scaled likelihood lower bound", default=5.0)
    high_value = Param("scaled likelihood upper bound", default=10.0)
    complementset_factor = Param("negative samples per observed row",
                                 default=2)
    apply_implicit_cf = Param("add complement-set negatives", default=True)
    seed = Param("rng seed", default=0)

    def _fit(self, table: Table) -> AccessAnomalyModel:
        tcol = self.tenant_col
        n = table.num_rows
        tenants = (np.asarray(table[tcol]) if tcol and tcol in table
                   else np.zeros(n, np.int64))
        users = np.asarray(table[self.user_col])
        ress = np.asarray(table[self.res_col])
        if self.likelihood_col:
            lik = np.asarray(table[self.likelihood_col], np.float64)
        else:
            lik = np.ones(n, np.float64)

        mappings: Dict[Any, Dict[str, Any]] = {}
        for t in np.unique(tenants):
            sel = tenants == t
            tu, uinv = np.unique(users[sel], return_inverse=True)
            tr, rinv = np.unique(ress[sel], return_inverse=True)
            nu, nr = len(tu), len(tr)
            ratings = np.zeros((nu, nr), np.float64)
            counts = np.zeros((nu, nr), np.float64)
            np.add.at(ratings, (uinv, rinv), lik[sel])
            np.add.at(counts, (uinv, rinv), 1.0)
            mask = counts > 0
            # scale observed likelihoods into [low, high] per tenant
            # (ref: _get_scaled_df)
            obs = ratings[mask]
            lo, hi = float(self.low_value), float(self.high_value)
            if obs.max() > obs.min():
                scaled = lo + (obs - obs.min()) / (obs.max() - obs.min()) \
                    * (hi - lo)
            else:
                scaled = np.full_like(obs, (lo + hi) / 2.0)
            ratings[mask] = scaled
            mask_f = mask.astype(np.float64)

            if self.apply_implicit_cf:
                # complement negatives at rating ~1 (below the low bound),
                # the implicit "should not access" signal
                rng = np.random.default_rng(int(self.seed))
                want = int(self.complementset_factor) * int(sel.sum())
                free = np.argwhere(~mask)
                if len(free):
                    pick = free[rng.permutation(len(free))[:want]]
                    ratings[pick[:, 0], pick[:, 1]] = 1.0
                    mask_f[pick[:, 0], pick[:, 1]] = 1.0

            u_vecs, v_vecs = _als(ratings, mask_f, int(self.rank_param),
                                  float(self.reg_param), int(self.max_iter),
                                  int(self.seed))
            # normalization on the *observed* accesses (ModelNormalize)
            dots = np.einsum("ij,ij->i", u_vecs[uinv], v_vecs[rinv])
            mean = float(dots.mean())
            std = float(dots.std()) or 1.0
            mappings[t] = {
                "users": tu, "ress": tr,
                "user_vecs": u_vecs, "res_vecs": v_vecs,
                "mean": mean, "std": std,
            }
        return AccessAnomalyModel(
            tenant_col=tcol, user_col=self.user_col, res_col=self.res_col,
            output_col=self.output_col, mappings=mappings)


class AccessAnomalyModelParams:
    """Kept for parity with the reference's config object
    (ref: AccessAnomalyConfig:44)."""

    default_tenant_col = "tenant"
    default_user_col = "user"
    default_res_col = "res"
    default_likelihood_col = "likelihood"
    default_output_col = "anomaly_score"
