"""Isolation Forest anomaly detector.

The reference wraps LinkedIn's isolation-forest Spark library
(ref: core/.../isolationforest/IsolationForest.scala:18-89, dep at
build.sbt:36). Here the algorithm is implemented natively: trees are built on
the host from subsamples (cheap, O(sample * trees)), then flattened into
stacked arrays so *scoring* — the hot path — is a single jitted scan over all
trees on device, the same stacked-tree layout the GBDT booster uses.
"""
from __future__ import annotations

import math
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasFeaturesCol, HasPredictionCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.data.table import Table


def _avg_path_length(n: float) -> float:
    """c(n): average unsuccessful BST search length (Liu et al. 2008)."""
    if n <= 1:
        return 0.0
    h = math.log(n - 1) + 0.5772156649
    return 2.0 * h - 2.0 * (n - 1) / n


def _build_tree(x: np.ndarray, rng: np.random.Generator, max_depth: int,
                feature, threshold, left, right, depth_adj):
    """Grow one isolation tree into flat arrays; returns node count used."""
    nodes = [(x, 0)]  # (rows, depth) queued for node i in BFS order
    i = 0
    while nodes:
        rows, depth = nodes.pop(0)
        n = len(rows)
        if depth >= max_depth or n <= 1:
            feature.append(-1)
            threshold.append(0.0)
            left.append(-1)
            right.append(-1)
            depth_adj.append(depth + _avg_path_length(n))
            i += 1
            continue
        # random split: feature uniform, threshold uniform in column range
        spread = rows.max(axis=0) - rows.min(axis=0)
        cand = np.flatnonzero(spread > 0)
        if len(cand) == 0:
            feature.append(-1)
            threshold.append(0.0)
            left.append(-1)
            right.append(-1)
            depth_adj.append(depth + _avg_path_length(n))
            i += 1
            continue
        f = int(rng.choice(cand))
        lo, hi = rows[:, f].min(), rows[:, f].max()
        t = float(rng.uniform(lo, hi))
        mask = rows[:, f] < t
        feature.append(f)
        threshold.append(t)
        # children appended after all queued nodes (BFS indexing)
        left.append(i + len(nodes) + 1)
        right.append(i + len(nodes) + 2)
        depth_adj.append(0.0)
        nodes.append((rows[mask], depth + 1))
        nodes.append((rows[~mask], depth + 1))
        i += 1
    return i


@partial(jax.jit, static_argnames=("depth_iters",))
def _path_lengths(stack, x, depth_iters: int):
    """stack: (feature [T,M], threshold [T,M], left, right, depth_adj);
    x: [N, D] -> mean path length [N] over trees via lax.scan.
    ``depth_iters`` must be >= the deepest leaf (trees are unbalanced, so the
    node count says nothing about depth)."""
    feat, thr, lft, rgt, dadj = stack

    rows = jnp.arange(x.shape[0])

    def one_tree(carry, tree):
        f, t, l, r, da = tree

        def step(_, node):
            fi = f[node]                                   # [N]
            col = x[rows, jnp.maximum(fi, 0)]              # per-row gather
            nxt = jnp.where(col < t[node], l[node], r[node])
            return jnp.where(fi < 0, node, nxt)

        node = jax.lax.fori_loop(
            0, depth_iters, step,
            jnp.zeros(x.shape[0], jnp.int32))
        return carry + da[node], None

    total, _ = jax.lax.scan(one_tree, jnp.zeros(x.shape[0], jnp.float32),
                            (feat, thr, lft, rgt, dadj))
    return total / feat.shape[0]


@partial(jax.jit, static_argnames=("depth_iters",))
def _path_lengths_pallas(stack, x, depth_iters: int):
    """Fused-kernel twin of :func:`_path_lengths`: the depth-
    accumulating variant of the GBDT traversal kernel
    (pallas_kernels.predict_forest_tpu with ``value=depth_adj`` and
    the isolation-forest ``x < thr`` strict comparison) — the whole
    forest in one launch, path-length sums resident in VMEM. Selected
    by the measured prober in :meth:`IsolationForestModel._scores`."""
    from synapseml_tpu.gbdt import pallas_kernels

    feat, thr, lft, rgt, dadj = stack
    total = pallas_kernels.predict_forest_tpu(
        x, feat, thr, lft, rgt, dadj, k=1, depth=depth_iters,
        strict=True)[:, 0]
    return total / feat.shape[0]


class IsolationForest(Estimator, HasFeaturesCol, HasPredictionCol):
    """ref: core/.../isolationforest/IsolationForest.scala:18 (param names
    follow the LinkedIn library the reference wraps)."""

    num_estimators = Param("number of trees", default=100)
    max_samples = Param("subsample size per tree", default=256)
    max_features = Param("feature subsample fraction", default=1.0)
    contamination = Param("expected anomaly fraction (sets the threshold)",
                          default=0.0)
    score_col = Param("anomaly score column", default="outlierScore")
    random_seed = Param("rng seed", default=1)

    def _fit(self, table: Table) -> "IsolationForestModel":
        x = np.asarray(table[self.features_col], np.float32)
        n = len(x)
        rng = np.random.default_rng(int(self.random_seed))
        sample = min(int(self.max_samples), n)
        max_depth = max(1, int(math.ceil(math.log2(max(sample, 2)))))
        d = x.shape[1]
        n_feat = max(1, min(d, int(round(float(self.max_features) * d))))
        trees = []
        for _ in range(int(self.num_estimators)):
            idx = rng.choice(n, size=sample, replace=False)
            feature: List[int] = []
            threshold: List[float] = []
            left: List[int] = []
            right: List[int] = []
            depth_adj: List[float] = []
            if n_feat < d:
                # per-tree feature subsample, as in the wrapped LinkedIn lib
                cols = np.sort(rng.choice(d, size=n_feat, replace=False))
                _build_tree(x[np.ix_(idx, cols)], rng, max_depth, feature,
                            threshold, left, right, depth_adj)
                feature = [int(cols[f]) if f >= 0 else -1 for f in feature]
            else:
                _build_tree(x[idx], rng, max_depth, feature, threshold,
                            left, right, depth_adj)
            trees.append((feature, threshold, left, right, depth_adj))
        m = max(len(t[0]) for t in trees)
        T = len(trees)
        feat = np.full((T, m), -1, np.int32)
        thr = np.zeros((T, m), np.float32)
        lft = np.zeros((T, m), np.int32)
        rgt = np.zeros((T, m), np.int32)
        dadj = np.zeros((T, m), np.float32)
        for i, (f, t, l, r, d) in enumerate(trees):
            feat[i, :len(f)] = f
            thr[i, :len(t)] = t
            lft[i, :len(l)] = l
            rgt[i, :len(r)] = r
            dadj[i, :len(d)] = d
        model = IsolationForestModel(
            trees=(feat, thr, lft, rgt, dadj),
            max_depth=max_depth,
            c_norm=_avg_path_length(sample),
            features_col=self.features_col,
            prediction_col=self.prediction_col,
            score_col=self.score_col)
        contamination = float(self.contamination)
        if contamination > 0:
            scores = model._scores(x)
            model.set(threshold=float(np.quantile(scores, 1 - contamination)))
        return model


class IsolationForestModel(Model, HasFeaturesCol, HasPredictionCol):
    trees = ComplexParam("stacked tree arrays (feature/threshold/left/right/depth)")
    max_depth = Param("tree depth cap used at fit time", default=12)
    c_norm = Param("c(sample_size) score normalizer", default=1.0)
    threshold = Param("score threshold for the 0/1 prediction", default=0.5)
    score_col = Param("anomaly score column", default="outlierScore")

    def _scores(self, x: np.ndarray) -> np.ndarray:
        if len(x) == 0:
            # zero-row score: answer the empty shape directly instead
            # of compiling a degenerate traversal program per model
            # (mirrors Booster._raw_scores' round-15 fix)
            return np.zeros(0, np.float32)
        feat, thr, lft, rgt, dadj = self.trees
        stack = tuple(jnp.asarray(a) for a in (feat, thr, lft, rgt, dadj))
        xd = jnp.asarray(x, jnp.float32)
        depth_iters = int(self.max_depth) + 1
        mean_path = None
        from synapseml_tpu.gbdt import predict_route

        t, m = np.asarray(feat).shape
        if predict_route.route_predict(
                len(x), t, m, x.shape[1], 1, strict=True,
                count=False) == "pallas":
            try:
                mean_path = np.asarray(
                    _path_lengths_pallas(stack, xd, depth_iters))
                predict_route.count("pallas")
            except Exception:  # noqa: BLE001 - silent fallback
                predict_route.poison(len(x), t, m, x.shape[1], 1,
                                     strict=True)
        if mean_path is None:
            # served-by honesty (catalog contract): the routed-away
            # case AND a kernel-leg failure both count xla
            predict_route.count("xla")
            mean_path = np.asarray(_path_lengths(stack, xd, depth_iters))
        return np.power(2.0, -mean_path / max(float(self.c_norm), 1e-9))

    def _transform(self, table: Table) -> Table:
        x = np.asarray(table[self.features_col], np.float32)
        scores = self._scores(x)
        return table.with_columns({
            self.score_col: scores.astype(np.float64),
            self.prediction_col: (scores >= float(self.threshold)).astype(np.float64),
        })
