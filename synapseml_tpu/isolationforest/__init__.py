from synapseml_tpu.isolationforest.iforest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
