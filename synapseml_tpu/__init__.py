"""synapseml_tpu — a TPU-native ML framework with the capability surface of
SynapseML/MMLSpark (reference: /root/reference), rebuilt on jax/XLA/Pallas.

Layer map (SURVEY.md §1 → TPU-native):
  core/      pipeline kernel + param system (SparkML plumbing analogue)
  data/      columnar Table data plane + minibatch machinery
  runtime/   device binding, jit-cached batched executor
  parallel/  mesh bootstrap, ICI collectives, ring attention, MoE, pipeline par.
  onnx/      ONNX -> jax importer + ONNXModel transformer
  gbdt/      LightGBM-equivalent histogram GBDT on TPU
  linear/    VW-equivalent hashed linear / contextual bandit learners
  explainers/ LIME + KernelSHAP (tabular/vector/image/text)
  featurize/ auto-featurization, indexing, text featurizers
  train/     TrainClassifier/TrainRegressor, model statistics
  automl/    hyperparameter search, FindBestModel
  stages/    utility transformers
  knn/       BallTree KNN / ConditionalKNN
  recommendation/ SAR recommender + ranking evaluators
  image/     image ops, ImageFeaturizer
  dl/        deep-learning models (ResNet, tagger), CNTKModel, ModelDownloader
  io/        HTTP-on-tables, serving, PowerBI, binary reader
  cognitive/ value-or-column ServiceParams + Azure-shaped service zoo
  cyber/     AccessAnomaly collaborative-filtering anomaly detection
  native/    C++ host bridge (NativeLoader analogue) via ctypes
  codegen/   reflection-driven R wrappers + API reference
  utils/     fault tolerance, hashing, profiling utilities
"""
__version__ = "0.2.0"  # r05: adaptive hist-kernel chunking — probe verdicts re-measure

from synapseml_tpu.core.param import Param, ComplexParam, Params
from synapseml_tpu.core.pipeline import (
    Estimator, Evaluator, Model, Pipeline, PipelineModel, PipelineStage, Transformer,
)
from synapseml_tpu.data.table import Table, concat_tables
