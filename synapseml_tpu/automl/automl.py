"""AutoML: hyperparameter spaces, tuning with k-fold CV, best-model selection.

Re-design of the reference's automl package
(ref: core/.../automl/TuneHyperparameters.scala:36-254 — randomized/grid
search with thread-pool parallelism (:97-120) and k-fold CV (fit :144);
ParamSpace.scala:43, HyperparamBuilder.scala:113, DefaultHyperparams.scala;
FindBestModel.scala — evaluate candidates on one dataset, keep the best).

Candidates run concurrently on a thread pool exactly like the reference;
each fit is itself jax-accelerated, and XLA serializes device work, so the
pool mainly overlaps host-side featurization/data prep.
"""
from __future__ import annotations

import itertools
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import Estimator, Evaluator, Model
from synapseml_tpu.data.table import Table


class Dist:
    """A distribution over one hyperparameter value."""

    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid(self) -> List[Any]:
        raise NotImplementedError


class DiscreteHyperParam(Dist):
    """Uniform over an explicit list (ref: HyperparamBuilder.DiscreteHyperParam)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self):
        return list(self.values)


class RangeHyperParam(Dist):
    """Uniform over [lo, hi); int or float (ref: HyperparamBuilder.RangeHyperParam)."""

    def __init__(self, lo, hi, n_grid: int = 5):
        self.lo, self.hi, self.n_grid = lo, hi, n_grid
        self.is_int = isinstance(lo, (int, np.integer)) and isinstance(hi, (int, np.integer))

    def sample(self, rng):
        if self.is_int:
            return int(rng.integers(self.lo, self.hi))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self):
        vals = np.linspace(self.lo, self.hi, self.n_grid)
        return [int(v) for v in vals] if self.is_int else [float(v) for v in vals]


class HyperparamBuilder:
    """Collects (param name -> Dist) pairs (ref: HyperparamBuilder.scala:113)."""

    def __init__(self):
        self._dists: Dict[str, Dist] = {}

    def add_hyperparam(self, name: str, dist: Dist) -> "HyperparamBuilder":
        self._dists[name] = dist
        return self

    def build(self) -> Dict[str, Dist]:
        return dict(self._dists)


class ParamSpace:
    """Random draws over a dist map (ref: ParamSpace.scala:43 RandomSpace)."""

    def __init__(self, dists: Dict[str, Dist], seed: int = 0):
        self.dists = dists
        self.rng = np.random.default_rng(seed)

    def sample(self) -> Dict[str, Any]:
        return {k: d.sample(self.rng) for k, d in self.dists.items()}

    def param_maps(self, n: int) -> List[Dict[str, Any]]:
        return [self.sample() for _ in range(n)]


class GridSpace:
    """Full cartesian grid (ref: GridSpace in ParamSpace.scala)."""

    def __init__(self, dists: Dict[str, Dist]):
        self.dists = dists

    def param_maps(self) -> List[Dict[str, Any]]:
        names = list(self.dists)
        grids = [self.dists[n].grid() for n in names]
        return [dict(zip(names, combo)) for combo in itertools.product(*grids)]


def _kfold_indices(n: int, k: int, seed: int) -> List[Tuple[np.ndarray, np.ndarray]]:
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, k)
    out = []
    for i in range(k):
        test = folds[i]
        train = np.concatenate([folds[j] for j in range(k) if j != i])
        out.append((train, test))
    return out


class TuneHyperparameters(Estimator):
    """Randomized/grid search over estimators with k-fold CV
    (ref: TuneHyperparameters.scala:36, fit :144, thread pool :97-120)."""

    models = ComplexParam("candidate estimators")
    evaluator = ComplexParam("metric Evaluator (larger-better aware)")
    param_space = ComplexParam("ParamSpace/GridSpace or list of param maps",
                               default=None)
    number_of_runs = Param("random samples per estimator", default=8)
    number_of_folds = Param("k in k-fold CV", default=3)
    parallelism = Param("concurrent candidate fits", default=4)
    seed = Param("cv split seed", default=0)

    def _fit(self, table: Table) -> "TuneHyperparametersModel":
        models: List[Estimator] = list(self.models)
        space = self.param_space
        if space is None:
            maps: List[Dict[str, Any]] = [{}]
        elif isinstance(space, list):
            maps = space
        elif isinstance(space, GridSpace):
            maps = space.param_maps()
        else:
            maps = space.param_maps(int(self.number_of_runs))
        candidates: List[Tuple[Estimator, Dict[str, Any]]] = [
            (est, pm) for est in models for pm in maps]
        folds = _kfold_indices(table.num_rows, int(self.number_of_folds),
                               int(self.seed))
        evaluator: Evaluator = self.evaluator
        larger_better = evaluator.is_larger_better

        def run(cand: Tuple[Estimator, Dict[str, Any]]) -> float:
            est, pm = cand
            metrics = []
            for train_idx, test_idx in folds:
                model = est.copy(**pm).fit(table.take(train_idx))
                scored = model.transform(table.take(test_idx))
                metrics.append(evaluator.evaluate(scored))
            return float(np.mean(metrics))

        with ThreadPoolExecutor(max_workers=int(self.parallelism)) as pool:
            metrics = list(pool.map(run, candidates))
        best_i = int(np.argmax(metrics) if larger_better else np.argmin(metrics))
        best_est, best_pm = candidates[best_i]
        best_model = best_est.copy(**best_pm).fit(table)
        return TuneHyperparametersModel(
            best_model=best_model, best_metric=float(metrics[best_i]),
            all_metrics=[float(m) for m in metrics],
            best_params=dict(best_pm))


class TuneHyperparametersModel(Model):
    """ref: TuneHyperparameters.scala:225."""

    best_model = ComplexParam("winning fitted model")
    best_metric = Param("winning CV metric", default=None)
    best_params = ComplexParam("winning param map", default=None)
    all_metrics = ComplexParam("metric per candidate", default=None)

    def _transform(self, table: Table) -> Table:
        return self.best_model.transform(table)

    def get_best_model_info(self) -> str:
        return f"metric={self.best_metric} params={self.best_params}"


class FindBestModel(Estimator):
    """Evaluate pre-built models on one dataset, keep the best
    (ref: FindBestModel.scala)."""

    models = ComplexParam("candidate fitted models OR estimators")
    evaluator = ComplexParam("metric Evaluator")

    def _fit(self, table: Table) -> "BestModel":
        evaluator: Evaluator = self.evaluator
        metrics = []
        fitted = []
        for m in self.models:
            model = m.fit(table) if isinstance(m, Estimator) else m
            fitted.append(model)
            metrics.append(evaluator.evaluate(model.transform(table)))
        best_i = int(np.argmax(metrics) if evaluator.is_larger_better
                     else np.argmin(metrics))
        return BestModel(best_model=fitted[best_i],
                         best_metric=float(metrics[best_i]),
                         all_metrics=[float(m) for m in metrics])


class BestModel(Model):
    best_model = ComplexParam("winning model")
    best_metric = Param("winning metric", default=None)
    all_metrics = ComplexParam("metric per candidate", default=None)

    def _transform(self, table: Table) -> Table:
        return self.best_model.transform(table)


class MetricEvaluator(Evaluator):
    """Simple column-based evaluator for tuning (accuracy / mse / auc)."""

    metric = Param("accuracy | mse | auc", default="accuracy")
    label_col = Param("label column", default="label")
    prediction_col = Param("prediction column", default="prediction")
    probability_col = Param("probability column (auc)", default="probability")

    def evaluate(self, table: Table) -> float:
        y = np.asarray(table[self.label_col], np.float64)
        if self.metric == "accuracy":
            pred = np.asarray(table[self.prediction_col], np.float64)
            return float((pred == y).mean())
        if self.metric == "mse":
            pred = np.asarray(table[self.prediction_col], np.float64)
            return float(np.mean((pred - y) ** 2))
        from synapseml_tpu.train.train import _binary_auc
        probs = table[self.probability_col]
        p1 = (np.asarray([p[1] for p in probs], np.float64)
              if probs.dtype == object or probs.ndim == 2
              else np.asarray(probs, np.float64))
        return _binary_auc(p1, y)

    @property
    def is_larger_better(self) -> bool:
        return self.metric != "mse"
