from synapseml_tpu.automl.automl import (
    BestModel,
    DiscreteHyperParam,
    FindBestModel,
    GridSpace,
    HyperparamBuilder,
    MetricEvaluator,
    ParamSpace,
    RangeHyperParam,
    TuneHyperparameters,
    TuneHyperparametersModel,
)

__all__ = [
    "BestModel", "DiscreteHyperParam", "FindBestModel", "GridSpace",
    "HyperparamBuilder", "MetricEvaluator", "ParamSpace", "RangeHyperParam",
    "TuneHyperparameters", "TuneHyperparametersModel",
]
