"""Exact k-nearest-neighbours: KNN / ConditionalKNN.

Re-design of the reference's broadcast-BallTree search
(ref: core/.../nn/BallTree.scala:109-271, KNN.scala:48-126,
ConditionalKNN.scala:31-120, BoundedPriorityQueue.scala).

TPU-first: the reference walks a JVM ball tree per query row; here the index
is a dense [N, D] matrix resident on device and search is one batched
``top_k`` over a distance matrix computed on the MXU
(``q @ index.T`` dominates, so the whole search is a matmul). That is both
exact (same results as the ball tree) and the idiomatic accelerator shape of
kNN. The conditional variant masks disallowed labels with +inf before top_k
(ref: ConditionalBallTree label-filtered search).
"""
from __future__ import annotations

from functools import partial
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasInputCol, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.data.table import Table


@partial(jax.jit, static_argnames=("k",))
def _knn_search(index, queries, k: int):
    """index [N, D], queries [Q, D] -> (dist [Q, k], idx [Q, k]).

    Squared-L2 via the expanded form so the [Q, N] inner-product block runs
    on the MXU; top_k on the negated distances.
    """
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)   # [Q, 1]
    xn = jnp.sum(index * index, axis=1)[None, :]             # [1, N]
    d2 = qn + xn - 2.0 * queries @ index.T
    neg, idx = jax.lax.top_k(-d2, k)
    return jnp.sqrt(jnp.maximum(-neg, 0.0)), idx


@partial(jax.jit, static_argnames=("k",))
def _knn_search_masked(index, queries, allowed, k: int):
    """Conditional search: allowed [Q, N] bool — False entries excluded."""
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)
    xn = jnp.sum(index * index, axis=1)[None, :]
    d2 = qn + xn - 2.0 * queries @ index.T
    d2 = jnp.where(allowed, d2, jnp.inf)
    neg, idx = jax.lax.top_k(-d2, k)
    return jnp.sqrt(jnp.maximum(-neg, 0.0)), idx


class KNN(Estimator, HasInputCol, HasOutputCol):
    """Fit stores the feature matrix + payload values (ref: KNN.scala:48)."""

    values_col = Param("column carried as the match payload", default=None)
    k = Param("neighbours per query", default=5)

    def _fit(self, table: Table) -> "KNNModel":
        x = np.ascontiguousarray(np.asarray(table[self.input_col], np.float32))
        vcol = self.values_col
        values = list(table[vcol]) if vcol else list(range(len(x)))
        return KNNModel(
            index=x, values=values, k=int(self.k),
            input_col=self.input_col, output_col=self.output_col)


class KNNModel(Model, HasInputCol, HasOutputCol):
    """Batched exact top-k search (ref: KNNModel.scala:78)."""

    index = ComplexParam("[N, D] feature matrix")
    values = ComplexParam("payload per index row")
    k = Param("neighbours per query", default=5)

    def _transform(self, table: Table) -> Table:
        q = np.asarray(table[self.input_col], np.float32)
        k = min(int(self.k), len(self.index))  # top_k requires k <= N
        dist, idx = _knn_search(
            jnp.asarray(self.index), jnp.asarray(q), k)
        dist, idx = np.asarray(dist), np.asarray(idx)
        values = self.values
        out = np.empty(len(q), dtype=object)
        for i in range(len(q)):
            out[i] = [
                {"value": values[j], "distance": float(d), "index": int(j)}
                for j, d in zip(idx[i], dist[i])
            ]
        return table.with_column(self.output_col, out)


class ConditionalKNN(Estimator, HasInputCol, HasOutputCol):
    """kNN restricted per-query to an allowed label set
    (ref: ConditionalKNN.scala:31, ConditionalBallTree.scala:202)."""

    values_col = Param("payload column", default=None)
    label_col = Param("index label column", default="labels")
    conditioner_col = Param("per-query allowed label set column",
                            default="conditioner")
    k = Param("neighbours per query", default=5)

    def _fit(self, table: Table) -> "ConditionalKNNModel":
        x = np.ascontiguousarray(np.asarray(table[self.input_col], np.float32))
        vcol = self.values_col
        values = list(table[vcol]) if vcol else list(range(len(x)))
        labels = list(table[self.label_col])
        return ConditionalKNNModel(
            index=x, values=values, labels=labels, k=int(self.k),
            input_col=self.input_col, output_col=self.output_col,
            conditioner_col=self.conditioner_col)


class ConditionalKNNModel(Model, HasInputCol, HasOutputCol):
    index = ComplexParam("[N, D] feature matrix")
    values = ComplexParam("payload per index row")
    labels = ComplexParam("label per index row")
    conditioner_col = Param("per-query allowed label set column",
                            default="conditioner")
    k = Param("neighbours per query", default=5)

    def _transform(self, table: Table) -> Table:
        q = np.asarray(table[self.input_col], np.float32)
        labels = np.asarray(self.labels, dtype=object)
        allowed = np.empty((len(q), len(labels)), dtype=bool)
        for i, cond in enumerate(table[self.conditioner_col]):
            cond_set = set(cond) if not isinstance(cond, set) else cond
            allowed[i] = [l in cond_set for l in labels]
        dist, idx = _knn_search_masked(
            jnp.asarray(self.index), jnp.asarray(q), jnp.asarray(allowed),
            min(int(self.k), len(self.index)))
        dist, idx = np.asarray(dist), np.asarray(idx)
        values = self.values
        out = np.empty(len(q), dtype=object)
        for i in range(len(q)):
            out[i] = [
                {"value": values[j], "distance": float(d),
                 "label": labels[j], "index": int(j)}
                for j, d in zip(idx[i], dist[i]) if np.isfinite(d)
            ]
        return table.with_column(self.output_col, out)


# ---------------------------------------------------------------------------
# Host-side BallTree for API parity (ref: BallTree.scala:109-271). The TPU
# path above is the default; this structure exists for host-only callers and
# as an exactness cross-check in tests.
# ---------------------------------------------------------------------------

class BallTree:
    """Classic ball tree over [N, D] points with best-first k-NN queries."""

    def __init__(self, points: np.ndarray, values: Optional[Sequence[Any]] = None,
                 leaf_size: int = 50):
        self.points = np.asarray(points, np.float64)
        self.values = list(values) if values is not None else list(range(len(points)))
        self.leaf_size = leaf_size
        idx = np.arange(len(self.points))
        self.root = self._build(idx)

    def _build(self, idx: np.ndarray):
        pts = self.points[idx]
        center = pts.mean(axis=0)
        radius = float(np.sqrt(((pts - center) ** 2).sum(axis=1)).max()) if len(idx) else 0.0
        node = {"center": center, "radius": radius, "idx": idx,
                "left": None, "right": None}
        if len(idx) > self.leaf_size:
            spread = pts.max(axis=0) - pts.min(axis=0)
            dim = int(np.argmax(spread))
            order = np.argsort(pts[:, dim], kind="stable")
            half = len(idx) // 2
            node["left"] = self._build(idx[order[:half]])
            node["right"] = self._build(idx[order[half:]])
        return node

    def query(self, q: np.ndarray, k: int = 5) -> List[dict]:
        q = np.asarray(q, np.float64)
        import heapq
        best: List = []  # max-heap by -dist

        def visit(node):
            if node is None:
                return
            gap = float(np.sqrt(((q - node["center"]) ** 2).sum())) - node["radius"]
            if len(best) == k and gap > -best[0][0]:
                return
            if node["left"] is None:
                for j in node["idx"]:
                    d = float(np.sqrt(((q - self.points[j]) ** 2).sum()))
                    if len(best) < k:
                        heapq.heappush(best, (-d, int(j)))
                    elif d < -best[0][0]:
                        heapq.heapreplace(best, (-d, int(j)))
            else:
                kids = sorted(
                    (node["left"], node["right"]),
                    key=lambda c: float(np.sqrt(((q - c["center"]) ** 2).sum())))
                for c in kids:
                    visit(c)

        visit(self.root)
        return [{"value": self.values[j], "distance": -nd, "index": j}
                for nd, j in sorted(best, key=lambda t: -t[0])]
