from synapseml_tpu.knn.knn import (
    BallTree,
    ConditionalKNN,
    ConditionalKNNModel,
    KNN,
    KNNModel,
)

__all__ = ["BallTree", "ConditionalKNN", "ConditionalKNNModel", "KNN", "KNNModel"]
