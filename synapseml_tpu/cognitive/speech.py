"""Streaming speech recognition — the SpeechToTextSDK analogue.

The reference pumps audio through the native Speech SDK: a WAV header is
parsed and the PCM pulled in chunks (ref: cognitive/src/main/scala/com/
microsoft/ml/spark/cognitive/AudioStreams.scala:17-94 — PCM mono 16 kHz
16-bit asserted), the service segments speech and fires one ``recognized``
event per utterance, and each event becomes an output row when
``streamIntermediateResults`` is set (ref: SpeechToTextSDK.scala:431-509,
transformAudioRows:315-347 flatMap).

The native SDK is out of TPU scope (SURVEY §2.9), so the continuous-
recognition loop is rebuilt on the REST short-audio endpoint: the WAV is
parsed with the same format asserts, an energy-based endpointer segments
the PCM into utterances (the service-side silence detection the SDK
relies on), each utterance ships as its own WAV request through the
retrying concurrent client, and results come back as per-utterance rows
with Azure-convention ``Offset``/``Duration`` (100-ns ticks) — or as one
array column per input row when ``stream_intermediate_results`` is off,
matching the reference's two output schemas (SpeechToTextSDK.scala:417-429).
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.cognitive.base import (CognitiveServicesBase, ServiceParam,
                                          with_url_params)
from synapseml_tpu.core.param import Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import (AsyncHTTPClient, HandlingUtils,
                                   HTTPRequestData, response_to_error)

_TICKS_PER_SEC = 10_000_000  # Azure offsets/durations are 100-ns ticks


class WavStream:
    """Parsed PCM WAV (ref AudioStreams.scala:38-83: RIFF/WAVE/fmt/data
    walk with PCM, mono, 16 kHz, 16-bit asserts; extended fmt chunks are
    skipped)."""

    def __init__(self, wav_bytes: bytes, require_canonical: bool = True):
        b = memoryview(bytes(wav_bytes))
        if len(b) < 12 or bytes(b[0:4]) != b"RIFF" or bytes(b[8:12]) != b"WAVE":
            raise ValueError("not a RIFF/WAVE file")
        pos = 12
        fmt = None
        data = None
        while pos + 8 <= len(b):
            tag = bytes(b[pos:pos + 4])
            size = struct.unpack_from("<I", b, pos + 4)[0]
            body = b[pos + 8: pos + 8 + size]
            if tag == b"fmt ":
                fmt = body
            elif tag == b"data":
                data = body
            pos += 8 + size + (size & 1)  # chunks are word-aligned
        if fmt is None or data is None:
            raise ValueError("WAV is missing fmt/data chunks")
        (self.format_tag, self.channels, self.sample_rate, _, _,
         self.bits_per_sample) = struct.unpack_from("<HHIIHH", fmt, 0)
        if self.format_tag != 1:
            raise ValueError("PCM required (formatTag == 1)")
        if require_canonical:
            # the reference's stream asserts (AudioStreams.scala:64-66)
            if self.channels != 1:
                raise ValueError("file needs to be single channel")
            if self.sample_rate != 16000:
                raise ValueError("file needs to have 16000 samples per second")
            if self.bits_per_sample != 16:
                raise ValueError("file needs to have 16 bits per sample")
        self.pcm = np.frombuffer(data, dtype="<i2")
        if self.channels > 1:
            self.pcm = self.pcm.reshape(-1, self.channels)[:, 0]

    def chunks(self, chunk_ms: int = 100):
        """Pull-stream view: successive PCM chunks, the SDK read() loop."""
        step = max(1, self.sample_rate * chunk_ms // 1000)
        for i in range(0, len(self.pcm), step):
            yield self.pcm[i:i + step]


def pcm_to_wav(pcm: np.ndarray, sample_rate: int = 16000) -> bytes:
    """Canonical 16-bit mono WAV bytes for one utterance's request."""
    pcm = np.asarray(pcm, dtype="<i2")
    raw = pcm.tobytes()
    hdr = struct.pack(
        "<4sI4s4sIHHIIHH4sI", b"RIFF", 36 + len(raw), b"WAVE", b"fmt ",
        16, 1, 1, sample_rate, sample_rate * 2, 2, 16, b"data", len(raw))
    return hdr + raw


def segment_utterances(pcm: np.ndarray, sample_rate: int,
                       frame_ms: int = 30, silence_ms: int = 300,
                       min_utterance_ms: int = 120,
                       energy_threshold: float = 0.01,
                       padding_ms: int = 60) -> List[Tuple[int, int]]:
    """Energy endpointer: (start_sample, end_sample) per utterance.

    Stands in for the service-side segmentation behind the SDK's
    ``recognized`` events: a frame is speech when its RMS exceeds
    ``energy_threshold`` (relative to int16 full scale); utterances end
    after ``silence_ms`` of non-speech and carry ``padding_ms`` context.
    """
    if len(pcm) == 0:
        return []
    x = pcm.astype(np.float32) / 32768.0
    frame = max(1, sample_rate * frame_ms // 1000)
    n_frames = (len(x) + frame - 1) // frame
    pad = n_frames * frame - len(x)
    rms = np.sqrt(np.mean(
        np.pad(x, (0, pad)).reshape(n_frames, frame) ** 2, axis=1))
    speech = rms > energy_threshold
    gap_frames = max(1, silence_ms // frame_ms)
    segs: List[Tuple[int, int]] = []
    start = None
    silence_run = 0
    for i, s in enumerate(speech):
        if s:
            if start is None:
                start = i
            silence_run = 0
        elif start is not None:
            silence_run += 1
            if silence_run >= gap_frames:
                segs.append((start, i - silence_run + 1))
                start, silence_run = None, 0
    if start is not None:
        segs.append((start, n_frames))
    pad_f = padding_ms // frame_ms
    out = []
    for s, e in segs:
        if (e - s) * frame_ms < min_utterance_ms:
            continue
        out.append((max(0, (s - pad_f)) * frame,
                    min(len(pcm), (e + pad_f) * frame)))
    return out


class SpeechToTextSDK(CognitiveServicesBase):
    """Continuous recognition over REST: one request per detected
    utterance, per-utterance output rows (ref: SpeechToTextSDK.scala:431;
    response shape ref: TranscriptionResponse in SpeechSchemas.scala).

    ``stream_intermediate_results=True`` (the reference default) explodes
    each input row into one output row per utterance; ``False`` collects
    an array column. ``Offset``/``Duration`` are 100-ns ticks.
    """

    audio_bytes = ServiceParam("full wav audio bytes", required=True)
    language = ServiceParam("recognition language", default="en-US")
    format = ServiceParam("result format", default="simple")
    profanity = ServiceParam("profanity handling", default="Masked")
    stream_intermediate_results = Param(
        "one output row per utterance (vs array per input row)",
        default=True)
    frame_ms = Param("endpointer frame size ms", default=30)
    silence_ms = Param("utterance-final silence ms", default=300)
    energy_threshold = Param("speech RMS threshold (of full scale)",
                             default=0.01)
    min_utterance_ms = Param("drop utterances shorter than this",
                             default=120)

    def _utterance_request(self, wav: bytes, language, fmt, profanity,
                           key) -> HTTPRequestData:
        url = with_url_params(self.url, language=language or "en-US",
                              format=fmt or "simple",
                              profanity=profanity or "Masked")
        return HTTPRequestData(
            url=url, method="POST",
            headers={**self._headers(key),
                     "Content-Type": "audio/wav; codecs=audio/pcm"},
            entity=wav)

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        names = self._service_param_names()
        resolved = {name: self._resolve(name, table, n) for name in names}

        # segment every row, then fire ALL utterances through one
        # concurrent client (the SDK overlaps recognition with pumping)
        reqs: List[Optional[HTTPRequestData]] = []
        owners: List[int] = []
        spans: List[Tuple[int, int, int]] = []  # (offset_ticks, dur_ticks, sr)
        per_row_counts = [0] * n
        for i in range(n):
            audio = resolved["audio_bytes"][i]
            if audio is None:
                continue
            ws = WavStream(audio)
            segs = segment_utterances(
                ws.pcm, ws.sample_rate, frame_ms=self.frame_ms,
                silence_ms=self.silence_ms,
                min_utterance_ms=self.min_utterance_ms,
                energy_threshold=self.energy_threshold)
            for s, e in segs:
                reqs.append(self._utterance_request(
                    pcm_to_wav(ws.pcm[s:e], ws.sample_rate),
                    resolved["language"][i], resolved["format"][i],
                    resolved["profanity"][i],
                    resolved["subscription_key"][i]))
                owners.append(i)
                spans.append((s * _TICKS_PER_SEC // ws.sample_rate,
                              (e - s) * _TICKS_PER_SEC // ws.sample_rate,
                              ws.sample_rate))
                per_row_counts[i] += 1

        client = AsyncHTTPClient(
            self.concurrency, HandlingUtils.advanced(*self.backoffs),
            self.timeout)
        resps = client.send_all(reqs)

        results: List[Dict[str, Any]] = []
        errors: List[Any] = []
        for r, (off, dur, _) in zip(resps, spans):
            err = None if r is None else response_to_error(r)
            if r is None or err is not None:
                results.append(None)
                errors.append(err)
                continue
            try:
                parsed = r.json()
                results.append({
                    "DisplayText": parsed.get("DisplayText"),
                    "RecognitionStatus": parsed.get("RecognitionStatus"),
                    "Offset": off, "Duration": dur,
                })
                errors.append(None)
            except (json.JSONDecodeError, AttributeError) as e:
                results.append(None)
                errors.append({"status_code": r.status_code,
                               "reason": f"parse error: {e}",
                               "body": r.text[:2048]})

        if self.stream_intermediate_results:
            # flatMap: each utterance becomes a row (rows with no
            # utterances keep one null row, as shouldSkip does)
            counts = [max(1, c) for c in per_row_counts]
            cols = {c: np.repeat(table[c], counts, axis=0)
                    for c in table.columns}
            out = np.empty(sum(counts), dtype=object)
            errs = np.empty(sum(counts), dtype=object)
            out[:] = None
            errs[:] = None
            row_base = np.cumsum([0] + counts[:-1])
            cursor = [0] * n
            for j, i in enumerate(owners):
                k = row_base[i] + cursor[i]
                out[k] = results[j]
                errs[k] = errors[j]
                cursor[i] += 1
            return Table(dict(cols, **{self.output_col: out,
                                       self.error_col: errs}))

        out = np.empty(n, dtype=object)
        errs = np.empty(n, dtype=object)
        for i in range(n):
            mine = [j for j, o in enumerate(owners) if o == i]
            out[i] = [results[j] for j in mine]
            errs[i] = next((errors[j] for j in mine
                            if errors[j] is not None), None)
        return table.with_columns({self.output_col: out,
                                   self.error_col: errs})


class AudioFeaturizer(Transformer):
    """Log-mel spectrogram features computed ON DEVICE.

    The reference ships audio to the Azure Speech SDK, which featurizes
    server-side; here the spectral front end is local TPU compute: the
    transformer composes an ONNX graph from the importer's own STFT /
    MelWeightMatrix / ReduceSumSquare / MatMul / Log ops (dogfooding the
    north-star path) and runs it through the BatchedExecutor — framing +
    one batched rfft + the mel projection as a single MXU matmul.

    Input column: 1-D float waveforms (object column of arrays, or a 2-D
    equal-length column) or raw WAV bytes (parsed via WavStream's
    format asserts). Clips in a batch pad to the longest; emitted
    frame counts follow each clip's true length.
    """

    input_col = Param("waveform / wav-bytes column", default="audio")
    output_col = Param("log-mel output column", default="features")
    sample_rate = Param("sample rate when input is raw waveform",
                        default=16000)
    frame_length = Param("window size in samples", default=400)
    frame_step = Param("hop in samples", default=160)
    num_mel_bins = Param("mel filter count", default=64)
    lower_hz = Param("mel filterbank lower edge", default=125.0)
    upper_hz = Param("mel filterbank upper edge", default=7600.0)
    log_offset = Param("epsilon inside the log", default=1e-6)

    def _graph_bytes(self, sr: int) -> bytes:
        from synapseml_tpu.onnx.builder import GraphBuilder

        flen, step = int(self.frame_length), int(self.frame_step)
        g = GraphBuilder(name="log_mel", opset=17)
        sig = g.add_input("signal", np.float32, ["N", "L"])
        win = g.add_initializer(
            "win", np.hanning(flen).astype(np.float32))
        stft = g.add_node(
            "STFT", [sig, g.add_initializer(
                "step", np.asarray(step, np.int64)), win], onesided=1)
        power = g.add_node(
            "ReduceSumSquare",
            [stft, g.add_initializer("axes", np.asarray([-1], np.int64))],
            keepdims=0)
        mel = g.add_node("MelWeightMatrix", [
            g.add_initializer("nmel", np.asarray(
                int(self.num_mel_bins), np.int64)),
            g.add_initializer("ndft", np.asarray(flen, np.int64)),
            g.add_initializer("sr", np.asarray(sr, np.int64)),
            g.add_initializer("lo", np.asarray(
                float(self.lower_hz), np.float32)),
            g.add_initializer("hi", np.asarray(
                float(self.upper_hz), np.float32))])
        melspec = g.add_node("MatMul", [power, mel])
        logmel = g.add_node("Log", [g.add_node("Add", [
            melspec, g.add_initializer("eps", np.asarray(
                float(self.log_offset), np.float32))])])
        g.add_output(logmel, np.float32, None)
        return g.to_bytes()

    def _waveform(self, v) -> Tuple[np.ndarray, int]:
        if isinstance(v, (bytes, bytearray)):
            ws = WavStream(bytes(v))
            return ws.pcm.astype(np.float32) / 32768.0, ws.sample_rate
        return np.asarray(v, np.float32), int(self.sample_rate)

    def _transform(self, table: Table) -> Table:
        from synapseml_tpu.onnx.importer import import_model
        from synapseml_tpu.runtime.executor import BatchedExecutor

        vals = table[self.input_col]
        waves, srs = zip(*[self._waveform(v) for v in vals]) \
            if table.num_rows else ((), ())
        if len(set(srs)) > 1:
            raise ValueError(
                f"AudioFeaturizer: mixed sample rates {sorted(set(srs))} "
                "in one batch")
        sr = srs[0] if srs else int(self.sample_rate)
        flen, step = int(self.frame_length), int(self.frame_step)
        cache = self.__dict__.setdefault("_audio_cache", {})
        key = (sr, flen, step, int(self.num_mel_bins),
               float(self.lower_hz), float(self.upper_hz),
               float(self.log_offset))
        if key not in cache:
            graph = import_model(self._graph_bytes(sr))
            cache.clear()  # one device-resident config at a time
            cache[key] = (graph, BatchedExecutor(
                graph.apply, bound_args=(graph.params,)))
        _, executor = cache[key]

        # bucket the padded length to a power-of-two frame count: every
        # distinct clip length would otherwise trace a fresh XLA program
        # (the executor buckets only the batch axis); trailing padding is
        # harmless because each row is trimmed to its true frame count
        max_len = max(flen, *(len(w) for w in waves)) if waves else flen
        frames = 1 + (max_len - flen) // step \
            + (1 if (max_len - flen) % step else 0)
        frames_b = 1 << max(frames - 1, 0).bit_length() if frames > 1 \
            else 1
        batch = np.zeros(
            (table.num_rows, flen + (frames_b - 1) * step), np.float32)
        for i, w in enumerate(waves):
            batch[i, :len(w)] = w
        (feats,) = executor(batch)
        out = np.empty(table.num_rows, dtype=object)
        for i, w in enumerate(waves):
            n_frames = 1 + (len(w) - flen) // step if len(w) >= flen else 0
            out[i] = np.asarray(feats[i][:n_frames], np.float32)
        return table.with_column(self.output_col, out)


def wav_to_utterance_rows(wav_bytes: bytes,
                          featurizer: Optional["AudioFeaturizer"] = None,
                          **endpointer_kw) -> Table:
    """One call from WAV bytes to per-utterance feature rows — the front
    half of the reference's speech scenario (SpeechToTextSDK.scala:431 +
    AudioStreams.scala:94: stream -> segment -> per-utterance requests),
    with featurization as local TPU compute instead of a service call.

    Parses the WAV (canonical-format asserts), segments utterances with
    the energy endpointer, and runs the on-device log-mel
    :class:`AudioFeaturizer` over ONE batch of all utterances. Returns a
    Table with per-utterance rows: ``utterance`` (index), ``t_start`` /
    ``t_end`` (seconds), ``audio`` (float waveform) and the featurizer's
    output column (log-mel ``[frames, num_mel_bins]``). Feed the feature
    column to any sequence model (the recurrent CNTK path, the ONNX
    BiLSTM tagger, ...) for the back half.
    """
    ws = WavStream(bytes(wav_bytes))
    segs = segment_utterances(ws.pcm, ws.sample_rate, **endpointer_kw)
    feat = featurizer or AudioFeaturizer()
    audio = np.empty(len(segs), dtype=object)
    for i, (s, e) in enumerate(segs):
        audio[i] = ws.pcm[s:e].astype(np.float32) / 32768.0
    table = Table({
        "utterance": np.arange(len(segs), dtype=np.int64),
        "t_start": np.asarray([s / ws.sample_rate for s, _ in segs],
                              np.float64),
        "t_end": np.asarray([e / ws.sample_rate for _, e in segs],
                            np.float64),
        str(feat.input_col): audio,
    })
    if not segs:
        table = table.with_column(str(feat.output_col),
                                  np.empty(0, dtype=object))
        return table
    if int(feat.sample_rate) != ws.sample_rate:
        # copy() scopes the rate override to this call — mutating a
        # shared featurizer would silently re-rate the caller's other
        # pipelines. Matching-rate calls (the streaming common case)
        # keep the caller's instance and its warm compiled-graph cache.
        feat = feat.copy(sample_rate=ws.sample_rate)
    return feat.transform(table)


def utterance_feature_batch(rows: Table, feature_col: str = "features"):
    """Pad per-utterance ``[frames, D]`` features into one ``[U, T, D]``
    batch for a sequence model (one device placement, static shapes);
    returns ``(batch, frame_counts)`` — trim each row's output back to
    its true frame count with ``frame_counts``."""
    feats = [np.asarray(f, np.float32) for f in rows[feature_col]]
    n_frames = np.asarray([len(f) for f in feats], np.int64)
    if not len(feats):
        return np.zeros((0, 0, 0), np.float32), n_frames
    batch = np.zeros((len(feats), int(n_frames.max()), feats[0].shape[1]),
                     np.float32)
    for i, f in enumerate(feats):
        batch[i, :len(f)] = f
    return batch, n_frames
