"""Streaming speech recognition — the SpeechToTextSDK analogue.

The reference pumps audio through the native Speech SDK: a WAV header is
parsed and the PCM pulled in chunks (ref: cognitive/src/main/scala/com/
microsoft/ml/spark/cognitive/AudioStreams.scala:17-94 — PCM mono 16 kHz
16-bit asserted), the service segments speech and fires one ``recognized``
event per utterance, and each event becomes an output row when
``streamIntermediateResults`` is set (ref: SpeechToTextSDK.scala:431-509,
transformAudioRows:315-347 flatMap).

The native SDK is out of TPU scope (SURVEY §2.9), so the continuous-
recognition loop is rebuilt on the REST short-audio endpoint: the WAV is
parsed with the same format asserts, an energy-based endpointer segments
the PCM into utterances (the service-side silence detection the SDK
relies on), each utterance ships as its own WAV request through the
retrying concurrent client, and results come back as per-utterance rows
with Azure-convention ``Offset``/``Duration`` (100-ns ticks) — or as one
array column per input row when ``stream_intermediate_results`` is off,
matching the reference's two output schemas (SpeechToTextSDK.scala:417-429).
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.cognitive.base import (CognitiveServicesBase, ServiceParam,
                                          with_url_params)
from synapseml_tpu.core.param import Param
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import (AsyncHTTPClient, HandlingUtils,
                                   HTTPRequestData, response_to_error)

_TICKS_PER_SEC = 10_000_000  # Azure offsets/durations are 100-ns ticks


class WavStream:
    """Parsed PCM WAV (ref AudioStreams.scala:38-83: RIFF/WAVE/fmt/data
    walk with PCM, mono, 16 kHz, 16-bit asserts; extended fmt chunks are
    skipped)."""

    def __init__(self, wav_bytes: bytes, require_canonical: bool = True):
        b = memoryview(bytes(wav_bytes))
        if len(b) < 12 or bytes(b[0:4]) != b"RIFF" or bytes(b[8:12]) != b"WAVE":
            raise ValueError("not a RIFF/WAVE file")
        pos = 12
        fmt = None
        data = None
        while pos + 8 <= len(b):
            tag = bytes(b[pos:pos + 4])
            size = struct.unpack_from("<I", b, pos + 4)[0]
            body = b[pos + 8: pos + 8 + size]
            if tag == b"fmt ":
                fmt = body
            elif tag == b"data":
                data = body
            pos += 8 + size + (size & 1)  # chunks are word-aligned
        if fmt is None or data is None:
            raise ValueError("WAV is missing fmt/data chunks")
        (self.format_tag, self.channels, self.sample_rate, _, _,
         self.bits_per_sample) = struct.unpack_from("<HHIIHH", fmt, 0)
        if self.format_tag != 1:
            raise ValueError("PCM required (formatTag == 1)")
        if require_canonical:
            # the reference's stream asserts (AudioStreams.scala:64-66)
            if self.channels != 1:
                raise ValueError("file needs to be single channel")
            if self.sample_rate != 16000:
                raise ValueError("file needs to have 16000 samples per second")
            if self.bits_per_sample != 16:
                raise ValueError("file needs to have 16 bits per sample")
        self.pcm = np.frombuffer(data, dtype="<i2")
        if self.channels > 1:
            self.pcm = self.pcm.reshape(-1, self.channels)[:, 0]

    def chunks(self, chunk_ms: int = 100):
        """Pull-stream view: successive PCM chunks, the SDK read() loop."""
        step = max(1, self.sample_rate * chunk_ms // 1000)
        for i in range(0, len(self.pcm), step):
            yield self.pcm[i:i + step]


def pcm_to_wav(pcm: np.ndarray, sample_rate: int = 16000) -> bytes:
    """Canonical 16-bit mono WAV bytes for one utterance's request."""
    pcm = np.asarray(pcm, dtype="<i2")
    raw = pcm.tobytes()
    hdr = struct.pack(
        "<4sI4s4sIHHIIHH4sI", b"RIFF", 36 + len(raw), b"WAVE", b"fmt ",
        16, 1, 1, sample_rate, sample_rate * 2, 2, 16, b"data", len(raw))
    return hdr + raw


def segment_utterances(pcm: np.ndarray, sample_rate: int,
                       frame_ms: int = 30, silence_ms: int = 300,
                       min_utterance_ms: int = 120,
                       energy_threshold: float = 0.01,
                       padding_ms: int = 60) -> List[Tuple[int, int]]:
    """Energy endpointer: (start_sample, end_sample) per utterance.

    Stands in for the service-side segmentation behind the SDK's
    ``recognized`` events: a frame is speech when its RMS exceeds
    ``energy_threshold`` (relative to int16 full scale); utterances end
    after ``silence_ms`` of non-speech and carry ``padding_ms`` context.
    """
    if len(pcm) == 0:
        return []
    x = pcm.astype(np.float32) / 32768.0
    frame = max(1, sample_rate * frame_ms // 1000)
    n_frames = (len(x) + frame - 1) // frame
    pad = n_frames * frame - len(x)
    rms = np.sqrt(np.mean(
        np.pad(x, (0, pad)).reshape(n_frames, frame) ** 2, axis=1))
    speech = rms > energy_threshold
    gap_frames = max(1, silence_ms // frame_ms)
    segs: List[Tuple[int, int]] = []
    start = None
    silence_run = 0
    for i, s in enumerate(speech):
        if s:
            if start is None:
                start = i
            silence_run = 0
        elif start is not None:
            silence_run += 1
            if silence_run >= gap_frames:
                segs.append((start, i - silence_run + 1))
                start, silence_run = None, 0
    if start is not None:
        segs.append((start, n_frames))
    pad_f = padding_ms // frame_ms
    out = []
    for s, e in segs:
        if (e - s) * frame_ms < min_utterance_ms:
            continue
        out.append((max(0, (s - pad_f)) * frame,
                    min(len(pcm), (e + pad_f) * frame)))
    return out


class SpeechToTextSDK(CognitiveServicesBase):
    """Continuous recognition over REST: one request per detected
    utterance, per-utterance output rows (ref: SpeechToTextSDK.scala:431;
    response shape ref: TranscriptionResponse in SpeechSchemas.scala).

    ``stream_intermediate_results=True`` (the reference default) explodes
    each input row into one output row per utterance; ``False`` collects
    an array column. ``Offset``/``Duration`` are 100-ns ticks.
    """

    audio_bytes = ServiceParam("full wav audio bytes", required=True)
    language = ServiceParam("recognition language", default="en-US")
    format = ServiceParam("result format", default="simple")
    profanity = ServiceParam("profanity handling", default="Masked")
    stream_intermediate_results = Param(
        "one output row per utterance (vs array per input row)",
        default=True)
    frame_ms = Param("endpointer frame size ms", default=30)
    silence_ms = Param("utterance-final silence ms", default=300)
    energy_threshold = Param("speech RMS threshold (of full scale)",
                             default=0.01)
    min_utterance_ms = Param("drop utterances shorter than this",
                             default=120)

    def _utterance_request(self, wav: bytes, language, fmt, profanity,
                           key) -> HTTPRequestData:
        url = with_url_params(self.url, language=language or "en-US",
                              format=fmt or "simple",
                              profanity=profanity or "Masked")
        return HTTPRequestData(
            url=url, method="POST",
            headers={**self._headers(key),
                     "Content-Type": "audio/wav; codecs=audio/pcm"},
            entity=wav)

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        names = self._service_param_names()
        resolved = {name: self._resolve(name, table, n) for name in names}

        # segment every row, then fire ALL utterances through one
        # concurrent client (the SDK overlaps recognition with pumping)
        reqs: List[Optional[HTTPRequestData]] = []
        owners: List[int] = []
        spans: List[Tuple[int, int, int]] = []  # (offset_ticks, dur_ticks, sr)
        per_row_counts = [0] * n
        for i in range(n):
            audio = resolved["audio_bytes"][i]
            if audio is None:
                continue
            ws = WavStream(audio)
            segs = segment_utterances(
                ws.pcm, ws.sample_rate, frame_ms=self.frame_ms,
                silence_ms=self.silence_ms,
                min_utterance_ms=self.min_utterance_ms,
                energy_threshold=self.energy_threshold)
            for s, e in segs:
                reqs.append(self._utterance_request(
                    pcm_to_wav(ws.pcm[s:e], ws.sample_rate),
                    resolved["language"][i], resolved["format"][i],
                    resolved["profanity"][i],
                    resolved["subscription_key"][i]))
                owners.append(i)
                spans.append((s * _TICKS_PER_SEC // ws.sample_rate,
                              (e - s) * _TICKS_PER_SEC // ws.sample_rate,
                              ws.sample_rate))
                per_row_counts[i] += 1

        client = AsyncHTTPClient(
            self.concurrency, HandlingUtils.advanced(*self.backoffs),
            self.timeout)
        resps = client.send_all(reqs)

        results: List[Dict[str, Any]] = []
        errors: List[Any] = []
        for r, (off, dur, _) in zip(resps, spans):
            err = None if r is None else response_to_error(r)
            if r is None or err is not None:
                results.append(None)
                errors.append(err)
                continue
            try:
                parsed = r.json()
                results.append({
                    "DisplayText": parsed.get("DisplayText"),
                    "RecognitionStatus": parsed.get("RecognitionStatus"),
                    "Offset": off, "Duration": dur,
                })
                errors.append(None)
            except (json.JSONDecodeError, AttributeError) as e:
                results.append(None)
                errors.append({"status_code": r.status_code,
                               "reason": f"parse error: {e}",
                               "body": r.text[:2048]})

        if self.stream_intermediate_results:
            # flatMap: each utterance becomes a row (rows with no
            # utterances keep one null row, as shouldSkip does)
            counts = [max(1, c) for c in per_row_counts]
            cols = {c: np.repeat(table[c], counts, axis=0)
                    for c in table.columns}
            out = np.empty(sum(counts), dtype=object)
            errs = np.empty(sum(counts), dtype=object)
            out[:] = None
            errs[:] = None
            row_base = np.cumsum([0] + counts[:-1])
            cursor = [0] * n
            for j, i in enumerate(owners):
                k = row_base[i] + cursor[i]
                out[k] = results[j]
                errs[k] = errors[j]
                cursor[i] += 1
            return Table(dict(cols, **{self.output_col: out,
                                       self.error_col: errs}))

        out = np.empty(n, dtype=object)
        errs = np.empty(n, dtype=object)
        for i in range(n):
            mine = [j for j, o in enumerate(owners) if o == i]
            out[i] = [results[j] for j in mine]
            errs[i] = next((errors[j] for j in mine
                            if errors[j] is not None), None)
        return table.with_columns({self.output_col: out,
                                   self.error_col: errs})
