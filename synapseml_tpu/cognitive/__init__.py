"""Cognitive services layer — the reference's largest module (9,186 LoC
Scala), rebuilt over the table-native HTTP stack (SURVEY.md §2.8).
"""
from synapseml_tpu.cognitive.base import (  # noqa: F401
    BatchedTextServiceBase,
    CognitiveServicesBase,
    HasAsyncReply,
    HasServiceParams,
    ServiceParam,
)
from synapseml_tpu.cognitive.face import (  # noqa: F401
    FindSimilarFace,
    GroupFaces,
    IdentifyFaces,
    VerifyFaces,
)
from synapseml_tpu.cognitive.form import (  # noqa: F401
    AnalyzeBusinessCards,
    AnalyzeCustomModel,
    AnalyzeIDDocuments,
    AnalyzeInvoices,
    AnalyzeLayout,
    AnalyzeReceipts,
    GetCustomModel,
    ListCustomModels,
    flatten_document_results,
    flatten_read_results,
)
from synapseml_tpu.cognitive.speech import (  # noqa: F401
    AudioFeaturizer,
    SpeechToTextSDK,
    WavStream,
    pcm_to_wav,
    segment_utterances,
    utterance_feature_batch,
    wav_to_utterance_rows,
)
from synapseml_tpu.cognitive.services import (  # noqa: F401
    AnalyzeImage,
    AzureSearchWriter,
    BingImageSearch,
    BreakSentence,
    DescribeImage,
    DescribeImageExtended,
    Detect,
    DetectEntireSeries,
    DetectFace,
    DetectLastAnomaly,
    DictionaryExamples,
    DictionaryLookup,
    DocumentTranslator,
    GenerateThumbnails,
    KeyPhraseExtractor,
    LanguageDetector,
    NER,
    OCR,
    ReadImage,
    RecognizeDomainSpecificContent,
    RecognizeText,
    SpeechToText,
    TagImage,
    TextSentiment,
    Translate,
    Transliterate,
    get_speaker_profile,
)
