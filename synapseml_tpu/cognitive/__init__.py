"""Cognitive services layer — the reference's largest module (9,186 LoC
Scala), rebuilt over the table-native HTTP stack (SURVEY.md §2.8).
"""
from synapseml_tpu.cognitive.base import (  # noqa: F401
    BatchedTextServiceBase,
    CognitiveServicesBase,
    HasServiceParams,
    ServiceParam,
)
from synapseml_tpu.cognitive.services import (  # noqa: F401
    AnalyzeImage,
    AzureSearchWriter,
    BingImageSearch,
    DescribeImage,
    DetectEntireSeries,
    DetectFace,
    DetectLastAnomaly,
    KeyPhraseExtractor,
    LanguageDetector,
    NER,
    OCR,
    SpeechToText,
    TextSentiment,
    Translate,
)
