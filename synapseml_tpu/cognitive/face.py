"""Face API services beyond detection.

Rebuild of the reference's Face module
(ref: cognitive/src/main/scala/com/microsoft/ml/spark/cognitive/Face.scala —
FindSimilarFace:96, GroupFaces:186, IdentifyFaces:208, VerifyFaces:278;
each posts a JSON body assembled from ServiceParams, with exactly the
set-or-omitted field semantics of ``prepareEntity``).
"""
from __future__ import annotations

from synapseml_tpu.cognitive.base import CognitiveServicesBase, ServiceParam


class _FaceJsonService(CognitiveServicesBase):
    """Shared body assembly: every non-None ServiceParam value lands in
    the JSON body under its camelCase field name (ref: Face.scala
    prepareEntity pattern :77-88, :352-356)."""

    _body_fields: tuple = ()
    _required_any: tuple = ()

    @staticmethod
    def _camel(name: str) -> str:
        head, *rest = name.split("_")
        return head + "".join(w.capitalize() for w in rest)

    def _build_request(self, rv):
        body = {
            self._camel(f): rv[f]
            for f in self._body_fields if rv.get(f) is not None
        }
        if self._required_any and not any(
                rv.get(f) is not None for f in self._required_any):
            return None
        return self._post(body, rv["subscription_key"])


class FindSimilarFace(_FaceJsonService):
    """Similar-face search against a face list / large face list / raw
    faceId array (ref: Face.scala FindSimilarFace:96-184)."""

    face_id = ServiceParam("query faceId from DetectFace", required=True)
    face_list_id = ServiceParam("faceListId to search")
    large_face_list_id = ServiceParam("largeFaceListId to search")
    face_ids = ServiceParam("candidate faceId array (max 1000)")
    max_num_of_candidates_returned = ServiceParam("top candidates (1-1000)")
    mode = ServiceParam("matchPerson or matchFace")

    _body_fields = ("face_id", "face_list_id", "large_face_list_id",
                    "face_ids", "max_num_of_candidates_returned", "mode")
    _required_any = ("face_id",)


class GroupFaces(_FaceJsonService):
    """Divide candidate faces into groups by similarity
    (ref: Face.scala GroupFaces:186-206)."""

    face_ids = ServiceParam("candidate faceId array (max 1000)",
                            required=True)

    _body_fields = ("face_ids",)
    _required_any = ("face_ids",)

    def _parse_response(self, parsed):
        return {"groups": parsed.get("groups", []),
                "messyGroup": parsed.get("messyGroup", [])}


class IdentifyFaces(_FaceJsonService):
    """1-to-many identification against a person group
    (ref: Face.scala IdentifyFaces:208-276)."""

    face_ids = ServiceParam("query faceIds (1-10)", required=True)
    person_group_id = ServiceParam("personGroupId to search")
    large_person_group_id = ServiceParam("largePersonGroupId to search")
    max_num_of_candidates_returned = ServiceParam("top candidates (1-5)")
    confidence_threshold = ServiceParam("custom identification threshold")

    _body_fields = ("face_ids", "person_group_id", "large_person_group_id",
                    "max_num_of_candidates_returned", "confidence_threshold")
    _required_any = ("face_ids",)


class VerifyFaces(_FaceJsonService):
    """Face-to-face or face-to-person verification
    (ref: Face.scala VerifyFaces:278-355 — faceId1+faceId2, or
    faceId+personId+{personGroupId|largePersonGroupId}; response is
    {isIdentical, confidence} :286-287)."""

    face_id1 = ServiceParam("first faceId")
    face_id2 = ServiceParam("second faceId")
    face_id = ServiceParam("faceId for face-to-person")
    person_group_id = ServiceParam("personGroupId of the person")
    large_person_group_id = ServiceParam("largePersonGroupId of the person")
    person_id = ServiceParam("personId to verify against")

    _body_fields = ("face_id1", "face_id2", "face_id", "person_id",
                    "person_group_id", "large_person_group_id")
    _required_any = ("face_id1", "face_id")

    def _parse_response(self, parsed):
        return {"isIdentical": parsed.get("isIdentical"),
                "confidence": parsed.get("confidence")}
