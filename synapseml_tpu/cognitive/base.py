"""Cognitive-service base: value-or-column params over the HTTP stack.

Rebuild of the reference's cognitive module core
(ref: cognitive/src/main/scala/com/microsoft/ml/spark/cognitive/CognitiveServiceBase.scala
— ``ServiceParam[T]``:29-127 (every request field settable as a literal or
bound to a column), ``CognitiveServicesBase.getInternalTransformer``:274-300
(each service builds a SimpleHTTPTransformer pipeline internally),
subscription key / location traits :128-256, error-column pattern).

A service transformer here:
1. resolves every ServiceParam per row (literal or column),
2. builds one HTTP request per row (or per mini-batch for batched
   services) via ``_build_request``,
3. fires them through the retrying concurrent client,
4. parses JSON through ``_parse_response`` into the output column, with
   failures flowing to ``error_col`` instead of aborting the batch.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from synapseml_tpu.core.param import Param, Params, _json_default
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import (AsyncHTTPClient, HandlingUtils,
                                   HTTPRequestData, HTTPResponseData)


def with_url_params(url: str, **params: Any) -> str:
    """Append non-None params to a URL, properly encoded — row-bound
    values must never be spliced raw into query strings."""
    from urllib.parse import urlencode

    items = {k: v for k, v in params.items() if v is not None}
    if not items:
        return url
    sep = "&" if "?" in url else "?"
    return f"{url}{sep}{urlencode(items)}"


class ServiceParam(Param):
    """A request field settable as a literal value OR bound to a column
    (ref: CognitiveServiceBase.scala ServiceParam:29).

    Stored in the param map as ``{"value": v}`` or ``{"col": name}`` so it
    serializes like any other param.
    """

    __slots__ = ("required",)

    def __init__(self, doc: str = "", default: Any = None,
                 required: bool = False):
        super().__init__(doc, default={"value": default}
                         if default is not None else None)
        self.required = required


class HasServiceParams(Params):
    """Resolution helpers + the fluent ``set_x``/``set_x_col`` surface."""

    def set_service_value(self, name: str, value: Any) -> "HasServiceParams":
        self.set(**{name: {"value": value}})
        return self

    def set_service_col(self, name: str, col: str) -> "HasServiceParams":
        self.set(**{name: {"col": col}})
        return self

    def _resolve(self, name: str, table: Table, n: int) -> List[Any]:
        """Per-row values for one ServiceParam (literal -> broadcast)."""
        spec = getattr(self, name)
        if spec is None:
            if getattr(type(self), name).required:
                raise ValueError(f"service param {name!r} is required "
                                 f"(set a value or bind a column)")
            return [None] * n
        if "col" in spec:
            return list(table[spec["col"]])
        return [spec["value"]] * n


class CognitiveServicesBase(Transformer, HasServiceParams):
    """Shared service plumbing (ref: CognitiveServicesBaseNoHandler:258,
    CognitiveServicesBase:315)."""

    subscription_key = ServiceParam("API key (value or column)")
    url = Param("service endpoint URL", default=None)
    output_col = Param("parsed output column", default="out")
    error_col = Param("error column", default="errors")
    concurrency = Param("max in-flight requests", default=4)
    timeout = Param("per-request timeout seconds", default=60.0)
    backoffs = Param("retry backoff schedule ms", default=(100, 500, 1000))

    # -- subclass surface ----------------------------------------------
    def _build_request(self, row_vals: Dict[str, Any]) -> Optional[HTTPRequestData]:
        """One request from this row's resolved service params (None row
        values -> None request -> null output row)."""
        raise NotImplementedError

    def _parse_response(self, parsed_json: Any) -> Any:
        """Service-specific extraction from the response JSON."""
        return parsed_json

    def _extract_output(self, resp: HTTPResponseData) -> Any:
        """Full-response hook; binary services (thumbnails) override this
        to bypass JSON parsing."""
        return self._parse_response(resp.json())

    def _handle_response(self, client, req: HTTPRequestData,
                         resp: Optional[HTTPResponseData]
                         ) -> Optional[HTTPResponseData]:
        """Post-send hook; the async-reply mixin turns a 202 +
        Operation-Location into the polled final response here."""
        return resp

    def _service_param_names(self) -> List[str]:
        return [
            name for name, p in type(self).params().items()
            if isinstance(p, ServiceParam)
        ]

    # -- shared machinery ----------------------------------------------
    def _headers(self, key: Optional[str]) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if key:
            h["Ocp-Apim-Subscription-Key"] = str(key)
        return h

    def _post(self, body: Any, key: Optional[str],
              url: Optional[str] = None) -> HTTPRequestData:
        return HTTPRequestData(
            url=url or self.url, method="POST", headers=self._headers(key),
            entity=json.dumps(body, default=_json_default).encode("utf-8"))

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        names = self._service_param_names()
        resolved = {name: self._resolve(name, table, n) for name in names}
        reqs: List[Optional[HTTPRequestData]] = []
        for i in range(n):
            row_vals = {name: resolved[name][i] for name in names}
            reqs.append(self._build_request(row_vals))

        client = AsyncHTTPClient(
            self.concurrency, HandlingUtils.advanced(*self.backoffs),
            self.timeout)
        resps = client.send_all(
            reqs, post=lambda q, r: self._handle_response(client, q, r))

        from synapseml_tpu.io.http import response_to_error

        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        for i, r in enumerate(resps):
            out[i] = None
            errors[i] = None if r is None else response_to_error(r)
            if r is None or errors[i] is not None:
                continue
            try:
                out[i] = self._extract_output(r)
            except (json.JSONDecodeError, KeyError, TypeError,
                    IndexError, AttributeError) as e:
                errors[i] = {"status_code": r.status_code,
                             "reason": f"parse error: {e}",
                             "body": r.text[:2048]}
        return table.with_columns({self.output_col: out,
                                   self.error_col: errors})


class HasAsyncReply(Params):
    """Long-running-operation reply handling: a 202 Accepted with an
    ``Operation-Location`` header is polled (GET + key header) until the
    body's ``status`` reaches succeeded/failed
    (ref: ComputerVision.scala BasicAsyncReply:211-257, HasAsyncReply
    :259-288 — backoffs/maxPollingRetries/pollingDelay params).

    A polling timeout becomes a synthetic 504 response so the failure
    lands in the error column instead of aborting the batch (the
    reference throws; the error-col contract here is stronger).
    """

    polling_delay_ms = Param("ms between polls", default=300)
    max_polling_retries = Param("number of times to poll", default=1000)

    #: statuses that mean "keep polling"; anything else is terminal (an
    #: unknown or missing status — e.g. an expired-op error body — must
    #: flow to the error column, never crash the batch)
    _PENDING_STATUSES = frozenset(
        {"notstarted", "running", "analyzing", "cancelling", "queued"})
    _FAILED_STATUSES = frozenset(
        {"failed", "cancelled", "validationfailed"})

    def _query_for_result(self, client, key: Optional[str],
                          location: str) -> Optional[HTTPResponseData]:
        headers = {} if not key else {"Ocp-Apim-Subscription-Key": str(key)}
        resp = client.send(HTTPRequestData(
            url=location, method="GET", headers=headers))
        try:
            status = str(resp.json().get("status", "")).lower()
        except (json.JSONDecodeError, AttributeError):
            return resp  # non-JSON terminal body
        if status in self._PENDING_STATUSES:
            return None
        if status in self._FAILED_STATUSES:
            # surface as a non-2xx so response_to_error catches it
            # instead of the row masquerading as an empty success
            return HTTPResponseData(
                status_code=502,
                reason=f"async operation ended in status {status!r}",
                headers=resp.headers, entity=resp.entity)
        return resp

    def _handle_response(self, client, req, resp):
        if resp is None or resp.status_code != 202:
            return resp
        location = next(
            (v for k, v in (resp.headers or {}).items()
             if k.lower() == "operation-location"), None)
        if location is None:
            # a 202 with no operation to poll can never produce a result;
            # surface it instead of masquerading as an empty success
            return HTTPResponseData(
                status_code=502,
                reason="202 Accepted without Operation-Location header",
                headers=resp.headers, entity=resp.entity)
        key = next(
            (v for k, v in (req.headers or {}).items()
             if k.lower() == "ocp-apim-subscription-key"), None)
        for _ in range(int(self.max_polling_retries)):
            final = self._query_for_result(client, key, location)
            if final is not None:
                return final
            time.sleep(self.polling_delay_ms / 1000.0)
        return HTTPResponseData(
            status_code=504,
            reason=f"async operation did not complete within "
                   f"{self.max_polling_retries} polls")


class BatchedTextServiceBase(CognitiveServicesBase):
    """Text Analytics-style services: up to ``batch_size`` documents ride
    one request (ref: TextAnalyticsBase batched documents payload)."""

    batch_size = Param("documents per request", default=10)
    text = ServiceParam("input text", required=True)
    language = ServiceParam("document language", default="en")

    def _docs_payload(self, texts: Sequence[str],
                      langs: Sequence[Any]) -> Dict[str, Any]:
        return {"documents": [
            {"id": str(i), "language": langs[i] or "en",
             "text": "" if texts[i] is None else str(texts[i])}
            for i in range(len(texts))
        ]}

    def _extract_document(self, doc: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        texts = self._resolve("text", table, n)
        langs = self._resolve("language", table, n)
        keys = self._resolve("subscription_key", table, n)
        bs = max(1, int(self.batch_size))

        # batches break on key changes: every row authenticates with ITS
        # key (a batch can only carry one subscription header)
        reqs = []
        spans = []
        start = 0
        while start < n:
            # contiguous same-key run, capped at bs rows
            stop = start + 1
            while stop < min(start + bs, n) and keys[stop] == keys[start]:
                stop += 1
            reqs.append(self._post(
                self._docs_payload(texts[start:stop], langs[start:stop]),
                keys[start]))
            spans.append((start, stop))
            start = stop

        client = AsyncHTTPClient(
            self.concurrency, HandlingUtils.advanced(*self.backoffs),
            self.timeout)
        resps = client.send_all(reqs)

        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        out[:] = None
        errors[:] = None
        from synapseml_tpu.io.http import response_to_error

        for (start, stop), r in zip(spans, resps):
            err = response_to_error(r)
            if r is None or err is not None:
                for i in range(start, stop):
                    errors[i] = err
                continue
            try:
                body = r.json()
                docs = {d["id"]: d for d in body.get("documents", [])}
                errs = {e["id"]: e for e in body.get("errors", [])}
                for j, i in enumerate(range(start, stop)):
                    doc = docs.get(str(j))
                    if doc is not None:
                        out[i] = self._extract_document(doc)
                    elif str(j) in errs:
                        errors[i] = errs[str(j)]
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                for i in range(start, stop):
                    errors[i] = {"status_code": r.status_code,
                                 "reason": f"parse error: {e}"}
        return table.with_columns({self.output_col: out,
                                   self.error_col: errors})
