"""Form Recognizer services (async long-running analyses).

Rebuild of the reference's FormRecognizer module
(ref: cognitive/src/main/scala/com/microsoft/ml/spark/cognitive/FormRecognizer.scala —
FormRecognizerBase:19-33 (url-or-bytes payload + BasicAsyncReply),
HasPages:37/HasTextDetails:52/HasModelID:64/HasLocale:72 URL-param
traits, AnalyzeLayout:170, AnalyzeReceipts:203, AnalyzeBusinessCards:217,
AnalyzeInvoices:231, AnalyzeIDDocuments:245, ListCustomModels:259,
GetCustomModel:284, AnalyzeCustomModel:326; FormsFlatteners text
extraction :86-110).

Every analyze call POSTs the document (URL as ``{"source": url}`` JSON or
raw bytes as octet-stream), receives 202 + ``Operation-Location`` and is
polled to completion by :class:`HasAsyncReply`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from synapseml_tpu.cognitive.base import (CognitiveServicesBase,
                                          HasAsyncReply, ServiceParam,
                                          with_url_params)
from synapseml_tpu.io.http import HTTPRequestData


def flatten_read_results(analyze_json: Optional[Dict[str, Any]]) -> str:
    """Joined text of all read results (ref: FormsFlatteners
    .flattenReadResults:86-110)."""
    if not analyze_json:
        return ""
    pages = analyze_json.get("analyzeResult", {}).get("readResults", [])
    return " ".join(
        " ".join(ln.get("text", "") for ln in page.get("lines", []))
        for page in pages).strip()


def flatten_document_results(analyze_json: Optional[Dict[str, Any]]
                             ) -> List[Dict[str, Any]]:
    """Per-document field dictionaries (ref: FormsFlatteners
    .flattenDocumentResults analogue)."""
    if not analyze_json:
        return []
    return [
        doc.get("fields", {})
        for doc in analyze_json.get("analyzeResult", {})
                               .get("documentResults", [])
    ]


class FormRecognizerBase(HasAsyncReply, CognitiveServicesBase):
    """(ref: FormRecognizerBase:19-33)."""

    image_url = ServiceParam("document URL")
    image_bytes = ServiceParam("raw document bytes")
    pages = ServiceParam("page selection, e.g. '1-3,5'")

    def _url_params(self, rv) -> Dict[str, Any]:
        out = {}
        if rv.get("pages") is not None:
            out["pages"] = rv["pages"]
        return out

    def _target_url(self, rv) -> Optional[str]:
        return self.url

    def _build_request(self, rv):
        base = self._target_url(rv)
        if base is None:
            return None
        url = with_url_params(base, **self._url_params(rv))
        if rv.get("image_url") is not None:
            return self._post({"source": rv["image_url"]},
                              rv["subscription_key"], url=url)
        if rv.get("image_bytes") is not None:
            return HTTPRequestData(
                url=url, method="POST",
                headers={**self._headers(rv["subscription_key"]),
                         "Content-Type": "application/octet-stream"},
                entity=bytes(rv["image_bytes"]))
        return None

    def _parse_response(self, parsed):
        return parsed


class AnalyzeLayout(FormRecognizerBase):
    """(ref: FormRecognizer.scala AnalyzeLayout:170-201 — language and
    readingOrder URL params)."""

    language = ServiceParam("BCP-47 language code override")
    reading_order = ServiceParam("basic or natural")

    def _url_params(self, rv):
        out = super()._url_params(rv)
        if rv.get("language") is not None:
            out["language"] = rv["language"]
        if rv.get("reading_order") is not None:
            out["readingOrder"] = rv["reading_order"]
        return out


def _bool_param(v: Any) -> Optional[str]:
    """Azure URL params spell booleans lowercase."""
    return None if v is None else ("true" if v else "false")


class _HasTextDetails(FormRecognizerBase):
    """includeTextDetails URL param (ref: HasTextDetails:52)."""

    include_text_details = ServiceParam("include text lines in result")

    def _url_params(self, rv):
        out = super()._url_params(rv)
        td = _bool_param(rv.get("include_text_details"))
        if td is not None:
            out["includeTextDetails"] = td
        return out


class _PrebuiltAnalyzeBase(_HasTextDetails):
    """Receipt/businessCard/invoice/idDocument analyses share
    includeTextDetails and locale (ref: HasTextDetails:52, HasLocale:72)."""

    locale = ServiceParam("document locale, e.g. en-US")

    def _url_params(self, rv):
        out = super()._url_params(rv)
        if rv.get("locale") is not None:
            out["locale"] = rv["locale"]
        return out


class AnalyzeReceipts(_PrebuiltAnalyzeBase):
    """(ref: FormRecognizer.scala AnalyzeReceipts:203)."""


class AnalyzeBusinessCards(_PrebuiltAnalyzeBase):
    """(ref: FormRecognizer.scala AnalyzeBusinessCards:217)."""


class AnalyzeInvoices(_PrebuiltAnalyzeBase):
    """(ref: FormRecognizer.scala AnalyzeInvoices:231)."""


class AnalyzeIDDocuments(_PrebuiltAnalyzeBase):
    """(ref: FormRecognizer.scala AnalyzeIDDocuments:245)."""


class AnalyzeCustomModel(_HasTextDetails):
    """Analysis through a user-trained model; the modelId rides the URL
    path (ref: FormRecognizer.scala AnalyzeCustomModel:326 —
    /custom/models/{modelId}/analyze)."""

    model_id = ServiceParam("custom model id", required=True)

    def _target_url(self, rv):
        if rv.get("model_id") is None:
            return None
        from urllib.parse import quote

        return f"{self.url}/{quote(str(rv['model_id']), safe='')}/analyze"


class ListCustomModels(CognitiveServicesBase):
    """GET the account's custom models (ref: FormRecognizer.scala
    ListCustomModels:259-282 — op URL param: summary or full)."""

    op = ServiceParam("summary or full")

    def _build_request(self, rv):
        url = with_url_params(self.url, op=rv.get("op"))
        return HTTPRequestData(
            url=url, method="GET",
            headers=self._headers(rv["subscription_key"]))

    def _parse_response(self, parsed):
        return parsed


class GetCustomModel(CognitiveServicesBase):
    """GET one custom model's info (ref: FormRecognizer.scala
    GetCustomModel:284-324 — modelId path, includeKeys URL param)."""

    model_id = ServiceParam("custom model id", required=True)
    include_keys = ServiceParam("include extracted keys")

    def _build_request(self, rv):
        if rv.get("model_id") is None:
            return None
        from urllib.parse import quote

        url = with_url_params(
            f"{self.url}/{quote(str(rv['model_id']), safe='')}",
            includeKeys=_bool_param(rv.get("include_keys")))
        return HTTPRequestData(
            url=url, method="GET",
            headers=self._headers(rv["subscription_key"]))
