"""Cognitive service transformers.

Rebuild of the reference's service zoo over the shared base
(ref: cognitive/src/main/scala/com/microsoft/ml/spark/cognitive/ —
TextAnalytics.scala:320 (sentiment/NER/key phrases/language, batched
documents payload), AnomalyDetector.scala:249 (DetectLastAnomaly /
DetectEntireSeries), ComputerVision.scala:573 (analyze/describe/OCR),
Face.scala:351, Translator.scala:406, BingImageSearch.scala:309,
AzureSearch.scala:348 (batched index writer with retry),
SpeechToText.scala:131 (REST recognition)).

Endpoints and payload shapes follow the Azure REST APIs the reference
targets; tests exercise them against a local mock service (this
environment has no egress — the reference hits live services with vault
keys, SURVEY.md §4.4).
"""
from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import Param, _json_default
from synapseml_tpu.cognitive.base import (BatchedTextServiceBase,
                                          CognitiveServicesBase,
                                          ServiceParam)
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import HTTPRequestData


# ---------------------------------------------------------------------------
# Text Analytics family (batched documents payload)
# ---------------------------------------------------------------------------

class TextSentiment(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala TextSentiment)."""

    def _extract_document(self, doc):
        return {"sentiment": doc.get("sentiment"),
                "confidenceScores": doc.get("confidenceScores")}


class NER(BatchedTextServiceBase):
    """Named entity recognition (ref: TextAnalytics.scala NER)."""

    def _extract_document(self, doc):
        return doc.get("entities", [])


class KeyPhraseExtractor(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala KeyPhraseExtractor)."""

    def _extract_document(self, doc):
        return doc.get("keyPhrases", [])


class LanguageDetector(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala LanguageDetector)."""

    def _docs_payload(self, texts, langs):
        # language detection omits the language field
        return {"documents": [
            {"id": str(i), "text": "" if texts[i] is None else str(texts[i])}
            for i in range(len(texts))
        ]}

    def _extract_document(self, doc):
        return doc.get("detectedLanguage", doc.get("detectedLanguages"))


# ---------------------------------------------------------------------------
# Anomaly Detector
# ---------------------------------------------------------------------------

class _AnomalyBase(CognitiveServicesBase):
    series = ServiceParam("list of {timestamp, value} points", required=True)
    granularity = ServiceParam("series granularity", default="daily")
    sensitivity = ServiceParam("anomaly sensitivity")
    max_anomaly_ratio = ServiceParam("max anomaly ratio")

    def _build_request(self, rv):
        if rv["series"] is None:
            return None
        series = [
            {"timestamp": pt[0], "value": float(pt[1])}
            if not isinstance(pt, dict) else pt
            for pt in rv["series"]
        ]
        body: Dict[str, Any] = {"series": series,
                                "granularity": rv["granularity"] or "daily"}
        if rv["sensitivity"] is not None:
            body["sensitivity"] = rv["sensitivity"]
        if rv["max_anomaly_ratio"] is not None:
            body["maxAnomalyRatio"] = rv["max_anomaly_ratio"]
        return self._post(body, rv["subscription_key"])


class DetectLastAnomaly(_AnomalyBase):
    """Is the latest point anomalous? (ref: AnomalyDetector.scala
    DetectLastAnomaly)."""

    def _parse_response(self, parsed):
        return {"isAnomaly": parsed.get("isAnomaly"),
                "expectedValue": parsed.get("expectedValue"),
                "upperMargin": parsed.get("upperMargin"),
                "lowerMargin": parsed.get("lowerMargin")}


class DetectEntireSeries(_AnomalyBase):
    """Batch anomaly detection over the whole series (ref:
    AnomalyDetector.scala DetectAnomalies)."""

    def _parse_response(self, parsed):
        return {"isAnomaly": parsed.get("isAnomaly"),
                "expectedValues": parsed.get("expectedValues"),
                "upperMargins": parsed.get("upperMargins"),
                "lowerMargins": parsed.get("lowerMargins")}


# ---------------------------------------------------------------------------
# Computer Vision / Face (image url-or-bytes value-or-column)
# ---------------------------------------------------------------------------

class _ImageServiceBase(CognitiveServicesBase):
    image_url = ServiceParam("image URL")
    image_bytes = ServiceParam("raw image bytes")

    def _image_request(self, rv, extra_body=None, url=None):
        if rv.get("image_url") is not None:
            body = {"url": rv["image_url"], **(extra_body or {})}
            return self._post(body, rv["subscription_key"], url=url)
        if rv.get("image_bytes") is not None:
            req = HTTPRequestData(
                url=url or self.url, method="POST",
                headers={**self._headers(rv["subscription_key"]),
                         "Content-Type": "application/octet-stream"},
                entity=bytes(rv["image_bytes"]))
            return req
        return None


class AnalyzeImage(_ImageServiceBase):
    """(ref: ComputerVision.scala AnalyzeImage)."""

    visual_features = Param("features to compute",
                            default=("Categories", "Tags", "Description"))

    def _build_request(self, rv):
        req = self._image_request(rv)
        if req is not None and "?" not in (req.url or ""):
            req.url = (f"{req.url}?visualFeatures="
                       f"{','.join(self.visual_features)}")
        return req

    def _parse_response(self, parsed):
        return parsed


class DescribeImage(_ImageServiceBase):
    """(ref: ComputerVision.scala DescribeImage)."""

    def _build_request(self, rv):
        return self._image_request(rv)

    def _parse_response(self, parsed):
        return parsed.get("description", parsed)


class OCR(_ImageServiceBase):
    """(ref: ComputerVision.scala OCR)."""

    def _build_request(self, rv):
        return self._image_request(rv)

    def _parse_response(self, parsed):
        words = [
            w.get("text")
            for region in parsed.get("regions", [])
            for line in region.get("lines", [])
            for w in line.get("words", [])
        ]
        return {"regions": parsed.get("regions", []),
                "text": " ".join(w for w in words if w)}


class DetectFace(_ImageServiceBase):
    """(ref: Face.scala DetectFace)."""

    return_face_attributes = Param("attributes to return", default=())

    def _build_request(self, rv):
        url = self.url
        if self.return_face_attributes:
            url = (f"{url}?returnFaceAttributes="
                   f"{','.join(self.return_face_attributes)}")
        return self._image_request(rv, url=url)


# ---------------------------------------------------------------------------
# Translator
# ---------------------------------------------------------------------------

class Translate(CognitiveServicesBase):
    """(ref: Translator.scala Translate)."""

    text = ServiceParam("text to translate", required=True)
    to_language = ServiceParam("target language(s)", required=True)
    from_language = ServiceParam("source language")

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        to = rv["to_language"]
        to_list = [to] if isinstance(to, str) else list(to)
        url = f"{self.url}?to={','.join(to_list)}"
        if rv["from_language"]:
            url += f"&from={rv['from_language']}"
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"], url=url)

    def _parse_response(self, parsed):
        return parsed[0].get("translations", []) if parsed else []


# ---------------------------------------------------------------------------
# Bing image search
# ---------------------------------------------------------------------------

class BingImageSearch(CognitiveServicesBase):
    """(ref: BingImageSearch.scala:309)."""

    query = ServiceParam("search query", required=True)
    count = ServiceParam("results per query", default=10)

    def _build_request(self, rv):
        if rv["query"] is None:
            return None
        from urllib.parse import quote

        url = (f"{self.url}?q={quote(str(rv['query']))}"
               f"&count={rv['count'] or 10}")
        return HTTPRequestData(url=url, method="GET",
                               headers=self._headers(rv["subscription_key"]))

    def _parse_response(self, parsed):
        return parsed.get("value", [])


# ---------------------------------------------------------------------------
# Speech to text (REST)
# ---------------------------------------------------------------------------

class SpeechToText(CognitiveServicesBase):
    """REST short-audio recognition (ref: SpeechToText.scala:131; the
    streaming native-SDK variant SpeechToTextSDK is out of TPU scope —
    SURVEY.md §2.9 keeps the HTTP path)."""

    audio_bytes = ServiceParam("wav audio bytes", required=True)
    language = ServiceParam("recognition language", default="en-US")
    format = ServiceParam("result format", default="simple")

    def _build_request(self, rv):
        if rv["audio_bytes"] is None:
            return None
        url = (f"{self.url}?language={rv['language'] or 'en-US'}"
               f"&format={rv['format'] or 'simple'}")
        return HTTPRequestData(
            url=url, method="POST",
            headers={**self._headers(rv["subscription_key"]),
                     "Content-Type": "audio/wav; codecs=audio/pcm"},
            entity=bytes(rv["audio_bytes"]))

    def _parse_response(self, parsed):
        return {"DisplayText": parsed.get("DisplayText"),
                "RecognitionStatus": parsed.get("RecognitionStatus")}


# ---------------------------------------------------------------------------
# Azure Search index writer
# ---------------------------------------------------------------------------

class AzureSearchWriter:
    """Batched index writer with retry
    (ref: AzureSearch.scala:348 AddDocuments + batching/retry :199).

    Not a Transformer — a sink, like the reference's writer object.
    """

    def __init__(self, url: str, subscription_key: str,
                 batch_size: int = 100, action: str = "mergeOrUpload",
                 backoffs_ms=(100, 500, 1000, 5000)):
        self.url = url
        self.key = subscription_key
        self.batch_size = batch_size
        self.action = action
        self.backoffs_ms = tuple(backoffs_ms)

    def write(self, table: Table) -> List[int]:
        from synapseml_tpu.io.http import (HandlingUtils,
                                           SingleThreadedHTTPClient)

        client = SingleThreadedHTTPClient(
            HandlingUtils.advanced(*self.backoffs_ms))
        statuses: List[int] = []
        rows = list(table.rows())
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            body = {"value": [
                {"@search.action": self.action, **row} for row in chunk
            ]}
            resp = client.send(HTTPRequestData(
                url=self.url, method="POST",
                headers={"Content-Type": "application/json",
                         "api-key": self.key},
                entity=json.dumps(body, default=_json_default).encode()))
            statuses.append(resp.status_code)
            if not 200 <= resp.status_code < 300:
                raise RuntimeError(
                    f"AzureSearch batch {start // self.batch_size} failed "
                    f"with {resp.status_code}: {resp.text[:500]}")
        return statuses
