"""Cognitive service transformers.

Rebuild of the reference's service zoo over the shared base
(ref: cognitive/src/main/scala/com/microsoft/ml/spark/cognitive/ —
TextAnalytics.scala:320 (sentiment/NER/key phrases/language, batched
documents payload), AnomalyDetector.scala:249 (DetectLastAnomaly /
DetectEntireSeries), ComputerVision.scala:573 (analyze/describe/OCR),
Face.scala:351, Translator.scala:406, BingImageSearch.scala:309,
AzureSearch.scala:348 (batched index writer with retry),
SpeechToText.scala:131 (REST recognition)).

Endpoints and payload shapes follow the Azure REST APIs the reference
targets; tests exercise them against a local mock service (this
environment has no egress — the reference hits live services with vault
keys, SURVEY.md §4.4).
"""
from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import Param, _json_default
from synapseml_tpu.cognitive.base import (BatchedTextServiceBase,
                                          CognitiveServicesBase,
                                          HasAsyncReply, ServiceParam,
                                          with_url_params)
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import HTTPRequestData


# ---------------------------------------------------------------------------
# Text Analytics family (batched documents payload)
# ---------------------------------------------------------------------------

class TextSentiment(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala TextSentiment)."""

    def _extract_document(self, doc):
        return {"sentiment": doc.get("sentiment"),
                "confidenceScores": doc.get("confidenceScores")}


class NER(BatchedTextServiceBase):
    """Named entity recognition (ref: TextAnalytics.scala NER)."""

    def _extract_document(self, doc):
        return doc.get("entities", [])


class KeyPhraseExtractor(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala KeyPhraseExtractor)."""

    def _extract_document(self, doc):
        return doc.get("keyPhrases", [])


class LanguageDetector(BatchedTextServiceBase):
    """(ref: TextAnalytics.scala LanguageDetector)."""

    def _docs_payload(self, texts, langs):
        # language detection omits the language field
        return {"documents": [
            {"id": str(i), "text": "" if texts[i] is None else str(texts[i])}
            for i in range(len(texts))
        ]}

    def _extract_document(self, doc):
        return doc.get("detectedLanguage", doc.get("detectedLanguages"))


# ---------------------------------------------------------------------------
# Anomaly Detector
# ---------------------------------------------------------------------------

class _AnomalyBase(CognitiveServicesBase):
    series = ServiceParam("list of {timestamp, value} points", required=True)
    granularity = ServiceParam("series granularity", default="daily")
    sensitivity = ServiceParam("anomaly sensitivity")
    max_anomaly_ratio = ServiceParam("max anomaly ratio")

    def _build_request(self, rv):
        if rv["series"] is None:
            return None
        series = [
            {"timestamp": pt[0], "value": float(pt[1])}
            if not isinstance(pt, dict) else pt
            for pt in rv["series"]
        ]
        body: Dict[str, Any] = {"series": series,
                                "granularity": rv["granularity"] or "daily"}
        if rv["sensitivity"] is not None:
            body["sensitivity"] = rv["sensitivity"]
        if rv["max_anomaly_ratio"] is not None:
            body["maxAnomalyRatio"] = rv["max_anomaly_ratio"]
        return self._post(body, rv["subscription_key"])


class DetectLastAnomaly(_AnomalyBase):
    """Is the latest point anomalous? (ref: AnomalyDetector.scala
    DetectLastAnomaly)."""

    def _parse_response(self, parsed):
        return {"isAnomaly": parsed.get("isAnomaly"),
                "expectedValue": parsed.get("expectedValue"),
                "upperMargin": parsed.get("upperMargin"),
                "lowerMargin": parsed.get("lowerMargin")}


class DetectEntireSeries(_AnomalyBase):
    """Batch anomaly detection over the whole series (ref:
    AnomalyDetector.scala DetectAnomalies)."""

    def _parse_response(self, parsed):
        return {"isAnomaly": parsed.get("isAnomaly"),
                "expectedValues": parsed.get("expectedValues"),
                "upperMargins": parsed.get("upperMargins"),
                "lowerMargins": parsed.get("lowerMargins")}


# ---------------------------------------------------------------------------
# Computer Vision / Face (image url-or-bytes value-or-column)
# ---------------------------------------------------------------------------

class _ImageServiceBase(CognitiveServicesBase):
    image_url = ServiceParam("image URL")
    image_bytes = ServiceParam("raw image bytes")

    def _image_request(self, rv, extra_body=None, url=None):
        if rv.get("image_url") is not None:
            body = {"url": rv["image_url"], **(extra_body or {})}
            return self._post(body, rv["subscription_key"], url=url)
        if rv.get("image_bytes") is not None:
            req = HTTPRequestData(
                url=url or self.url, method="POST",
                headers={**self._headers(rv["subscription_key"]),
                         "Content-Type": "application/octet-stream"},
                entity=bytes(rv["image_bytes"]))
            return req
        return None


class AnalyzeImage(_ImageServiceBase):
    """(ref: ComputerVision.scala AnalyzeImage)."""

    visual_features = Param("features to compute",
                            default=("Categories", "Tags", "Description"))

    def _build_request(self, rv):
        req = self._image_request(rv)
        if req is not None and "?" not in (req.url or ""):
            req.url = (f"{req.url}?visualFeatures="
                       f"{','.join(self.visual_features)}")
        return req

    def _parse_response(self, parsed):
        return parsed


class DescribeImage(_ImageServiceBase):
    """(ref: ComputerVision.scala DescribeImage)."""

    def _build_request(self, rv):
        return self._image_request(rv)

    def _parse_response(self, parsed):
        return parsed.get("description", parsed)


class OCR(_ImageServiceBase):
    """(ref: ComputerVision.scala OCR)."""

    def _build_request(self, rv):
        return self._image_request(rv)

    def _parse_response(self, parsed):
        words = [
            w.get("text")
            for region in parsed.get("regions", [])
            for line in region.get("lines", [])
            for w in line.get("words", [])
        ]
        return {"regions": parsed.get("regions", []),
                "text": " ".join(w for w in words if w)}


class DetectFace(_ImageServiceBase):
    """(ref: Face.scala DetectFace)."""

    return_face_attributes = Param("attributes to return", default=())

    def _build_request(self, rv):
        url = self.url
        if self.return_face_attributes:
            url = (f"{url}?returnFaceAttributes="
                   f"{','.join(self.return_face_attributes)}")
        return self._image_request(rv, url=url)


class TagImage(_ImageServiceBase):
    """(ref: ComputerVision.scala TagImage:512)."""

    def _build_request(self, rv):
        return self._image_request(rv)

    def _parse_response(self, parsed):
        return parsed.get("tags", [])


class DescribeImageExtended(DescribeImage):
    """DescribeImage with maxCandidates (ref: ComputerVision.scala
    DescribeImage:540 maxCandidates param); kept separate so the plain
    class stays payload-identical with round-1 serde fixtures."""

    max_candidates = Param("caption candidates", default=1)

    def _build_request(self, rv):
        req = self._image_request(rv)
        if req is not None:
            req.url = with_url_params(
                req.url, maxCandidates=int(self.max_candidates))
        return req


class GenerateThumbnails(_ImageServiceBase):
    """Returns raw thumbnail bytes, not JSON
    (ref: ComputerVision.scala GenerateThumbnails:380 — BasicAsyncReply
    not needed; output is the binary entity)."""

    width = Param("thumbnail width", default=64)
    height = Param("thumbnail height", default=64)
    smart_cropping = Param("smart cropping", default=True)

    def _build_request(self, rv):
        url = with_url_params(
            self.url, width=int(self.width), height=int(self.height),
            smartCropping="true" if self.smart_cropping else "false")
        return self._image_request(rv, url=url)

    def _extract_output(self, resp):
        return resp.entity


class RecognizeDomainSpecificContent(_ImageServiceBase):
    """Domain-model analysis; the model rides the URL path
    (ref: ComputerVision.scala RecognizeDomainSpecificContent:487 —
    /models/{model}/analyze)."""

    model = Param("domain model, e.g. celebrities/landmarks",
                  default="celebrities")

    def _build_request(self, rv):
        return self._image_request(rv, url=f"{self.url}/{self.model}/analyze")

    def _parse_response(self, parsed):
        return parsed.get("result", parsed)


class RecognizeText(HasAsyncReply, _ImageServiceBase):
    """Printed/handwritten text via the async recognizeText API
    (ref: ComputerVision.scala RecognizeText:301 — 202 + Operation-Location
    polling, mode query param; flattened text like :200-205)."""

    mode = Param("Printed or Handwritten", default="Printed")

    def _build_request(self, rv):
        return self._image_request(
            rv, url=with_url_params(self.url, mode=self.mode))

    def _parse_response(self, parsed):
        rr = parsed.get("recognitionResult", {})
        lines = rr.get("lines", [])
        return {"lines": lines,
                "text": " ".join(ln.get("text", "") for ln in lines)}


class ReadImage(HasAsyncReply, _ImageServiceBase):
    """The Read API (successor of OCR/recognizeText)
    (ref: ComputerVision.scala ReadImage:347 — async reply, language
    param, analyzeResult.readResults)."""

    language = ServiceParam("read language hint")

    def _build_request(self, rv):
        url = with_url_params(self.url, language=rv.get("language"))
        return self._image_request(rv, url=url)

    def _parse_response(self, parsed):
        results = parsed.get("analyzeResult", {}).get("readResults", [])
        text = " ".join(
            ln.get("text", "") for page in results
            for ln in page.get("lines", []))
        return {"readResults": results, "text": text}


# ---------------------------------------------------------------------------
# Translator
# ---------------------------------------------------------------------------

class Translate(CognitiveServicesBase):
    """(ref: Translator.scala Translate)."""

    text = ServiceParam("text to translate", required=True)
    to_language = ServiceParam("target language(s)", required=True)
    from_language = ServiceParam("source language")

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        to = rv["to_language"]
        to_list = [to] if isinstance(to, str) else list(to)
        url = with_url_params(self.url, to=",".join(to_list),
                              **({"from": rv["from_language"]}
                                 if rv["from_language"] else {}))
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"], url=url)

    def _parse_response(self, parsed):
        return parsed[0].get("translations", []) if parsed else []


class Transliterate(CognitiveServicesBase):
    """Script conversion (ref: TextTranslator.scala Transliterate:283 —
    language/fromScript/toScript query params)."""

    text = ServiceParam("text to transliterate", required=True)
    language = ServiceParam("language of the text", required=True)
    from_script = ServiceParam("source script", required=True)
    to_script = ServiceParam("target script", required=True)

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        url = with_url_params(
            self.url, language=rv["language"],
            fromScript=rv["from_script"], toScript=rv["to_script"])
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"], url=url)

    def _parse_response(self, parsed):
        return parsed[0] if parsed else None


class Detect(CognitiveServicesBase):
    """Language detection via the Translator API
    (ref: TextTranslator.scala Detect:318)."""

    text = ServiceParam("text to detect", required=True)

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"])

    def _parse_response(self, parsed):
        return parsed[0] if parsed else None


class BreakSentence(CognitiveServicesBase):
    """Sentence boundary detection (ref: TextTranslator.scala
    BreakSentence:331)."""

    text = ServiceParam("text to split", required=True)
    language = ServiceParam("language hint")

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        url = with_url_params(self.url, language=rv.get("language"))
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"], url=url)

    def _parse_response(self, parsed):
        return parsed[0] if parsed else None


class _DictionaryBase(CognitiveServicesBase):
    from_language = ServiceParam("source language", required=True)
    to_language = ServiceParam("target language", required=True)

    def _dict_url(self, rv):
        return with_url_params(
            self.url, **{"from": rv["from_language"],
                         "to": rv["to_language"]})

    def _parse_response(self, parsed):
        return parsed[0] if parsed else None


class DictionaryLookup(_DictionaryBase):
    """Alternative translations for a word (ref: TextTranslator.scala
    DictionaryLookup:360)."""

    text = ServiceParam("word to look up", required=True)

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        return self._post([{"text": str(rv["text"])}],
                          rv["subscription_key"], url=self._dict_url(rv))


class DictionaryExamples(_DictionaryBase):
    """Usage examples for a (text, translation) pair
    (ref: TextTranslator.scala DictionaryExamples:389)."""

    text = ServiceParam("source word", required=True)
    translation = ServiceParam("target-language translation", required=True)

    def _build_request(self, rv):
        if rv["text"] is None:
            return None
        return self._post(
            [{"text": str(rv["text"]),
              "translation": str(rv["translation"])}],
            rv["subscription_key"], url=self._dict_url(rv))


class DocumentTranslator(HasAsyncReply, CognitiveServicesBase):
    """Batch blob-to-blob document translation: POST the batches request,
    then poll the operation (ref: DocumentTranslator.scala:28-120 —
    submits to /translator/text/batch/v1.0/batches, 202 +
    Operation-Location, status field polling)."""

    source_url = ServiceParam("source container URL", required=True)
    target_url = ServiceParam("target container URL", required=True)
    target_language = ServiceParam("target language", required=True)

    def _build_request(self, rv):
        if rv["source_url"] is None:
            return None
        body = {"inputs": [{
            "source": {"sourceUrl": rv["source_url"]},
            "targets": [{"targetUrl": rv["target_url"],
                         "language": rv["target_language"]}],
        }]}
        return self._post(body, rv["subscription_key"])


# ---------------------------------------------------------------------------
# Bing image search
# ---------------------------------------------------------------------------

class BingImageSearch(CognitiveServicesBase):
    """(ref: BingImageSearch.scala:309)."""

    query = ServiceParam("search query", required=True)
    count = ServiceParam("results per query", default=10)

    def _build_request(self, rv):
        if rv["query"] is None:
            return None
        from urllib.parse import quote

        url = (f"{self.url}?q={quote(str(rv['query']))}"
               f"&count={rv['count'] or 10}")
        return HTTPRequestData(url=url, method="GET",
                               headers=self._headers(rv["subscription_key"]))

    def _parse_response(self, parsed):
        return parsed.get("value", [])


# ---------------------------------------------------------------------------
# Speech to text (REST)
# ---------------------------------------------------------------------------

class SpeechToText(CognitiveServicesBase):
    """REST short-audio recognition (ref: SpeechToText.scala:131; the
    streaming native-SDK variant SpeechToTextSDK is out of TPU scope —
    SURVEY.md §2.9 keeps the HTTP path)."""

    audio_bytes = ServiceParam("wav audio bytes", required=True)
    language = ServiceParam("recognition language", default="en-US")
    format = ServiceParam("result format", default="simple")

    def _build_request(self, rv):
        if rv["audio_bytes"] is None:
            return None
        url = with_url_params(self.url,
                              language=rv["language"] or "en-US",
                              format=rv["format"] or "simple")
        return HTTPRequestData(
            url=url, method="POST",
            headers={**self._headers(rv["subscription_key"]),
                     "Content-Type": "audio/wav; codecs=audio/pcm"},
            entity=bytes(rv["audio_bytes"]))

    def _parse_response(self, parsed):
        return {"DisplayText": parsed.get("DisplayText"),
                "RecognitionStatus": parsed.get("RecognitionStatus")}


def get_speaker_profile(audio_bytes: bytes, key: str, url: str,
                        backoffs_ms=(100, 500, 1000)) -> str:
    """Voice-signature helper for conversation transcription
    (ref: SpeechAPI.scala getSpeakerProfile:20-48 — multipart POST,
    returns the Signature field; here the wav rides as octet-stream,
    which the signature service also accepts).
    """
    from synapseml_tpu.io.http import (HandlingUtils,
                                       SingleThreadedHTTPClient)

    client = SingleThreadedHTTPClient(HandlingUtils.advanced(*backoffs_ms))
    resp = client.send(HTTPRequestData(
        url=url, method="POST",
        headers={"Ocp-Apim-Subscription-Key": key,
                 "Content-Type": "application/octet-stream"},
        entity=bytes(audio_bytes)))
    if not 200 <= resp.status_code < 300:
        raise RuntimeError(
            f"speaker profile request failed: {resp.status_code} "
            f"{resp.text[:500]}")
    return json.dumps(resp.json().get("Signature"))


# ---------------------------------------------------------------------------
# Azure Search index writer
# ---------------------------------------------------------------------------

class AzureSearchWriter:
    """Batched index writer with retry
    (ref: AzureSearch.scala:348 AddDocuments + batching/retry :199).

    Not a Transformer — a sink, like the reference's writer object.
    """

    def __init__(self, url: str, subscription_key: str,
                 batch_size: int = 100, action: str = "mergeOrUpload",
                 backoffs_ms=(100, 500, 1000, 5000)):
        self.url = url
        self.key = subscription_key
        self.batch_size = batch_size
        self.action = action
        self.backoffs_ms = tuple(backoffs_ms)

    def write(self, table: Table) -> List[int]:
        from synapseml_tpu.io.http import (HandlingUtils,
                                           SingleThreadedHTTPClient)

        client = SingleThreadedHTTPClient(
            HandlingUtils.advanced(*self.backoffs_ms))
        statuses: List[int] = []
        rows = list(table.rows())
        for start in range(0, len(rows), self.batch_size):
            chunk = rows[start:start + self.batch_size]
            body = {"value": [
                {"@search.action": self.action, **row} for row in chunk
            ]}
            resp = client.send(HTTPRequestData(
                url=self.url, method="POST",
                headers={"Content-Type": "application/json",
                         "api-key": self.key},
                entity=json.dumps(body, default=_json_default).encode()))
            statuses.append(resp.status_code)
            if not 200 <= resp.status_code < 300:
                raise RuntimeError(
                    f"AzureSearch batch {start // self.batch_size} failed "
                    f"with {resp.status_code}: {resp.text[:500]}")
        return statuses
