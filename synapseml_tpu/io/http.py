"""HTTP-on-tables: schema, clients, and transformer stages.

Rebuild of the reference's HTTP-on-Spark layer
(ref: core/src/main/scala/com/microsoft/ml/spark/io/http/ —
HTTPSchema.scala (request/response case classes + row codecs),
HTTPClients.scala:12-176 (async + single-threaded clients, retry ladder
``HandlingUtils.advanced``:65-155), HTTPTransformer.scala:22-141,
SimpleHTTPTransformer.scala:20-171, Parsers.scala).

Differences from the reference, by design:
- rows live in the columnar :class:`Table`; request/response objects ride in
  object columns instead of Catalyst structs;
- the async client is a thread pool per transform call (the reference keeps
  a client per partition); responses return in row order regardless of
  completion order, matching the reference's buffered futures;
- everything is stdlib (http.client/urllib) — no external HTTP dependency.
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import http.client
import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from synapseml_tpu.core.param import (ComplexParam, HasInputCol,
                                      HasOutputCol, Param, Params)
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.utils.fault import retry_with_timeout  # noqa: F401 (re-export)


# ---------------------------------------------------------------------------
# schema (HTTPSchema.scala analogue)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HTTPRequestData:
    """One HTTP request as data (ref: HTTPSchema.scala HTTPRequestData)."""
    url: str
    method: str = "POST"
    headers: Dict[str, str] = dataclasses.field(default_factory=dict)
    entity: Optional[bytes] = None

    @staticmethod
    def from_any(v: Any) -> "HTTPRequestData":
        if isinstance(v, HTTPRequestData):
            return v
        if isinstance(v, dict):
            ent = v.get("entity")
            if isinstance(ent, str):
                ent = ent.encode("utf-8")
            return HTTPRequestData(
                url=v["url"], method=v.get("method", "POST"),
                headers=dict(v.get("headers") or {}), entity=ent)
        raise TypeError(f"cannot interpret {type(v)} as HTTPRequestData")


@dataclasses.dataclass
class HTTPResponseData:
    """One HTTP response as data (ref: HTTPSchema.scala HTTPResponseData)."""
    status_code: int
    reason: str = ""
    headers: Dict[str, str] = dataclasses.field(default_factory=dict)
    entity: Optional[bytes] = None

    @property
    def text(self) -> str:
        return (self.entity or b"").decode("utf-8", errors="replace")

    def json(self) -> Any:
        return json.loads(self.text)


def string_to_request(url: str, s: str, method: str = "POST",
                      content_type: str = "application/json") -> HTTPRequestData:
    """``to_http_request`` SQL-function analogue (HTTPSchema.scala)."""
    return HTTPRequestData(url=url, method=method,
                           headers={"Content-Type": content_type},
                           entity=s.encode("utf-8"))


# ---------------------------------------------------------------------------
# clients (HTTPClients.scala analogue)
# ---------------------------------------------------------------------------

class HandlingUtils:
    """Retry ladder (ref: HTTPClients.scala HandlingUtils.advanced:65-155).

    ``advanced(*backoffs_ms)`` returns a handler retrying retryable statuses
    (429/5xx) and IO errors over the given backoff schedule.
    """

    RETRYABLE = frozenset({408, 429, 500, 502, 503, 504})

    @staticmethod
    def basic():
        return HandlingUtils.advanced()

    @staticmethod
    def advanced(*backoffs_ms: int):
        def handle(send_fn: Callable[[], HTTPResponseData]) -> HTTPResponseData:
            last: Optional[HTTPResponseData] = None
            for i in range(len(backoffs_ms) + 1):
                try:
                    last = send_fn()
                except (urllib.error.URLError, ConnectionError, OSError,
                        http.client.HTTPException, ValueError) as e:
                    # ValueError: malformed URLs; HTTPException: garbage
                    # status lines — both must land in the error column,
                    # not crash the batch
                    last = HTTPResponseData(status_code=0, reason=str(e))
                if last.status_code not in HandlingUtils.RETRYABLE \
                        and last.status_code != 0:
                    return last
                if i < len(backoffs_ms):
                    time.sleep(backoffs_ms[i] / 1000.0)
            return last
        return handle


def _send_once(req: HTTPRequestData, timeout: float) -> HTTPResponseData:
    r = urllib.request.Request(
        req.url, data=req.entity, method=req.method,
        headers=dict(req.headers))
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return HTTPResponseData(
                status_code=resp.status, reason=resp.reason or "",
                headers=dict(resp.headers.items()), entity=resp.read())
    except urllib.error.HTTPError as e:
        return HTTPResponseData(
            status_code=e.code, reason=str(e.reason),
            headers=dict(e.headers.items()) if e.headers else {},
            entity=e.read() if e.fp else None)


class SingleThreadedHTTPClient:
    """(ref: HTTPClients.scala SingleThreadedHTTPClient:170)."""

    def __init__(self, handler=None, timeout: float = 60.0):
        self.handler = handler or HandlingUtils.advanced(100, 500, 1000)
        self.timeout = timeout

    def send(self, req: HTTPRequestData) -> HTTPResponseData:
        return self.handler(lambda: _send_once(req, self.timeout))

    def send_all(self, reqs: Sequence[Optional[HTTPRequestData]],
                 post=None) -> List[Optional[HTTPResponseData]]:
        """``post(req, resp) -> resp`` runs per request in the worker —
        long-running-operation polling hooks in here so polls overlap
        under the async client instead of serializing after the sends."""
        if post is None:
            return [None if r is None else self.send(r) for r in reqs]
        return [None if r is None else post(r, self.send(r)) for r in reqs]


class AsyncHTTPClient(SingleThreadedHTTPClient):
    """Buffered-futures client: up to ``concurrency`` requests in flight,
    results returned in request order (ref: HTTPClients.scala
    AsyncHTTPClient:158, concurrency + buffered futures)."""

    def __init__(self, concurrency: int = 8, handler=None,
                 timeout: float = 60.0):
        super().__init__(handler, timeout)
        self.concurrency = max(1, int(concurrency))

    def send_all(self, reqs, post=None):
        def work(r):
            resp = self.send(r)
            return resp if post is None else post(r, resp)

        out: List[Optional[HTTPResponseData]] = [None] * len(reqs)
        with concurrent.futures.ThreadPoolExecutor(self.concurrency) as pool:
            futs = {
                pool.submit(work, r): i
                for i, r in enumerate(reqs) if r is not None
            }
            for fut in concurrent.futures.as_completed(futs):
                out[futs[fut]] = fut.result()
        return out


# ---------------------------------------------------------------------------
# transformer stages
# ---------------------------------------------------------------------------

class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Column of requests -> column of responses
    (ref: io/http/HTTPTransformer.scala:22-141; ``concurrency`` and
    ``timeout`` params mirror HasHandler + client-per-partition)."""

    concurrency = Param("max in-flight requests", default=8)
    timeout = Param("per-request timeout seconds", default=60.0)
    backoffs = Param("retry backoff schedule in ms", default=(100, 500, 1000))

    def _client(self):
        handler = HandlingUtils.advanced(*self.backoffs)
        if self.concurrency > 1:
            return AsyncHTTPClient(self.concurrency, handler, self.timeout)
        return SingleThreadedHTTPClient(handler, self.timeout)

    def _transform(self, table: Table) -> Table:
        reqs = [
            None if v is None else HTTPRequestData.from_any(v)
            for v in table[self.input_col]
        ]
        resps = self._client().send_all(reqs)
        col = np.empty(len(resps), dtype=object)
        col[:] = resps
        return table.with_column(self.output_col, col)


class JSONInputParser(Transformer, HasInputCol, HasOutputCol):
    """Rows -> JSON POST requests (ref: Parsers.scala JSONInputParser)."""

    url = Param("target URL", default=None)
    method = Param("HTTP method", default="POST")
    headers = Param("extra headers", default=None)

    def _transform(self, table: Table) -> Table:
        from synapseml_tpu.core.param import _json_default

        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            body = json.dumps(v, default=_json_default).encode("utf-8")
            headers = {"Content-Type": "application/json",
                       **(self.headers or {})}
            out[i] = HTTPRequestData(url=self.url, method=self.method,
                                     headers=headers, entity=body)
        return table.with_column(self.output_col, out)


class CustomInputParser(Transformer, HasInputCol, HasOutputCol):
    """User function row-value -> HTTPRequestData (ref: Parsers.scala)."""

    udf = ComplexParam("value -> HTTPRequestData function")

    def _transform(self, table: Table) -> Table:
        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            r = self.udf(v)
            out[i] = HTTPRequestData.from_any(r)
        return table.with_column(self.output_col, out)


class StringOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Response -> body string (ref: Parsers.scala StringOutputParser)."""

    def _transform(self, table: Table) -> Table:
        out = np.array(
            ["" if r is None else r.text for r in table[self.input_col]],
            dtype=object)
        return table.with_column(self.output_col, out)


class JSONOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Response -> parsed JSON objects (ref: Parsers.scala JSONOutputParser;
    the reference requires a dataType schema — here objects stay dynamic and
    ``post_process`` optionally maps them)."""

    post_process = ComplexParam("optional parsed-json -> value function", default=None)

    def _transform(self, table: Table) -> Table:
        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        fn = getattr(self, "post_process", None)
        for i, r in enumerate(vals):
            if r is None or not (r.entity or b""):
                out[i] = None
                continue
            try:
                parsed = r.json()
            except (json.JSONDecodeError, UnicodeDecodeError):
                parsed = None
            out[i] = fn(parsed) if (fn is not None and parsed is not None) \
                else parsed
        return table.with_column(self.output_col, out)


class CustomOutputParser(Transformer, HasInputCol, HasOutputCol):
    """User function HTTPResponseData -> value (ref: Parsers.scala)."""

    udf = ComplexParam("HTTPResponseData -> value function")

    def _transform(self, table: Table) -> Table:
        vals = table[self.input_col]
        out = np.empty(len(vals), dtype=object)
        for i, r in enumerate(vals):
            out[i] = self.udf(r)
        return table.with_column(self.output_col, out)


def response_to_error(r: Optional[HTTPResponseData]) -> Optional[Dict[str, Any]]:
    """The shared error-column shape for non-2xx responses
    ({status_code, reason, body}) — used by SimpleHTTPTransformer and the
    cognitive services so error schemas never diverge."""
    if r is None or 200 <= r.status_code < 300:
        return None
    return {"status_code": r.status_code, "reason": r.reason,
            "body": r.text[:2048]}


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """input parse -> HTTP (retrying, concurrent) -> output parse, with an
    error column keeping failed rows flowing
    (ref: io/http/SimpleHTTPTransformer.scala:20-171, ErrorUtils:22-62).

    ``error_col`` receives ``{"status_code", "reason", "body"}`` dicts for
    responses outside 2xx (None on success), and the output column is None
    for those rows — the cognitive-services error pattern.
    """

    url = Param("target URL", default=None)
    input_parser = ComplexParam("Transformer producing request col", default=None)
    output_parser = ComplexParam("Transformer consuming response col", default=None)
    error_col = Param("error column name", default="errors")
    concurrency = Param("max in-flight requests", default=8)
    timeout = Param("per-request timeout seconds", default=60.0)
    backoffs = Param("retry backoff schedule in ms", default=(100, 500, 1000))

    _REQ = "__http_request__"
    _RESP = "__http_response__"

    def _transform(self, table: Table) -> Table:
        # copy user-supplied parsers before re-pointing their columns, so a
        # parser object shared with other pipelines keeps its own config
        inp = self.input_parser
        inp = (JSONInputParser(url=self.url) if inp is None
               else inp.copy())
        inp.set(input_col=self.input_col, output_col=self._REQ)
        outp = self.output_parser
        outp = (JSONOutputParser() if outp is None else outp.copy())
        outp.set(input_col=self._RESP, output_col=self.output_col)

        http = HTTPTransformer(
            input_col=self._REQ, output_col=self._RESP,
            concurrency=self.concurrency, timeout=self.timeout,
            backoffs=self.backoffs)

        t = inp.transform(table)
        t = http.transform(t)

        resps = t[self._RESP]
        errors = np.empty(len(resps), dtype=object)
        ok = np.zeros(len(resps), dtype=bool)
        for i, r in enumerate(resps):
            errors[i] = response_to_error(r)
            ok[i] = r is not None and errors[i] is None
        # blank failed responses so the output parser yields None rows
        cleaned = np.empty(len(resps), dtype=object)
        for i, r in enumerate(resps):
            cleaned[i] = r if ok[i] else None
        t = t.with_column(self._RESP, cleaned)
        t = outp.transform(t)
        return t.drop(self._REQ, self._RESP).with_column(
            self.error_col, errors)
