"""PowerBI streaming-dataset writer.

Rebuild of the reference's PowerBI writer
(ref: core/src/main/scala/com/microsoft/ml/spark/io/powerbi/PowerBIWriter.scala:17-114):
rows are grouped into JSON-array batches and POSTed to the dataset push URL
with the retrying client; batch + "streaming" (table-at-once) modes.
"""
from __future__ import annotations

import json
from typing import List, Optional

from synapseml_tpu.core.param import _json_default
from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import (HandlingUtils, HTTPRequestData,
                                   SingleThreadedHTTPClient)


def write_to_powerbi(table: Table, url: str, batch_size: int = 100,
                     backoffs_ms=(100, 500, 1000, 5000),
                     client: Optional[SingleThreadedHTTPClient] = None
                     ) -> List[int]:
    """POST the table to a PowerBI push URL in row batches; returns the
    status code per batch. Raises on any non-2xx after retries (the
    reference surfaces failures through the stream, :96-114)."""
    client = client or SingleThreadedHTTPClient(
        HandlingUtils.advanced(*backoffs_ms))
    statuses: List[int] = []
    rows = list(table.rows())  # numpy values handled by _json_default
    for start in range(0, len(rows), batch_size):
        body = json.dumps(rows[start:start + batch_size],
                          default=_json_default).encode("utf-8")
        resp = client.send(HTTPRequestData(
            url=url, method="POST",
            headers={"Content-Type": "application/json"}, entity=body))
        statuses.append(resp.status_code)
        if not 200 <= resp.status_code < 300:
            raise RuntimeError(
                f"PowerBI POST failed with {resp.status_code}: "
                f"{resp.text[:500]}")
    return statuses
