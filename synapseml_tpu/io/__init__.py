"""IO layer: HTTP-on-tables, serving, PowerBI, binary/image readers.

TPU-native rebuild of the reference's L5 serving & IO layer (SURVEY.md §2.3):
HTTP schema/clients/transformers (``io.http``), per-shard serving servers
with reply routing + replay (``io.serving``), PowerBI writer
(``io.powerbi``), binary file format (``io.binary``).
"""
from synapseml_tpu.io.http import (  # noqa: F401
    AsyncHTTPClient,
    CustomInputParser,
    CustomOutputParser,
    HandlingUtils,
    HTTPRequestData,
    HTTPResponseData,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
    SingleThreadedHTTPClient,
    StringOutputParser,
    string_to_request,
)
from synapseml_tpu.io.serving import (  # noqa: F401
    ContinuousServer,
    DistributedServer,
    HTTPSourceStateHolder,
    MultiChannelMap,
    WorkerServer,
    make_reply,
    parse_request,
    requests_to_table,
    send_replies,
)
