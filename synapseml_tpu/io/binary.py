"""Binary file format: directory/zip traversal + subsampling reader.

Rebuild of the reference's ``"binary"`` Hadoop data source
(ref: core/src/main/scala/com/microsoft/ml/spark/io/binary/BinaryFileFormat.scala
(251 LoC) — recursive directory listing, zip-archive traversal where each
entry becomes a row named ``archive.zip/entry``, and Bernoulli subsampling
with a seeded RNG; BinaryRecordReader:~35).

Rows: ``path`` (str), ``length`` (int64), ``modification_time`` (float64,
epoch seconds), ``bytes`` (object: bytes).
"""
from __future__ import annotations

import fnmatch
import os
import zipfile
from typing import Iterator, List, Optional, Tuple

import numpy as np

from synapseml_tpu.data.table import Table


def _iter_files(root: str, recursive: bool, pattern: Optional[str]
                ) -> Iterator[str]:
    if os.path.isfile(root):
        yield root
        return
    if recursive:
        for dirpath, _, files in os.walk(root):
            for f in sorted(files):
                if pattern is None or fnmatch.fnmatch(f, pattern):
                    yield os.path.join(dirpath, f)
    else:
        for f in sorted(os.listdir(root)):
            p = os.path.join(root, f)
            if os.path.isfile(p) and (pattern is None
                                      or fnmatch.fnmatch(f, pattern)):
                yield p


def _records(path: str, inspect_zip: bool
             ) -> Iterator[Tuple[str, int, float, bytes]]:
    mtime = os.path.getmtime(path)
    if inspect_zip and zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            for info in zf.infolist():
                if info.is_dir():
                    continue
                data = zf.read(info.filename)
                # "archive.zip/entry" naming, as the reference's zip
                # traversal exposes entries (BinaryFileFormat.scala)
                yield (f"{path}/{info.filename}", len(data), mtime, data)
    else:
        with open(path, "rb") as fh:
            data = fh.read()
        yield (path, len(data), mtime, data)


def read_binary_files(path: str, recursive: bool = True,
                      pattern: Optional[str] = None,
                      sample_ratio: float = 1.0, seed: int = 0,
                      inspect_zip: bool = True) -> Table:
    """Read files (and zip entries) under ``path`` into a Table, keeping
    each record with probability ``sample_ratio`` (seeded Bernoulli, the
    reference's subsampling knob)."""
    rng = np.random.default_rng(seed)
    paths: List[str] = []
    lengths: List[int] = []
    mtimes: List[float] = []
    blobs: List[bytes] = []
    for f in _iter_files(path, recursive, pattern):
        for rec_path, length, mtime, data in _records(f, inspect_zip):
            if sample_ratio < 1.0 and rng.random() >= sample_ratio:
                continue
            paths.append(rec_path)
            lengths.append(length)
            mtimes.append(mtime)
            blobs.append(data)
    byte_col = np.empty(len(blobs), dtype=object)
    byte_col[:] = blobs
    return Table({
        "path": np.array(paths, dtype=object),
        "length": np.array(lengths, dtype=np.int64),
        "modification_time": np.array(mtimes, dtype=np.float64),
        "bytes": byte_col,
    })
