"""Serving: per-shard embedded HTTP servers with reply routing + replay.

Rebuild of Spark Serving v2
(ref: core/src/main/scala/org/apache/spark/sql/execution/streaming/continuous/HTTPSourceV2.scala —
``WorkerServer``:475-696 (per-partition com.sun HttpServer, epoch request
queues, ``routingTable``, ``historyQueues``/``recoveredPartitions`` replay
:488-505), HTTPSinkV2.scala:55-150 (reply writer), ServingUDFs.scala:17-51,
and the v1 ``DistributedHTTPSource``/``JVMSharedServer``).

Architecture here: one :class:`WorkerServer` per shard (stdlib
ThreadingHTTPServer). An arriving request parks its connection on an event,
rides the micro-batch as a row, and the reply routed back through
:class:`HTTPSourceStateHolder` releases the connection — request->score->reply
round trip without any polling, which is what makes the reference's
"sub-millisecond serving" claim reachable. A :class:`ContinuousServer`
drives source -> pipeline -> sink in a loop thread (the serving query).
"""
from __future__ import annotations

import errno
import hashlib
import http.server
import json
import math
import os
import queue
import random
import re
import socket
import threading
import time
import urllib.parse
import uuid
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import HTTPRequestData, HTTPResponseData
from synapseml_tpu.runtime import blackbox as _bb
from synapseml_tpu.runtime import capture as _cap
from synapseml_tpu.runtime import costmodel as _cm
from synapseml_tpu.runtime import faults as _flt
from synapseml_tpu.runtime import perfwatch as _pw
from synapseml_tpu.runtime import slo as _slo
from synapseml_tpu.runtime import structlog as _slog
from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime import tracearchive as _ta
from synapseml_tpu.runtime.faults import PipelineBrokenError
from synapseml_tpu.runtime.locksan import make_lock

_REGISTRY_LOCK = make_lock("serving:_REGISTRY_LOCK")

# client-supplied X-Request-Id acceptance (docs/observability.md): a
# well-formed external id becomes THE rid — span, logs, flight events,
# and the echoed reply header all carry the caller's own correlation
# key. Anything else (missing, oversized, exotic charset) falls back to
# a minted uuid; never reject a request over its id.
_RID_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")

# flight-recorder slow-batch threshold: a pipeline_fn call slower than
# this lands a "slow_batch" event (with its rids) in the ring — the
# breadcrumb a latency incident is diagnosed from. 0 disables.
_SLOW_BATCH_S = float(os.environ.get("SYNAPSEML_SLOW_BATCH_MS",
                                     "1000")) / 1e3

# /debug/profile single-flight gate: jax.profiler supports one trace at
# a time per process, so a second concurrent request gets 409 instead
# of corrupting the first trace. SYNAPSEML_DEBUG_PROFILE=0 disables the
# endpoint entirely (403) for deployments that lock debug surfaces down.
_PROFILE_LOCK = make_lock("serving:_PROFILE_LOCK")
_PROFILE_MAX_MS = 10_000.0

# fault-injection points (runtime/faults.py, docs/robustness.md) —
# resolved once at import; fire() is a single attribute test when no
# fault is armed. Unlike the executor's kill points (which fire with a
# unit in hand, because the supervision registry fails its futures),
# every serving thread_kill fires at the loop top BEFORE the blocking
# get: a dying serving thread must never take a request batch with it —
# there is no failure channel for an in-hand batch except the client's
# reply_timeout.
_F_REPLY = _flt.point("reply")
_F_LAT_SCORE = _flt.point("latency", "score")
# channel-scoped stall: fires inside EVERY channel's scoring path (the
# per-channel compute points are resolved lazily — channel counts are a
# runtime property, see DistributedServer._channel_point)
_F_LAT_STALL = _flt.point("latency", "channel_stall")
_F_KILL_SCORER = _flt.point("thread_kill", "scorer")
_F_KILL_REPLY = _flt.point("thread_kill", "reply")
_F_KILL_COLLECT = _flt.point("thread_kill", "collector")
_F_KILL_DIST = _flt.point("thread_kill", "distributor")


def _retry_rng(injected=None):
    """The PRNG behind transient-retry jitter. Injectable (``retry_rng=``)
    so tests control the draw; ``SYNAPSEML_RETRY_SEED`` seeds a private
    deterministic stream (retry-timing assertions stop depending on
    wall-clock luck); default is the shared module PRNG."""
    if injected is not None:
        return injected
    seed = os.environ.get("SYNAPSEML_RETRY_SEED")
    if seed:
        try:
            return random.Random(int(seed))
        except ValueError:
            pass  # malformed seed: fall through to the shared PRNG
    return random


def _drain_queue(q: "queue.Queue", max_rows: int,
                 timeout: float, linger: float = 0.0,
                 coalesce: float = 0.0) -> List["CachedRequest"]:
    """Deadline-bounded drain: block up to ``timeout`` for the first item,
    then keep collecting for up to ``linger`` seconds more (micro-batch
    coalescing — with concurrent clients a few ms of linger turns N serial
    device round trips into one batched trip; 0 preserves the
    take-what's-there behavior for latency-first pipelines).

    ``coalesce`` is the deadline-based variant: the collection window is
    anchored at the FIRST request's *arrival* time (stamped on enqueue),
    not at the moment the drain observes it — so concurrent low-QPS
    clients whose requests land within the window batch into one device
    round trip, while a request that already waited ``coalesce`` seconds
    (e.g. behind a busy scorer) pays zero additional delay. The two
    windows compose: the drain keeps collecting until the LATER of the
    linger and coalesce deadlines."""
    out: List[CachedRequest] = []
    deadline = time.monotonic() + timeout
    while len(out) < max_rows:
        if not out:
            remaining = deadline - time.monotonic()
        elif linger > 0 or coalesce > 0:
            # expired window clamps to a NON-blocking sweep, not a break:
            # under a backlog (head already older than the window) the
            # drain must still take everything instantly available, like
            # the windowless path — breaking at a singleton would make
            # the coalescing knob degrade batching exactly under load
            remaining = max(0.0, deadline - time.monotonic())
        else:
            remaining = 0.0
        try:
            out.append(q.get(timeout=max(0.0, remaining)))
        except queue.Empty:
            break
        if len(out) == 1:
            deadline = time.monotonic() + linger
            if coalesce > 0:
                arrival = getattr(out[0], "arrival", None)
                if arrival is not None:
                    deadline = max(deadline, arrival + coalesce)
    return out


def _drain_all(q: "queue.Queue") -> List["CachedRequest"]:
    """Non-blocking pop-until-empty — the shed/redisperse paths all
    take EVERYTHING off a queue before acting on it (acting while
    popping can chase concurrent re-puts forever)."""
    out: List[CachedRequest] = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def find_open_port(base: int = 12400, host: str = "127.0.0.1") -> int:
    """Ascending port search (ref: TrainUtils.findOpenPort:193-220).

    Inherently TOCTOU — the port is free when probed, not when the
    caller binds it. :class:`WorkerServer` therefore retries the bind
    itself on the next ports (``port_attempts``) instead of trusting a
    probe; keep this helper for non-HTTP uses (e.g. distributed
    coordinator ports) where the consumer cannot retry."""
    for port in range(base, base + 1000):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind((host, port))
                return port
            except OSError:
                continue
    raise OSError(f"no open port in [{base}, {base + 1000})")


def _supervise_loop(fn: Callable[[], Any], stop: threading.Event,
                    on_restart: Callable[[BaseException], None]):
    """The supervision boundary every serving-stage thread body runs
    under: an exception escaping ``fn`` (injected kill, bug) used to
    kill the thread silently — every subsequent request then parked
    until its reply_timeout. Instead ``on_restart`` records/counts the
    death and the loop RESTARTS ``fn``. Exits only when ``fn`` returns
    cleanly (stop requested) or the death raced ``stop``."""
    while True:
        try:
            fn()
            return
        except BaseException as e:  # noqa: BLE001 - supervision boundary
            if stop.is_set():
                return
            on_restart(e)
            # tiny pause: a persistent crash (e.g. prob-1.0 injected
            # kill) degrades to a slow restart loop, not a hot spin
            time.sleep(0.01)


def _debug_profile(path: str) -> Tuple[int, Dict[str, Any]]:
    """``GET /debug/profile?ms=<n>``: record a bounded on-demand
    ``jax.profiler`` trace (via :func:`utils.profiling.trace`, so the
    executor's live ``TraceAnnotation`` bridge lights up for exactly
    this window) into the flight-recorder dump dir. Gated
    (``SYNAPSEML_DEBUG_PROFILE=0`` → 403) and single-flight (the jax
    profiler supports one trace per process — a concurrent request
    gets 409, never a corrupted trace). The handler thread blocks for
    the window; scoring continues on the pipeline threads."""
    if os.environ.get("SYNAPSEML_DEBUG_PROFILE", "") == "0":
        return 403, {"error":
                     "profiling disabled (SYNAPSEML_DEBUG_PROFILE=0)"}
    params = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
    try:
        ms = float(params.get("ms", ["500"])[0])
    except ValueError:
        return 400, {"error": "ms must be a number"}
    ms = max(1.0, min(_PROFILE_MAX_MS, ms))
    if not _PROFILE_LOCK.acquire(blocking=False):
        return 409, {"error": "a profile is already in flight"}
    try:
        from synapseml_tpu.utils import profiling

        # uuid suffix: two short profiles inside one wall-clock second
        # (the single-flight lock only serializes, it doesn't space
        # them out) must not interleave traces in one directory
        stamp = (time.strftime("%Y%m%dT%H%M%S", time.gmtime())
                 + "-" + uuid.uuid4().hex[:8])
        out_dir = os.path.join(_bb.dump_dir(), f"profile-{stamp}")
        t0 = time.monotonic()
        with profiling.trace(out_dir):
            # trace() degrades to a no-op where the profiler is
            # unsupported; report whether anything actually recorded
            recorded = profiling.trace_active()
            time.sleep(ms / 1e3)
        wall = time.monotonic() - t0
        _bb.record("debug_profile", ms=ms, recorded=recorded,
                   trace_dir=out_dir)
        return 200, {"trace_dir": out_dir, "ms": ms,
                     "recorded": recorded,
                     "seconds": round(wall, 6)}
    finally:
        _PROFILE_LOCK.release()


_BUILD_STATIC: Optional[Dict[str, Any]] = None
_BUILD_LOCK = make_lock("serving:_BUILD_LOCK")


def _build_static() -> Dict[str, Any]:
    """The immutable half of the /debug/build payload, resolved once:
    git sha (``SYNAPSEML_GIT_SHA`` — the image build arg — else a
    best-effort ``git rev-parse`` over the source tree), python and
    jax/jaxlib versions via importlib.metadata (NO jax import: a
    jax-free front-end answering /debug/build must stay jax-free)."""
    global _BUILD_STATIC
    if _BUILD_STATIC is not None:
        return _BUILD_STATIC
    # Resolve OUTSIDE the lock: the git subprocess can park the thread
    # for up to its 5s timeout (a DS003 blocking-call finding when held
    # under _BUILD_LOCK), and the payload is deterministic per process,
    # so racing resolvers compute identical values — only publication
    # needs the lock.
    import platform
    import subprocess

    sha = os.environ.get("SYNAPSEML_GIT_SHA", "").strip()
    if not sha:
        try:
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            sha = subprocess.run(
                ["git", "rev-parse", "HEAD"], cwd=root, timeout=5,
                capture_output=True, text=True).stdout.strip()
        except Exception:  # noqa: BLE001 - no git in the image
            sha = ""

    def _ver(dist: str) -> Optional[str]:
        try:
            from importlib import metadata

            return metadata.version(dist)
        except Exception:  # noqa: BLE001 - dist absent
            return None

    built = {
        "git_sha": sha or None,
        "python": platform.python_version(),
        "jax": _ver("jax"),
        "jaxlib": _ver("jaxlib"),
        "pid": os.getpid(),
    }
    with _BUILD_LOCK:
        if _BUILD_STATIC is None:
            _BUILD_STATIC = built
        return _BUILD_STATIC


def _build_info(server: "WorkerServer") -> Dict[str, Any]:
    """``GET /debug/build``: version-skew + lifecycle diagnosis for one
    replica — what a fleet operator diffs across pods when a shared
    cache starts reporting ``cache_skew``. Backend/device fields are
    read ONLY when a jax backend already exists (the endpoint itself
    never initializes one)."""
    info = dict(_build_static())
    backend = device_kind = None
    if _pw._jax_initialized():
        try:
            import jax

            backend = jax.default_backend()
            devs = jax.local_devices()
            device_kind = devs[0].device_kind if devs else None
        except Exception:  # noqa: BLE001 - introspection is best-effort
            pass
    info.update({
        "backend": backend or "uninitialized",
        "device_kind": device_kind,
        "server": server.name,
        "ready": server.ready,
        "draining": server.draining,
    })
    return info


class _PendingReply:
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Optional[HTTPResponseData] = None


class CachedRequest:
    """(ref: HTTPSourceV2.scala CachedRequest). ``arrival`` (monotonic
    enqueue time) anchors the deadline-based coalescing window and the
    span's ``queue_wait`` stage; ``span`` is the request's telemetry
    trace (a shared no-op when telemetry is disabled), ``drained`` the
    moment a drain took it off the queue (stamped in
    ``_record_epoch``).
    ``deadline`` is the absolute monotonic instant the client stops
    caring (``X-Deadline-Ms`` header or the server default; None = no
    deadline) — a request already past it at batch-form time is shed
    504 before any scoring work is wasted.
    ``trace_id``/``parent_span_id``/``span_id`` thread the request's
    W3C trace context (accepted from ``traceparent`` or minted at
    enqueue) into its span, so this server's leg stitches into the
    caller's distributed trace; ``origin`` names the server on the
    span for multi-leg disambiguation."""
    __slots__ = ("rid", "request", "epoch", "replied", "arrival", "span",
                 "drained", "deadline")

    def __init__(self, rid: str, request: HTTPRequestData,
                 deadline_ms: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 origin: str = ""):
        self.rid = rid
        self.request = request
        self.epoch: Optional[int] = None
        self.replied = False
        self.arrival = time.monotonic()
        self.span = _tm.start_span(rid, trace_id=trace_id,
                                   parent_span_id=parent_span_id,
                                   span_id=span_id, origin=origin)
        self.drained = 0.0
        self.deadline = (None if not deadline_ms
                         else self.arrival + deadline_ms / 1e3)


class WorkerServer:
    """One shard's embedded HTTP server
    (ref: HTTPSourceV2.scala WorkerServer:475-696).

    Requests park their connection until :meth:`reply_to` releases them;
    dequeued-but-uncommitted requests are kept in per-epoch history so a
    restarted shard can replay them (``historyQueues`` ->
    ``recoveredPartitions``, :488-505,608-613).
    """

    def __init__(self, name: str, host: str = "127.0.0.1",
                 port: Optional[int] = None, api_path: str = "/",
                 reply_timeout: float = 60.0, ready: bool = True,
                 default_deadline_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 port_attempts: int = 32,
                 retry_after_s: float = 1.0):
        """``default_deadline_ms``: per-request deadline applied when the
        client sends no ``X-Deadline-Ms`` header (None/0 = none).
        ``max_queue``: admission control — a request arriving while that
        many are already queued is shed 429 at enqueue instead of
        parking a connection it will likely time out on (None =
        unbounded). ``port_attempts``: how many successive ports to try
        when an explicit ``port`` is already bound — the bind itself
        retries, closing the probe-then-bind TOCTOU race two
        concurrently constructed servers used to crash on (read the
        actual port back from ``self.port``). ``retry_after_s``: the
        ``Retry-After`` hint every shed path (429/503/504) carries so
        load balancers and clients back off on a schedule instead of
        immediately re-hammering a saturated or draining replica."""
        self.name = name
        self.host = host
        self.default_deadline_ms = default_deadline_ms  # synlint: shared
        self.max_queue = max_queue  # synlint: shared
        self.retry_after_s = retry_after_s
        # decode mode (runtime/decode.py): when a DecodeScheduler is
        # attached here, POST /generate admits autoregressive sequences
        # instead of riding the scoring queue — see Handler._generate
        self.decode = None  # synlint: shared
        # readiness gate: /health answers 503 until set_ready(True) —
        # a k8s replica that is still AOT-warming its compile cache must
        # not receive traffic (the serving entry's --warmup flow)
        self._ready = threading.Event()
        if ready:
            self._ready.set()
        # graceful-drain gate: while draining, /health/ready answers 503
        # (the load balancer routes away) and NEW enqueues are refused
        # 503 + Retry-After — already-accepted requests keep scoring to
        # a real reply (the SIGTERM rolling-restart contract)
        self._draining = threading.Event()
        # port=0 lets the OS assign one race-free; the actual port is read
        # back from server_address after bind
        self.port = 0 if port is None else port
        self.api_path = api_path
        self.reply_timeout = reply_timeout
        self.requests: "queue.Queue[CachedRequest]" = queue.Queue()
        self.routing: Dict[str, _PendingReply] = {}
        self.history: Dict[int, List[CachedRequest]] = {}
        self.current_epoch = 0
        self._lock = make_lock("WorkerServer._lock")
        # telemetry handles, resolved once per server (docs/
        # observability.md catalogs the series); the queue-depth gauge
        # samples qsize() at scrape time — nothing on the request path
        self._m_requests = _tm.counter("serving_requests_total",
                                       server=name)
        self._m_batch_size = _tm.histogram(
            "serving_batch_size", buckets=_tm.SIZE_BUCKETS, server=name)
        self._m_queue_wait = _tm.histogram("serving_queue_wait_seconds",
                                           server=name)
        self._m_coalesce = _tm.histogram(
            "serving_coalesce_delay_seconds", server=name)
        self._m_roundtrip = _tm.histogram("serving_request_seconds",
                                          server=name)
        self._m_reply_timeout = _tm.counter("serving_reply_timeout_total",
                                            server=name)
        self._m_queue_shed = _tm.counter("serving_queue_shed_total",
                                         server=name)
        self._m_drain_shed = _tm.counter("serving_drain_shed_total",
                                         server=name)
        self._m_drain_s = _tm.histogram("serving_drain_seconds",
                                        server=name)
        self._m_replies: Dict[int, _tm.Counter] = {}
        _tm.gauge_fn("serving_queue_depth", self.requests.qsize,
                     server=name)
        # performance observatory (runtime/perfwatch.py): per-device
        # memory gauges registered once per process. lazy=True — a
        # jax-free front-end (pure-numpy pipeline, router beside a
        # separate scorer holding exclusive libtpu access) must not
        # force-initialize the backend by merely binding a port; any
        # scoring replica registers via its executor's construction
        _pw.ensure_registered(lazy=True)
        # SLO accounting (runtime/slo.py; methodology in docs/
        # observability.md "SLO accounting"): scrape-time views over
        # the reply counters and roundtrip histogram this server
        # already feeds — nothing new on the request path. Targets are
        # env-configured once per server (the chart wires them); the
        # attributes stay writable for tests/embedding callers.
        self.slo_availability_target = float(os.environ.get(
            "SYNAPSEML_SLO_AVAILABILITY",
            str(_slo.DEFAULT_AVAILABILITY_TARGET)))  # synlint: shared
        self.slo_latency_target = float(os.environ.get(
            "SYNAPSEML_SLO_LATENCY_TARGET", "0.99"))  # synlint: shared
        self.slo_latency_threshold_s = float(os.environ.get(
            "SYNAPSEML_SLO_LATENCY_MS",
            str(_slo.DEFAULT_LATENCY_MS))) / 1e3  # synlint: shared
        _tm.gauge_fn("serving_slo_availability",
                     self._slo_availability, server=name)
        _tm.gauge_fn(
            "serving_slo_availability_burn_rate",
            lambda: _slo.burn_rate(self._slo_availability(),
                                   self.slo_availability_target),
            server=name)
        _tm.gauge_fn("serving_slo_latency_good_fraction",
                     self._slo_latency_good, server=name)
        _tm.gauge_fn(
            "serving_slo_latency_burn_rate",
            lambda: _slo.burn_rate(self._slo_latency_good(),
                                   self.slo_latency_target),
            server=name)
        _tm.gauge_fn("serving_slo_latency_threshold_ms",
                     lambda: self.slo_latency_threshold_s * 1e3,
                     server=name)
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # without these, headers and body leave as separate unbuffered
            # TCP segments and Nagle + delayed-ACK stalls every keep-alive
            # request ~40ms; buffered writes + TCP_NODELAY keep the reply
            # to one immediate segment (sub-millisecond round trips)
            disable_nagle_algorithm = True
            wbufsize = 64 * 1024

            def log_message(self, *a):  # quiet
                pass

            def _enqueue(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                req = HTTPRequestData(
                    url=self.path, method=self.command,
                    headers=dict(self.headers.items()), entity=body)
                # client-supplied X-Request-Id becomes THE rid when
                # well-formed (validated + length-capped), so the
                # caller's own logs correlate with ours; otherwise mint.
                # Echoed on EVERY reply path — sheds included — below.
                client_rid = (self.headers.get("X-Request-Id")
                              or "").strip()
                rid = (client_rid if _RID_RE.match(client_rid)
                       else uuid.uuid4().hex)
                # W3C trace context: a well-formed traceparent is
                # ADOPTED (this server's span becomes one leg of the
                # caller's trace); anything else mints a fresh trace.
                # One regex fullmatch + two uuid4 draws — no lock, and
                # the echoed header names OUR span as the parent so
                # every reply path (sheds included) continues the
                # trace (docs/observability.md "Distributed tracing")
                parsed_tp = _tm.parse_traceparent(
                    self.headers.get("traceparent"))
                if parsed_tp is not None:
                    trace_id, parent_span_id = parsed_tp
                else:
                    trace_id, parent_span_id = _tm.mint_trace_id(), None
                span_id = _tm.mint_span_id()
                tp_echo = _tm.format_traceparent(trace_id, span_id)
                outer._m_requests.inc()
                if _slog.enabled("debug"):
                    _slog.log("debug", "request", rid=rid,
                              trace=trace_id,
                              server=outer.name, method=self.command,
                              path=self.path, bytes=length)
                retry_hdr = (("Retry-After", outer._retry_after_value()),
                             ("X-Request-Id", rid),
                             ("traceparent", tp_echo))
                if outer._draining.is_set():
                    # graceful drain: the replica is going away — refuse
                    # NEW work with an explicit 503 + Retry-After (the
                    # LB's cue to route elsewhere) while accepted
                    # requests keep scoring to a real reply
                    outer._m_drain_shed.inc()
                    outer._reply_counter(503).inc()
                    _bb.record("shed_drain", rid=rid, level="warn",
                               trace=trace_id, server=outer.name)
                    self._send_plain(503, b"draining", headers=retry_hdr)
                    # incident capture: an enqueue-path shed never
                    # reaches the reply handler's retention hook below,
                    # so the breach is captured here — after the socket
                    # write, like every capture
                    _cap.maybe_capture(
                        req, 503, 0.0, rid=rid, trace_id=trace_id,
                        origin=outer.name,
                        threshold_s=outer.slo_latency_threshold_s)
                    return
                if (outer.decode is not None
                        and self.path.split("?", 1)[0].rstrip("/")
                        == "/generate"):
                    # decode mode: sequences go to the continuous-
                    # batching scheduler, not the scoring queue — its
                    # admission control (max_waiting) replaces the
                    # queue-depth shed below
                    self._generate(req, rid, trace_id, tp_echo,
                                   span_id, retry_hdr)
                    return
                if (outer.max_queue is not None
                        and outer.requests.qsize() >= outer.max_queue):
                    # admission control: shed at enqueue with 429 — a
                    # request this far over capacity would only park a
                    # connection it will likely 504 on anyway.
                    # Retry-After makes the client's backoff principled
                    # instead of an immediate re-hammer
                    outer._m_queue_shed.inc()
                    outer._reply_counter(429).inc()
                    _bb.record("shed_queue", rid=rid, level="warn",
                               trace=trace_id, server=outer.name,
                               depth=outer.requests.qsize())
                    self._send_plain(429, b"request queue full",
                                     headers=retry_hdr)
                    _cap.maybe_capture(
                        req, 429, 0.0, rid=rid, trace_id=trace_id,
                        origin=outer.name,
                        threshold_s=outer.slo_latency_threshold_s)
                    return
                deadline_ms = outer.default_deadline_ms
                hdr = self.headers.get("X-Deadline-Ms")
                if hdr:
                    try:
                        deadline_ms = float(hdr)
                    except ValueError:
                        pass  # malformed header: keep the server default
                pending = _PendingReply()
                with outer._lock:
                    collided = rid in outer.routing
                    if collided:
                        # a client reusing an id while its first request
                        # is still in flight must not hijack that
                        # request's reply slot: the second gets a minted
                        # id (still echoed back, so the caller sees the
                        # substitution)
                        requested_rid, rid = rid, uuid.uuid4().hex
                    outer.routing[rid] = pending
                if collided and _slog.enabled("debug"):
                    # keep the grep-by-rid trail intact both ways: the
                    # "request" line above carries the requested id,
                    # this one links it to the id the reply will carry
                    _slog.log("debug", "rid_substituted", rid=rid,
                              server=outer.name,
                              requested=requested_rid)
                cr = CachedRequest(rid, req, deadline_ms,
                                   trace_id=trace_id,
                                   parent_span_id=parent_span_id,
                                   span_id=span_id, origin=outer.name)
                outer.requests.put(cr)
                pending.event.wait(outer.reply_timeout)
                with outer._lock:
                    # claim-or-expire under the lock: if reply_to committed
                    # first, response is set (deliver it even at the timeout
                    # boundary); otherwise popping rid guarantees a late
                    # reply_to returns False and the request stays replayable
                    outer.routing.pop(rid, None)
                    resp = pending.response
                status = resp.status_code if resp is not None else 504
                outer._reply_counter(status).inc()
                dt = time.monotonic() - cr.arrival
                # output digest: sha256 over the exact reply bytes,
                # echoed as X-Output-Digest and stamped on the span —
                # the determinism fingerprint clients, loadgen, and
                # tools/replay.py verify without storing the output.
                # Computed once per reply (~2.6us at 32B, ~6us at 4KiB
                # on the CI box), before the headers leave.
                body = (resp.entity or b"") if resp is not None else b""
                if resp is not None:
                    digest = hashlib.sha256(body).hexdigest()
                    if cr.span.span_id:
                        # raw attribute write, so it must skip the
                        # shared _NOOP_SPAN (span_id "") telemetry
                        # hands out when disabled — stamping that
                        # singleton would smear one request's digest
                        # across every concurrent handler
                        cr.span.output_digest = digest
                else:
                    # a reply-timeout 504 sends no body and no digest
                    # header: stamping sha256(b"") would hand forensics
                    # a concrete-looking fingerprint for a reply that
                    # carried none
                    digest = ""
                # exemplar: this trace becomes the covering latency
                # bucket's link-out (last-write-wins slot assignment —
                # still no lock on the request path)
                outer._m_roundtrip.observe(dt, exemplar=trace_id)
                if _slog.enabled("debug"):
                    _slog.log("debug", "reply", rid=rid,
                              trace=trace_id,
                              server=outer.name, status=status,
                              seconds=round(dt, 6))
                try:
                    if resp is None:
                        # the wait expired with no response set: an
                        # explicit 504, never a silent empty wait-out
                        outer._m_reply_timeout.inc()
                        self.send_response(504)
                        # the id still goes back: a timed-out client
                        # can ask /span/<rid> where its request got
                        # stuck
                        self.send_header("X-Request-Id", rid)
                        self.send_header("traceparent", tp_echo)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                    else:
                        self.send_response(resp.status_code)
                        for k, v in resp.headers.items():
                            if k.lower() not in ("content-length",
                                                 "date", "server"):
                                self.send_header(k, v)
                        # rid correlates the reply with its trace span
                        # (the telemetry e2e test asserts this header
                        # matches the span record); traceparent hands
                        # the caller its continued trace context back;
                        # X-Output-Digest lets the caller assert
                        # determinism against a replay without either
                        # side storing the body
                        self.send_header("X-Request-Id", rid)
                        self.send_header("traceparent", tp_echo)
                        self.send_header("X-Output-Digest", digest)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                finally:
                    # the reply bytes must be ON the socket before any
                    # retention work: the wfile buffer normally flushes
                    # at handler return, which would put the archive +
                    # capture file writes below BETWEEN the client's
                    # reply and its flush — a process exiting mid-drain
                    # kills daemon handler threads parked there,
                    # turning committed replies into connection resets
                    try:
                        self.wfile.flush()
                    except OSError:
                        pass  # client hung up: still breach evidence
                    # tail-based retention: the outcome is known here —
                    # breaches (5xx / shed / over-threshold latency)
                    # and the head-sampled healthy few land one JSONL
                    # record in the archive. Deliberately AFTER the
                    # socket write (a slow dump volume must delay
                    # forensics, never the client's reply during an
                    # incident when every reply breaches) — but in a
                    # finally: a client that hung up mid-write is
                    # breach evidence, not a reason to lose the record
                    _ta.maybe_archive(
                        cr.span, status, dt,
                        threshold_s=outer.slo_latency_threshold_s)
                    # incident capture (runtime/capture.py): same
                    # tail-based decision, but keeping the request
                    # BYTES — the replay harness's input. Also after
                    # the socket write: a slow capture volume delays
                    # forensics, never the reply
                    _cap.maybe_capture(
                        cr.request, status, dt, rid=rid,
                        trace_id=trace_id, span_id=cr.span.span_id,
                        origin=outer.name, digest=digest,
                        reply_entity=(resp.entity or b""
                                      if resp is not None else None),
                        threshold_s=outer.slo_latency_threshold_s)

            def _generate(self, req, rid, trace_id, tp_echo, span_id,
                          retry_hdr):
                """POST /generate — decode-mode sequence admission.

                Body: ``{"tokens": [...], "max_new_tokens": N,
                "stream": bool}``. Non-streamed replies are one JSON
                body (``{"prompt_len", "tokens", "finish_reason"}``)
                through the standard digest/capture contract —
                X-Output-Digest is sha256 over the exact reply bytes,
                so ``tools/replay.py --serve`` verifies decode
                determinism unchanged. Streamed replies are chunked
                NDJSON: rid + traceparent ride the response headers
                (sent before the first token), one ``{"i", "t"}`` line
                per token as it decodes, and the final line carries
                ``finish_reason`` plus ``digest`` — sha256 of the
                CANONICAL (non-streamed) reply body for the same
                result, so a streamed client can assert the same
                fingerprint a replay recomputes."""
                t0 = time.monotonic()
                try:
                    payload = json.loads(req.entity or b"{}")
                    tokens = [int(t) for t in payload["tokens"]]
                    max_new = int(payload.get("max_new_tokens", 16))
                    stream = bool(payload.get("stream", False))
                except (ValueError, KeyError, TypeError) as e:
                    outer._reply_counter(400).inc()
                    self._send_plain(
                        400, f"bad decode request: {e!r}".encode(),
                        headers=retry_hdr[1:])
                    return
                deadline_ms = outer.default_deadline_ms
                hdr = self.headers.get("X-Deadline-Ms")
                if hdr:
                    try:
                        deadline_ms = float(hdr)
                    except ValueError:
                        pass
                try:
                    handle = outer.decode.submit(
                        tokens, max_new,
                        deadline_s=(deadline_ms / 1e3
                                    if deadline_ms else None))
                except ValueError as e:
                    outer._reply_counter(400).inc()
                    self._send_plain(400, repr(e).encode(),
                                     headers=retry_hdr[1:])
                    return
                except RuntimeError:
                    # admission queue full (or scheduler stopping):
                    # same shed contract as the scoring queue
                    outer._m_queue_shed.inc()
                    outer._reply_counter(429).inc()
                    _bb.record("shed_queue", rid=rid, level="warn",
                               trace=trace_id, server=outer.name,
                               path="/generate")
                    self._send_plain(429, b"decode queue full",
                                     headers=retry_hdr)
                    _cap.maybe_capture(
                        req, 429, 0.0, rid=rid, trace_id=trace_id,
                        origin=outer.name,
                        threshold_s=outer.slo_latency_threshold_s)
                    return

                def canonical_body(toks, reason):
                    return json.dumps(
                        {"prompt_len": len(tokens), "tokens": toks,
                         "finish_reason": reason}).encode()

                if not stream:
                    try:
                        toks, reason = handle.result(
                            timeout=outer.reply_timeout)
                    except TimeoutError:
                        outer._m_reply_timeout.inc()
                        outer._reply_counter(504).inc()
                        self._send_plain(504, b"", headers=retry_hdr)
                        return
                    except Exception as e:  # noqa: BLE001 - loop fault
                        outer._reply_counter(500).inc()
                        self._send_plain(500, repr(e).encode(),
                                         headers=retry_hdr[1:])
                        return
                    body = canonical_body(toks, reason)
                    digest = hashlib.sha256(body).hexdigest()
                    status = 200
                    outer._reply_counter(status).inc()
                    dt = time.monotonic() - t0
                    outer._m_roundtrip.observe(dt, exemplar=trace_id)
                    self._send_plain(
                        status, body, content_type="application/json",
                        headers=(("X-Request-Id", rid),
                                 ("traceparent", tp_echo),
                                 ("X-Output-Digest", digest)))
                    _cap.maybe_capture(
                        req, status, dt, rid=rid, trace_id=trace_id,
                        span_id=span_id, origin=outer.name,
                        digest=digest, reply_entity=body,
                        threshold_s=outer.slo_latency_threshold_s)
                    return
                # streamed: headers (rid + traceparent) leave before
                # the first token; tokens flush per decode step so the
                # client's inter-token latency measures the scheduler,
                # not this buffer
                self.send_response(200)
                self.send_header("X-Request-Id", rid)
                self.send_header("traceparent", tp_echo)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(b: bytes):
                    self.wfile.write(f"{len(b):x}\r\n".encode()
                                     + b + b"\r\n")

                toks = []
                status = 200
                try:
                    for tok in handle:
                        line = json.dumps(
                            {"i": len(toks), "t": tok}).encode()
                        toks.append(tok)
                        chunk(line + b"\n")
                        self.wfile.flush()
                    reason = handle.finish_reason or "completed"
                    body = canonical_body(toks, reason)
                    digest = hashlib.sha256(body).hexdigest()
                    final = json.dumps(
                        {"done": True, "n": len(toks),
                         "finish_reason": reason,
                         "digest": digest}).encode()
                    chunk(final + b"\n")
                    chunk(b"")  # 0\r\n\r\n terminator
                    self.wfile.flush()
                except OSError:
                    # client hung up mid-stream: release the sequence's
                    # KV budget; the scheduler-side finish already
                    # happened or will via deadline
                    digest = ""
                    status = 499
                except Exception as e:  # noqa: BLE001 - loop fault
                    # headers are gone — terminate the chunk stream
                    # with an error line instead of a silent cut
                    digest = ""
                    status = 500
                    try:
                        chunk(json.dumps(
                            {"done": True, "error": repr(e)}).encode()
                            + b"\n")
                        chunk(b"")
                        self.wfile.flush()
                    except OSError:
                        pass
                outer._reply_counter(status).inc()
                dt = time.monotonic() - t0
                outer._m_roundtrip.observe(dt, exemplar=trace_id)
                _cap.maybe_capture(
                    req, status, dt, rid=rid, trace_id=trace_id,
                    span_id=span_id, origin=outer.name, digest=digest,
                    threshold_s=outer.slo_latency_threshold_s)

            def _send_plain(self, status: int, body: bytes,
                            content_type: str = "text/plain",
                            headers: Tuple[Tuple[str, str], ...] = ()):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                # flush NOW, not at handler return: the wfile is a
                # 64KB buffer, and the shed paths do post-reply work
                # (incident capture — a file write) after sending. A
                # draining process exits by killing daemon handler
                # threads; one parked in that work with its 503 still
                # buffered turns a clean shed into a client-visible
                # connection reset (found by the chaos sigterm phase:
                # zero drain 503s observed once capture landed there).
                # OSError-tolerant: a client that already hung up must
                # not skip the capture that follows at the call site
                try:
                    self.wfile.flush()
                except OSError:
                    pass

            def do_GET(self):
                if self.path == "/health/live":
                    # liveness: the PROCESS is up and its accept loop
                    # answers — true throughout warmup AND drain, so k8s
                    # never kills a replica that is merely warming or
                    # gracefully draining (that is readiness's job)
                    self._send_plain(200, b"alive")
                    return
                if self.path in ("/health", "/health/ready"):
                    # readiness fast-path: never rides the pipeline.
                    # 503 while warming keeps the load balancer away
                    # from a replica that would park requests on a
                    # compiling (or not-yet-started) scoring query; 503
                    # while DRAINING routes rollouts away before the
                    # replica exits. /health stays an alias for ready —
                    # existing probes keep their semantics.
                    if outer._draining.is_set():
                        self._send_plain(
                            503, b"draining",
                            headers=(("Retry-After",
                                      outer._retry_after_value()),))
                    elif outer._ready.is_set():
                        self._send_plain(200, b"ok")
                    else:
                        self._send_plain(503, b"warming")
                    return
                if self.path == "/metrics":
                    # Prometheus scrape surface: the whole process-wide
                    # registry (executor + serving + compile cache), off
                    # the scoring pipeline entirely. OpenMetrics (with
                    # histogram exemplars linking latency buckets to
                    # trace ids) is negotiated on the Accept header or
                    # forced by SYNAPSEML_OPENMETRICS=1; the default
                    # 0.0.4 exposition never carries an exemplar, so
                    # strict format-0.0.4 parsers are unaffected
                    om = ("application/openmetrics-text"
                          in (self.headers.get("Accept") or "")
                          or os.environ.get("SYNAPSEML_OPENMETRICS",
                                            "") == "1")
                    self._send_plain(
                        200,
                        _tm.prometheus_text(
                            openmetrics=om).encode("utf-8"),
                        ("application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8") if om else
                        "text/plain; version=0.0.4; charset=utf-8")
                    return
                if (self.path.startswith("/debug/")
                        and os.environ.get("SYNAPSEML_DEBUG_ENDPOINTS",
                                           "") == "0"):
                    # locked-down deployments: thread stacks + event
                    # history are internals no unauthenticated client
                    # should read — one switch gates the whole /debug
                    # surface (profile keeps its own finer-grained
                    # SYNAPSEML_DEBUG_PROFILE gate on top)
                    self._send_plain(403, b"debug endpoints disabled")
                    return
                if self.path == "/debug/flight":
                    # live flight-recorder snapshot: ring events +
                    # telemetry + per-thread stacks — what a dump file
                    # contains, without waiting for a trigger
                    self._send_plain(
                        200,
                        json.dumps(_bb.snapshot(),
                                   default=repr).encode("utf-8"),
                        "application/json")
                    return
                if self.path == "/debug/threads":
                    # every live thread's current stack (pipeline/
                    # scorer/probe supervision forensics)
                    self._send_plain(
                        200,
                        json.dumps(_bb.thread_stacks()).encode("utf-8"),
                        "application/json")
                    return
                if self.path == "/debug/build":
                    # fleet version-skew diagnosis: git sha + jax/
                    # jaxlib/backend + device kind + lifecycle state,
                    # per replica (docs/observability.md "Debug
                    # endpoints"; behind the SYNAPSEML_DEBUG_ENDPOINTS
                    # gate above like the whole /debug surface)
                    self._send_plain(
                        200,
                        json.dumps(_build_info(outer)).encode("utf-8"),
                        "application/json")
                    return
                if self.path == "/debug/memory":
                    # per-device memory picture (runtime/perfwatch.py):
                    # memory_stats where the backend has an allocator,
                    # live_arrays aggregation otherwise, plus process
                    # peaks — fresh sample, the operator wants NOW
                    self._send_plain(
                        200,
                        json.dumps(_pw.memory_snapshot(),
                                   default=repr).encode("utf-8"),
                        "application/json")
                    return
                if self.path == "/debug/cost":
                    # roofline cost table (runtime/costmodel.py): the
                    # per-signature flops/bytes/bound ledger captured
                    # at warmup, with the current window's achieved
                    # attribution folded in — what tools/perf_report.py
                    # reads offline, served live beside /debug/memory
                    self._send_plain(
                        200,
                        json.dumps(_cm.snapshot(),
                                   default=repr).encode("utf-8"),
                        "application/json")
                    return
                if self.path.startswith("/debug/capture"):
                    # the incident-capture ledger (runtime/capture.py):
                    # last-N record summaries + the live file's path/
                    # size, so an operator can confirm a breach was
                    # kept — and where to point tools/replay.py —
                    # without shelling into the pod. Bodies are elided
                    # (the file has them); behind the same
                    # SYNAPSEML_DEBUG_ENDPOINTS gate as the whole
                    # /debug surface (403 handled above)
                    params = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        n = int(params.get("n", ["32"])[0])
                    except ValueError:
                        n = 32
                    cap_path = _cap.capture_path()
                    try:
                        cap_size = os.path.getsize(cap_path)
                    except OSError:
                        cap_size = 0
                    self._send_plain(
                        200,
                        json.dumps({
                            "enabled": _cap.enabled(),
                            "path": cap_path,
                            "size_bytes": cap_size,
                            "model_hash": _cap.model_hash(),
                            "records": _cap.tail_summaries(
                                max(1, min(256, n))),
                        }, default=repr).encode("utf-8"),
                        "application/json")
                    return
                if self.path.startswith("/debug/profile"):
                    status, payload = _debug_profile(self.path)
                    self._send_plain(
                        status, json.dumps(payload).encode("utf-8"),
                        "application/json")
                    return
                if self.path.startswith("/span/"):
                    span = _tm.get_span(self.path[len("/span/"):])
                    if span is None:
                        self._send_plain(404, b"no such span")
                        return
                    self._send_plain(
                        200, json.dumps(span.breakdown()).encode("utf-8"),
                        "application/json")
                    return
                if self.path.startswith("/trace/"):
                    # this replica's legs of one distributed trace —
                    # what the controller's /fleet/trace fans out to.
                    # Span storage is process-wide, so every leg any
                    # server in this process created comes back, each
                    # labeled with its origin server
                    tid = self.path[len("/trace/"):].strip("/").lower()
                    if not re.fullmatch(r"[0-9a-f]{32}", tid):
                        self._send_plain(400, b"trace id must be 32 "
                                              b"lowercase hex chars")
                        return
                    legs = _tm.trace_spans(tid)
                    if not legs:
                        self._send_plain(404, b"no spans for trace")
                        return
                    self._send_plain(
                        200,
                        json.dumps({"trace_id": tid,
                                    "server": outer.name,
                                    "pid": os.getpid(),
                                    "legs": legs}).encode("utf-8"),
                        "application/json")
                    return
                self._enqueue()

            do_POST = _enqueue
            do_PUT = _enqueue

        class Server(http.server.ThreadingHTTPServer):
            # default backlog (5) resets connections under concurrent
            # client bursts — the whole point of micro-batch serving
            request_queue_size = 128

        # bind-with-next-port retry: an explicit port may have been
        # probed free (find_open_port) and grabbed since — the TOCTOU
        # window closes by retrying the BIND, not re-probing. port=0
        # stays single-shot (the OS assigns race-free).
        last_err: Optional[OSError] = None
        for attempt in range(max(1, port_attempts) if self.port else 1):
            try:
                self._httpd = Server((host, self.port + attempt), Handler)
                last_err = None
                break
            except OSError as e:
                if e.errno != errno.EADDRINUSE:
                    # only in-use is the TOCTOU race; EACCES/
                    # EADDRNOTAVAIL etc. would either silently serve a
                    # port nobody is pointing at or retry futilely
                    raise
                last_err = e
        if last_err is not None:
            raise last_err
        if self.port and self._httpd.server_address[1] != self.port:
            # drift must be LOUD: a fixed-port deployment (k8s Service
            # targetPort, a peer holding a pre-advertised probe result)
            # routes to the REQUESTED port — only callers that read
            # server.port back can follow the retry
            warnings.warn(
                f"WorkerServer {name!r}: requested port {self.port} in "
                f"use; bound {self._httpd.server_address[1]} instead — "
                "fixed-port consumers must read server.port back",
                RuntimeWarning, stacklevel=2)
        self.port = self._httpd.server_address[1]
        self._httpd.daemon_threads = True
        # synlint: disable=RL001 - socketserver owns this loop's fault
        # handling: per-request errors route to handle_error, and
        # serve_forever only exits via stop()'s shutdown()+join
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"serving-{name}",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    def set_ready(self, ready: bool = True):
        """Flip the /health readiness gate (the serving entry calls this
        after AOT warmup completes)."""
        if ready:
            self._ready.set()
        else:
            self._ready.clear()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self):
        """Flip the graceful-drain gate: /health/ready (and /health)
        answer 503 so the load balancer routes away, and NEW enqueues
        are refused 503 + Retry-After — while every already-accepted
        request keeps scoring to a real reply. The SIGTERM half of the
        k8s rolling-restart contract (ContinuousServer.drain drives the
        wait-then-stop half)."""
        if not self._draining.is_set():
            _bb.record("drain_begin", server=self.name)
        self._draining.set()

    def wait_drained(self, timeout: float) -> bool:
        """Block until every ACCEPTED request has a terminal reply —
        the queue is empty and no connection is parked in the routing
        table — or ``timeout`` elapses. Call after :meth:`begin_drain`
        (otherwise new arrivals can keep this from ever converging).
        Returns True when fully drained."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._lock:
                empty = not self.routing
            if empty and self.requests.qsize() == 0:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def drain(self, timeout_ms: float = 5000.0) -> bool:
        """Graceful drain, one call (the SIGTERM half of a k8s rolling
        restart — the chart's preStop + terminationGracePeriodSeconds
        budget this window): flip the drain gate (new enqueues 503 +
        Retry-After, /health/ready goes 503 so the LB routes away),
        then wait up to ``timeout_ms`` for every accepted request to
        reach a terminal reply. Observes ``serving_drain_seconds``;
        returns True when nothing in flight remained. Both serving
        front-ends (:meth:`ContinuousServer.drain`,
        :meth:`DistributedServer.drain`) delegate here — parked
        connections live in THIS routing table until replied, whatever
        queue their requests ride. Call the front-end's ``stop`` after:
        anything still queued past the deadline gets an explicit 503
        there, never a silent drop."""
        t0 = time.monotonic()
        self.begin_drain()
        drained = self.wait_drained(timeout_ms / 1e3)
        dt = time.monotonic() - t0
        self._m_drain_s.observe(dt)
        _bb.record("drain_end", server=self.name, drained=drained,
                   seconds=round(dt, 6))
        return drained

    def _slo_availability(self) -> float:
        """Good-reply fraction over every terminal reply this server
        committed (5xx = bad; see runtime/slo.py for the policy)."""
        return _slo.availability(
            {code: c.value for code, c in list(self._m_replies.items())})

    def _slo_latency_good(self) -> float:
        """Fraction of roundtrips at or under the latency threshold,
        estimated from the roundtrip histogram's buckets."""
        counts, _total, _n = self._m_roundtrip._aggregate()
        return _slo.fraction_le(self._m_roundtrip.bounds, counts,
                                self.slo_latency_threshold_s)

    def _retry_after_value(self) -> str:
        """``Retry-After`` is integer seconds (RFC 9110): round the
        configured hint UP so a sub-second hint never renders as 0
        (= retry immediately, the exact re-hammer the header exists to
        prevent)."""
        return str(max(1, math.ceil(self.retry_after_s)))

    def _reply_counter(self, status: int) -> "_tm.Counter":
        """Per-status reply counter, registered on first use."""
        c = self._m_replies.get(status)
        if c is None:
            c = self._m_replies.setdefault(status, _tm.counter(
                "serving_replies_total", server=self.name,
                code=str(status)))
        return c

    # -- source side ----------------------------------------------------
    def get_batch(self, max_rows: int = 64, timeout: float = 0.1,
                  linger: float = 0.0,
                  coalesce: float = 0.0) -> List[CachedRequest]:
        """Drain up to ``max_rows`` requests as one epoch's batch.
        ``coalesce`` holds the batch open until the first request is
        that many seconds old (deadline-based coalescing window — see
        :func:`_drain_queue`)."""
        out = _drain_queue(self.requests, max_rows, timeout, linger,
                           coalesce)
        self._record_epoch(out)
        return out

    def _record_epoch(self, out: List[CachedRequest]):
        """Stamp a batch with an epoch and park it in replay history —
        every consumption path (direct or via DistributedServer channels)
        must pass through here or recover() cannot replay it. Also the
        single choke point where batch-size / queue-wait / coalesce-delay
        telemetry gets recorded (outside the lock)."""
        if out:
            now = time.monotonic()
            self._m_batch_size.observe(len(out))
            # coalesce delay: how long the batch's HEAD request waited
            # from arrival to being drained — the price the coalescing
            # window charged (0 linger/coalesce => just scheduling lag)
            self._m_coalesce.observe(now - out[0].arrival)
            for cr in out:
                wait = now - cr.arrival
                cr.drained = now
                self._m_queue_wait.observe(wait)
                cr.span.note("queue_wait", wait)
            with self._lock:
                epoch = self.current_epoch
                self.current_epoch += 1
                for cr in out:
                    cr.epoch = epoch
                self.history[epoch] = list(out)

    def commit(self, epoch: int, exact: bool = False):
        """Prune replay history through ``epoch`` (ref: commit :555-567).

        ``exact=True`` prunes ONLY that epoch — required when epochs
        complete out of order (concurrent scoring workers): a cumulative
        commit of a later epoch would silently drop an earlier,
        still-in-flight epoch's replay history."""
        with self._lock:
            if exact:
                self.history.pop(epoch, None)
                return
            for e in [e for e in self.history if e <= epoch]:
                del self.history[e]

    def recover(self):
        """Re-enqueue uncommitted, unreplied requests (task-retry replay,
        ref: HTTPSourceV2.scala:488-505 recoveredPartitions)."""
        with self._lock:
            pending = [
                cr for ep in sorted(self.history)
                for cr in self.history[ep] if not cr.replied
            ]
            self.history.clear()
        for cr in pending:
            self.requests.put(cr)
        return len(pending)

    # -- sink side ------------------------------------------------------
    def reply_to(self, rid: str, response: HTTPResponseData) -> bool:
        """(ref: WorkerServer.replyTo via HTTPSourceStateHolder :535-553).

        Returns True only when a waiter will actually consume the response;
        an already-expired request is left unreplied so :meth:`recover`
        replays it."""
        with self._lock:
            pending = self.routing.pop(rid, None)
            if pending is None:
                return False
            pending.response = response
            for ep_items in self.history.values():
                for cr in ep_items:
                    if cr.rid == rid:
                        cr.replied = True
        pending.event.set()
        return True

    def fail_queued(self, status: int = 503,
                    reason: str = "server stopping",
                    q: Optional["queue.Queue"] = None) -> int:
        """Reply ``status`` to every request still parked on ``q``
        (default: this server's intake queue; DistributedServer passes
        its channel queues) — the explicit-shed half of shutdown/drain.
        Counted in ``serving_drain_shed_total``; carries Retry-After so
        clients back off before re-trying the replacement replica.
        Returns how many were failed."""
        hdrs = {"Retry-After": self._retry_after_value()}
        shed = _drain_all(self.requests if q is None else q)
        for cr in shed:
            self._m_drain_shed.inc()
            self.reply_to(cr.rid, HTTPResponseData(
                status_code=status, reason=reason, headers=hdrs))
            cr.span.finish("shed")
        if shed:
            _bb.record("shed_stop", level="warn", server=self.name,
                       status=status, n=len(shed),
                       rids=[cr.rid for cr in shed[:8]],
                       trace_ids=[cr.span.trace_id for cr in shed[:8]])
        return len(shed)

    def stop(self):
        # unhook the scrape-time samplers first: a scrape racing the
        # shutdown must read 0, not call into a closed server
        _tm.unregister("serving_queue_depth", server=self.name)
        for slo_series in ("serving_slo_availability",
                           "serving_slo_availability_burn_rate",
                           "serving_slo_latency_good_fraction",
                           "serving_slo_latency_burn_rate",
                           "serving_slo_latency_threshold_ms"):
            _tm.unregister(slo_series, server=self.name)
        _slog.log("info", "server_stop", server=self.name)
        # queued-but-unconsumed requests get an explicit 503 + Retry-
        # After instead of a silent drop that parks their clients until
        # reply_timeout (their handler threads still hold live
        # connection sockets; only the accept loop closes below).
        # Gate first: a handler racing this shed would otherwise pass
        # the drain check and re-park on the just-emptied queue with no
        # consumer left — then shed again after the accept loop stops,
        # for handlers that were already past the gate check.
        self._draining.set()
        self.fail_queued()
        self._httpd.shutdown()
        self.fail_queued()
        self._httpd.server_close()


class HTTPSourceStateHolder:
    """Process-wide registry name -> WorkerServer
    (ref: HTTPSourceV2.scala HTTPSourceStateHolder:337)."""

    _servers: Dict[str, WorkerServer] = {}

    @classmethod
    def get_or_create_server(cls, name: str, host: str = "127.0.0.1",
                             port: Optional[int] = None,
                             **kw) -> WorkerServer:
        with _REGISTRY_LOCK:
            srv = cls._servers.get(name)
            if srv is None:
                srv = WorkerServer(name, host, port, **kw)
                cls._servers[name] = srv
            return srv

    @classmethod
    def get_server(cls, name: str) -> WorkerServer:
        return cls._servers[name]

    @classmethod
    def remove(cls, name: str):
        with _REGISTRY_LOCK:
            srv = cls._servers.pop(name, None)
        if srv is not None:
            srv.stop()


class MultiChannelMap:
    """Depth-aware request distribution across N consumer channels
    (ref: DistributedHTTPSource.scala MultiChannelMap:27-80 — adds rotate
    through channel lists; updateNLists disperses orphaned channels on
    elastic resize).

    Placement is least-loaded-first among ENABLED channels (rotation
    order breaks ties, so an idle map degrades to exact round-robin):
    a channel whose consumer backs up sheds new load to its siblings
    instead of accumulating it — the queue-depth half of the channel
    circuit breakers. ``set_channel_enabled(i, False)`` quarantines a
    channel (breaker OPEN): placement never picks it while any enabled
    channel exists, and its parked requests re-disperse immediately.
    When EVERY channel is disabled, placement degrades to least-loaded
    over all of them — availability over purity; the half-open probes
    re-admit channels as they heal.

    All channel-list access stays under the lock (queue puts included —
    they never block, so holding the lock is safe): a put outside it
    could land on a channel a concurrent shrink already drained, losing
    the request."""

    def __init__(self, n_channels: int):
        self._lock = make_lock("MultiChannelMap._lock")
        self._channels: List["queue.Queue[CachedRequest]"] = [
            queue.Queue() for _ in range(max(1, n_channels))
        ]
        self._add_index = 0
        self._disabled: set = set()

    @property
    def n_channels(self) -> int:
        with self._lock:
            return len(self._channels)

    def depths(self) -> List[int]:
        """Current queue depth per channel (one consistent snapshot)."""
        with self._lock:
            return [q.qsize() for q in self._channels]

    def enabled_channels(self) -> List[int]:
        """Indices placement may currently target (breaker CLOSED)."""
        with self._lock:
            return [i for i in range(len(self._channels))
                    if i not in self._disabled]

    def _place(self, item: CachedRequest):
        """Least-loaded enabled channel, rotation-order tiebreak —
        caller holds the lock. With no consumers draining, depths grow
        uniformly and this IS round-robin; under skewed drain rates the
        deepest channel stops receiving."""
        n = len(self._channels)
        candidates = [i for i in range(n) if i not in self._disabled] \
            or list(range(n))
        start = self._add_index
        best = min(candidates,
                   key=lambda i: (self._channels[i].qsize(),
                                  (i - start) % n))
        # every caller holds self._lock (the "caller holds the lock"
        # contract in this method's docstring) — invisible to the
        # analyzer's same-function guard detection
        self._add_index = (best + 1) % n  # synlint: disable=CC001
        self._channels[best].put(item)

    def add(self, item: CachedRequest):
        with self._lock:
            self._place(item)

    def channel(self, i: int) -> "queue.Queue[CachedRequest]":
        """Current queue for channel ``i`` (clamped: a concurrent shrink
        must degrade to serving a live channel, not IndexError)."""
        with self._lock:
            return self._channels[i % len(self._channels)]

    def set_channel_enabled(self, i: int, enabled: bool) -> int:
        """Quarantine (``False``) or re-admit (``True``) channel ``i``.
        Quarantining re-disperses its parked requests onto enabled
        channels — a request must never sit on a queue no healthy
        consumer drains. Returns how many requests moved."""
        # synlint: disable=DS001 - breaker -> channel-map nesting is
        # one-way: the map never calls back into the breaker
        with self._lock:
            if not 0 <= i < len(self._channels):
                return 0
            if enabled:
                self._disabled.discard(i)
                return 0
            self._disabled.add(i)
            # drain FULLY before re-placing: when every channel is
            # disabled _place's availability fallback may legitimately
            # pick this channel again
            orphaned = _drain_all(self._channels[i])
            for item in orphaned:
                self._place(item)
        if orphaned:
            # the flight-recorder breadcrumb a trip forensic needs:
            # WHICH requests moved off the quarantined channel (rids
            # capped — counts tell the scale, ids tell the story)
            _bb.record("redisperse", channel=i, level="warn",
                       n=len(orphaned),
                       rids=[cr.rid for cr in orphaned[:8]])
        return len(orphaned)

    def update_n_channels(self, n: int):
        """Resize; requests parked on removed channels are re-dispersed
        (ref: updateNLists:39-52). Quarantine state for surviving
        indices is preserved; removed indices forget theirs."""
        n = max(1, n)
        with self._lock:
            orphaned: List[CachedRequest] = []
            while len(self._channels) > n:
                dead = self._channels.pop()
                self._disabled.discard(len(self._channels))
                orphaned.extend(_drain_all(dead))
            while len(self._channels) < n:
                self._channels.append(queue.Queue())
            self._add_index %= len(self._channels)
            for item in orphaned:
                self._place(item)


def device_for_channel(channel: int, devices=None):
    """Round-robin map of a serving channel index onto a local device.

    The serving-side counterpart of the executor's dp fan-out: shard i of
    a DistributedServer scores on ``device_for_channel(i)`` so concurrent
    channels use distinct chips (ref: the reference's one-ORT-session-per-
    Spark-partition layout, ONNXModel.scala:497-508). ``devices`` defaults
    to ``jax.local_devices()``; import is deferred so the serving module
    stays importable without a device runtime."""
    import jax

    devices = list(devices) if devices is not None else jax.local_devices()
    return devices[channel % len(devices)]


# circuit-breaker states, exported on the
# serving_channel_breaker_state{channel=} gauge. CLOSED = traffic flows
# (electrical convention: the circuit conducts); OPEN = quarantined;
# HALF_OPEN = a canary probe is in flight.
BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 0, 1, 2
_BREAKER_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                        BREAKER_HALF_OPEN: "half_open"}


class DistributedServer:
    """Serving v1 analogue: ONE shared HTTP server per host whose
    requests distribute across worker channels
    (ref: DistributedHTTPSource.scala JVMSharedServer:90 shared via
    SharedSingleton :384, MultiChannelMap :27, DistributedHTTPSink:364).
    Each shard drains its own channel with ``get_batch(channel=i)`` and
    replies through the shared server — or :meth:`serve` runs the
    per-channel scoring loops in-process.

    The CHANNEL is the unit of fault tolerance (docs/robustness.md,
    "channel failure domains"): each channel carries a circuit breaker.
    ``breaker_threshold`` consecutive scoring failures — or a score
    stalled past ``stall_timeout`` — trip it OPEN: the channel's device
    is quarantined, its parked requests re-disperse onto healthy
    channels, and new placement avoids it. A background probe then
    flips it HALF_OPEN, re-scores a canary under the channel's own
    fault points, and re-admits (CLOSED) on success. An in-hand batch
    whose channel breaks mid-score fails over ONCE to a healthy channel
    (:meth:`score_on_channel`) before any client-visible error —
    bit-identically, since the failover re-runs the same scoring fn."""

    def __init__(self, name: str, n_channels: int,
                 host: str = "127.0.0.1", port: Optional[int] = None,
                 reply_timeout: float = 60.0,
                 breaker_threshold: int = 3,
                 probe_interval: float = 0.25,
                 stall_timeout: Optional[float] = None,
                 canary_fn: Optional[Callable[[int], Any]] = None):
        """``breaker_threshold``: consecutive failures that trip a
        channel OPEN (1 = first failure quarantines). ``probe_interval``
        seconds between half-open canary probes of OPEN channels.
        ``stall_timeout``: a score wall-time past this counts as a
        breaker failure even though its result still returns (the
        slow-channel trip condition; None = off). ``canary_fn(ch)``:
        extra health work the half-open probe runs on the quarantined
        channel (e.g. re-score a pinned canary batch on its device);
        when None, :meth:`serve` wires a default that re-scores the
        first successfully scored row through the real pipeline. The
        probe always fires the channel's fault points, so injected
        chaos alone round-trips OPEN -> HALF_OPEN -> CLOSED."""
        self.server = HTTPSourceStateHolder.get_or_create_server(
            name, host, port, reply_timeout=reply_timeout)
        # exactly one distributor may own a server's request queue: a
        # second consumer would silently steal an arbitrary subset.
        # check-and-claim happens atomically under the server's lock —
        # the historical unlocked getattr-then-set let two concurrent
        # constructors both pass the check and both start distributors
        with self.server._lock:
            if getattr(self.server, "_dist_owner", None) is not None:
                raise ValueError(
                    f"server {name!r} already has a DistributedServer "
                    f"attached; reuse that instance or pick another name")
            self.server._dist_owner = self  # synlint: shared
        self.channels = MultiChannelMap(n_channels)
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.probe_interval = float(probe_interval)
        self.stall_timeout = stall_timeout
        self.canary_fn = canary_fn
        # breaker state: guarded by its own lock. Lock order is
        # breaker -> map, ONE direction — state flips and their matching
        # map enable/disable happen together under the breaker lock
        # (map calls never block: queue puts only), so a channel can
        # never be breaker-OPEN yet placement-enabled, which would park
        # requests on a queue whose consumer loop is idling
        self._breaker_lock = make_lock("DistributedServer._breaker_lock")
        self._breaker_state: Dict[int, int] = {}
        self._breaker_fails: Dict[int, int] = {}
        # one-row snapshot of the first successfully scored input:
        # serve()'s default canary re-scores it through the REAL
        # pipeline so the half-open probe proves the device works, not
        # just that a no-op returns (benign last-write-wins race between
        # channel loops: every candidate snapshot is known-good)
        self._canary_table: Optional[Table] = None  # synlint: shared
        self._channel_points: Dict[int, "_flt.FaultPoint"] = {}
        self._m_failover = _tm.counter("serving_failover_total",
                                       server=name)
        self._m_redispersed = _tm.counter("serving_redispersed_total",
                                          server=name)
        self._m_trips = _tm.counter("serving_channel_trips_total",
                                    server=name)
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_wake = threading.Event()
        self._serve_threads: List[threading.Thread] = []
        self._n_channel_gauges = 0
        self._sync_channel_gauges()
        self._stop = threading.Event()
        self._m_dist_restarts = _tm.counter(
            "serving_thread_restarts_total", server=name,
            thread="distributor")
        self._distributor = threading.Thread(
            target=self._distribute_supervised, name=f"dist-{name}",
            daemon=True)
        self._distributor.start()

    def _sync_channel_gauges(self):
        """One scrape-time depth sampler + breaker-state gauge per live
        channel (re-synced on elastic resize; samplers beyond the new
        count are dropped)."""
        n = self.channels.n_channels
        for i in range(n):
            _tm.gauge_fn(
                "serving_channel_depth",
                lambda ch=i: self.channels.channel(ch).qsize(),
                server=self.server.name, channel=str(i))
            _tm.gauge_fn(
                "serving_channel_breaker_state",
                lambda ch=i: self.channel_state(ch),
                server=self.server.name, channel=str(i))
        for i in range(n, self._n_channel_gauges):
            _tm.unregister("serving_channel_depth",
                           server=self.server.name, channel=str(i))
            _tm.unregister("serving_channel_breaker_state",
                           server=self.server.name, channel=str(i))
        self._n_channel_gauges = n

    @property
    def url(self) -> str:
        return self.server.url

    # -- channel circuit breakers ---------------------------------------

    def channel_state(self, channel: int) -> int:
        """BREAKER_CLOSED / BREAKER_OPEN / BREAKER_HALF_OPEN."""
        with self._breaker_lock:
            return self._breaker_state.get(channel, BREAKER_CLOSED)

    def _set_state_locked(self, channel: int, state: int):
        # caller holds _breaker_lock. Transitions are COUNTED (not just
        # gauged): a probe's OPEN->HALF_OPEN->OPEN bounce is faster than
        # any scrape interval, so the chaos check asserts the counter,
        # the dashboards plot the gauge.
        prev = self._breaker_state.get(channel, BREAKER_CLOSED)
        if prev == state:
            return
        self._breaker_state[channel] = state
        _tm.counter("serving_breaker_transitions_total",
                    server=self.server.name, channel=str(channel),
                    state=_BREAKER_STATE_NAMES[state]).inc()
        # ring + log breadcrumb (blackbox.record is leaf-lock safe
        # under _breaker_lock): every state entry, including the
        # OPEN->HALF_OPEN->OPEN probe bounces no scrape ever sees
        _bb.record("breaker_transition", channel=channel,
                   level="warn" if state != BREAKER_CLOSED else "info",
                   server=self.server.name,
                   state=_BREAKER_STATE_NAMES[state],
                   prev=_BREAKER_STATE_NAMES[prev])

    def _channel_point(self, channel: int) -> "_flt.FaultPoint":
        """The channel's ``compute.channel<N>`` fault point, resolved
        lazily — channel counts are a runtime property, unlike the
        import-time module points."""
        p = self._channel_points.get(channel)
        if p is None:
            p = self._channel_points.setdefault(
                channel, _flt.point("compute", f"channel{channel}"))
        return p

    def _record_channel_success(self, channel: int):
        with self._breaker_lock:
            self._breaker_fails[channel] = 0
            if self._breaker_state.get(channel,
                                       BREAKER_CLOSED) != BREAKER_CLOSED:
                # state flip + map re-enable are ATOMIC under the
                # breaker lock: a racing trip on another thread cannot
                # interleave its disable between them and leave the
                # channel OPEN-but-enabled (a request black hole)
                self._set_state_locked(channel, BREAKER_CLOSED)
                self.channels.set_channel_enabled(channel, True)

    def _record_channel_failure(self, channel: int) -> bool:
        """Count one failure against the channel; returns True when it
        tripped the breaker just now (quarantine + redisperse done)."""
        with self._breaker_lock:
            if self._breaker_state.get(channel,
                                       BREAKER_CLOSED) == BREAKER_OPEN:
                return False
            fails = self._breaker_fails.get(channel, 0) + 1
            self._breaker_fails[channel] = fails
            if fails < self.breaker_threshold:
                return False
            self._set_state_locked(channel, BREAKER_OPEN)
            # quarantine atomically with the state flip (breaker -> map
            # order): re-disperse what was parked on the channel
            moved = self.channels.set_channel_enabled(channel, False)
        if moved:
            self._m_redispersed.inc(moved)
        self._m_trips.inc()
        # the incident trigger: the trip event lands in the ring, then
        # the recorder dumps ring + gauges + thread stacks to the dump
        # dir (debounced) — the forensic file the runbook says to pull
        # first (docs/robustness.md). Runs with no locks held.
        _bb.trigger("breaker_trip", channel=channel,
                    server=self.server.name, fails=fails,
                    redispersed=moved)
        self._ensure_probe_thread()
        self._probe_wake.set()
        return True

    def _channel_score(self, channel: int, score_fn: Callable[[], Any]):
        """Run one unit of scoring work AS channel ``channel``: fires
        the shared stall point and the channel's own compute point
        first, so injected channel faults land exactly here."""
        _F_LAT_STALL.fire()
        self._channel_point(channel).fire()
        return score_fn()

    def _failover_target(self, exclude: int) -> Optional[int]:
        """Least-loaded healthy channel other than ``exclude`` (depth-
        aware, same policy as placement), or None when no healthy
        sibling exists."""
        depths = self.channels.depths()
        best, best_depth = None, None
        for ch in self.channels.enabled_channels():
            if ch == exclude or self.channel_state(ch) != BREAKER_CLOSED:
                continue
            d = depths[ch] if ch < len(depths) else 0
            if best is None or d < best_depth:
                best, best_depth = ch, d
        return best

    def score_on_channel(self, channel: int,
                         score_fn: Callable[[], Any],
                         rids: Optional[List[str]] = None,
                         trace_ids: Optional[List[str]] = None):
        """Failover dispatch: run ``score_fn`` as channel ``channel``'s
        scoring work under its fault points and breaker accounting. On
        failure, the SAME in-hand work is re-dispatched ONCE to a
        healthy channel before any client-visible error — bit-identical
        output, because the failover re-runs the identical fn (the
        channel only selects WHERE it runs). A score stalled past
        ``stall_timeout`` counts as a breaker failure even though its
        result still returns. ``rids``: the request ids riding the
        in-hand work — they ride the flight-recorder failover event so
        a dump names WHICH requests moved channels."""
        t0 = time.monotonic()
        try:
            out = self._channel_score(channel, score_fn)
        except Exception as first_err:
            self._record_channel_failure(channel)
            target = self._failover_target(exclude=channel)
            if target is None:
                raise  # no healthy sibling: the caller's error path
            self._m_failover.inc()
            _bb.record("failover", channel=channel, level="warn",
                       server=self.server.name, to_channel=target,
                       rids=(rids or [])[:8],
                       trace_ids=(trace_ids or [])[:8],
                       error=repr(first_err)[:200])
            t1 = time.monotonic()
            try:
                out = self._channel_score(target, score_fn)
            except Exception:
                # the same work failed on a healthy channel too: likely
                # the BATCH, not the channel — but count it anyway; a
                # wrongly tripped channel is re-admitted by its probe
                self._record_channel_failure(target)
                raise
            self._record_outcome(target, t1)
            return out
        self._record_outcome(channel, t0)
        return out

    def _record_outcome(self, channel: int, t0: float):
        """Success-or-stall accounting for one completed score: a score
        stalled past ``stall_timeout`` counts as a breaker failure even
        though its result still returns — on the FAILOVER attempt too,
        or a degraded channel every failover lands on would be recorded
        as an unconditional success and convoy the cluster."""
        if (self.stall_timeout is not None
                and time.monotonic() - t0 > self.stall_timeout):
            self._record_channel_failure(channel)
        else:
            self._record_channel_success(channel)

    def _ensure_probe_thread(self):
        # check-and-start under the breaker lock: two channels tripping
        # in the same instant must not each spawn a probe loop (the
        # loser's thread would double-probe quarantined devices and
        # escape stop()'s join, which only knows self._probe_thread)
        with self._breaker_lock:
            if (self._probe_thread is not None
                    and self._probe_thread.is_alive()):
                return
            self._probe_thread = threading.Thread(
                target=self._probe_loop_supervised,
                name=f"breaker-probe-{self.server.name}", daemon=True)
            self._probe_thread.start()

    def _probe_loop_supervised(self):
        """:func:`_supervise_loop` around :meth:`_probe_loop`: a dead
        probe thread would strand every OPEN channel quarantined
        forever — the breaker re-admits channels through this loop."""
        def on_restart(e: BaseException):
            _tm.counter("serving_thread_restarts_total",
                        server=self.server.name, thread="probe").inc()
            _bb.record("thread_restart", level="error",
                       server=self.server.name, thread="probe",
                       error=repr(e)[:200])

        _supervise_loop(self._probe_loop, self._stop, on_restart)

    def _probe_loop(self):
        """Half-open probe: every ``probe_interval`` seconds, each OPEN
        channel goes HALF_OPEN and re-scores a canary under its own
        fault points (plus ``canary_fn``); success re-admits it CLOSED
        (and re-enables placement), failure re-opens it."""
        while not self._stop.is_set():
            self._probe_wake.wait(self.probe_interval)
            self._probe_wake.clear()
            if self._stop.is_set():
                return
            with self._breaker_lock:
                open_chs = [ch for ch, st in self._breaker_state.items()
                            if st == BREAKER_OPEN]
            for ch in open_chs:
                with self._breaker_lock:
                    self._set_state_locked(ch, BREAKER_HALF_OPEN)
                t0 = time.monotonic()
                try:
                    self._channel_score(
                        ch, (lambda: self.canary_fn(ch))
                        if self.canary_fn is not None else lambda: None)
                except Exception:
                    ok = False
                else:
                    # the canary gets the SAME stall accounting as a
                    # real score: a channel tripped for slowness must
                    # not be re-admitted by a canary that itself
                    # stalled (trip -> re-admit -> trip flapping, with
                    # a redisperse every cycle)
                    ok = (self.stall_timeout is None
                          or time.monotonic() - t0 <= self.stall_timeout)
                _tm.counter("serving_channel_probe_total",
                            server=self.server.name,
                            outcome="ok" if ok else "fail").inc()
                _bb.record("breaker_probe", channel=ch,
                           server=self.server.name,
                           outcome="ok" if ok else "fail")
                if ok:
                    self._record_channel_success(ch)
                else:
                    with self._breaker_lock:
                        self._set_state_locked(ch, BREAKER_OPEN)

    def _distribute_supervised(self):
        """:func:`_supervise_loop` around :meth:`_distribute`: an
        exception there used to silently stop ALL traffic."""
        def on_restart(e: BaseException):
            self._m_dist_restarts.inc()
            _bb.record("thread_restart", level="error",
                       server=self.server.name, thread="distributor",
                       error=repr(e)[:200])

        _supervise_loop(self._distribute, self._stop, on_restart)

    def _distribute(self):
        while not self._stop.is_set():
            # kill point BEFORE the get: a dying distributor must never
            # take an already-dequeued request with it
            _F_KILL_DIST.fire()
            try:
                item = self.server.requests.get(timeout=0.05)
            except queue.Empty:
                continue
            self.channels.add(item)

    def get_batch(self, channel: int, max_rows: int = 64,
                  timeout: float = 0.1, linger: float = 0.0,
                  coalesce: float = 0.0) -> List[CachedRequest]:
        out = _drain_queue(self.channels.channel(channel), max_rows,
                           timeout, linger, coalesce)
        # same epoch/history bookkeeping as the direct path, so a shard
        # that dies mid-batch stays replayable through server.recover()
        self.server._record_epoch(out)
        return out

    def device_for_channel(self, channel: int):
        """Map a serving channel onto a local accelerator, round-robin —
        the per-channel scorer passes this (as ``devices=[dev]``, or as
        ``ONNXModel.devices``) so N channels fan their micro-batches out
        over N chips instead of convoying on device 0. With more channels
        than chips, channels share devices round-robin; the executor's
        submit/drain pipeline interleaves their batches."""
        return device_for_channel(channel)

    def reply_to(self, rid: str, response: HTTPResponseData) -> bool:
        return self.server.reply_to(rid, response)

    def update_n_channels(self, n: int):
        if self._serve_threads:
            # serve() snapshots the channel count: growing it now would
            # route new requests (depth-aware _place prefers the empty
            # newcomers) onto queues NO scorer loop drains — clients
            # would park until reply_timeout with no error at the
            # resize call
            raise ValueError(
                f"server {self.server.name!r} has channel scorers "
                "running; resize while serving is not supported "
                "(stop, resize, re-serve)")
        self.channels.update_n_channels(n)
        self._sync_channel_gauges()

    # -- in-process channel scoring loops -------------------------------

    def serve(self, pipeline_fn: Callable[[Table], Table],
              max_batch: int = 64, linger: float = 0.0,
              coalesce: float = 0.0, parse_json: bool = True,
              reply_col: str = "reply") -> "DistributedServer":
        """Start one supervised scorer thread per channel — the
        multi-channel serving query. Each loop drains its own channel
        and scores through :meth:`score_on_channel`, so a channel whose
        device breaks mid-score fails its in-hand batch over to a
        healthy sibling (200, bit-identical) instead of 500ing, and a
        tripped channel idles until its half-open probe re-admits it
        (its parked requests having re-dispersed at trip time). The
        channel-count is snapshotted here; resize while serving is not
        supported (stop, resize, re-serve)."""
        if self._serve_threads:
            raise ValueError(
                f"server {self.server.name!r} already has channel "
                "scorers running")
        if self.canary_fn is None:
            # a no-op canary would re-admit a genuinely broken device
            # every probe_interval (trip -> re-admit flapping, one
            # redisperse per cycle): probe with the real pipeline
            self.canary_fn = self._pipeline_canary(pipeline_fn)
        for ch in range(self.channels.n_channels):
            t = threading.Thread(
                target=self._channel_loop_supervised,
                args=(ch, pipeline_fn, max_batch, linger, coalesce,
                      parse_json, reply_col),
                name=f"chan-scorer-{self.server.name}-{ch}", daemon=True)
            t.start()
            self._serve_threads.append(t)
        return self

    def _pipeline_canary(self, pipeline_fn) -> Callable[[int], Any]:
        """Default half-open canary for :meth:`serve`: re-score the
        captured known-good one-row input through the REAL pipeline, so
        re-admission proves the channel can score — not just that a
        no-op returns. Before the first successful score nothing is
        known-good, so the probe degrades to firing the channel's fault
        points only (injected chaos still round-trips the breaker)."""
        def canary(ch: int):
            table = self._canary_table
            if table is not None:
                pipeline_fn(table)
        return canary

    def _channel_loop_supervised(self, ch: int, *args):
        def on_restart(e: BaseException):
            _tm.counter("serving_thread_restarts_total",
                        server=self.server.name,
                        thread=f"channel{ch}").inc()
            _bb.record("thread_restart", channel=ch, level="error",
                       server=self.server.name,
                       thread=f"channel{ch}", error=repr(e)[:200])

        _supervise_loop(
            lambda: self._channel_loop(ch, *args), self._stop,
            on_restart)

    def _channel_loop(self, ch: int, pipeline_fn, max_batch, linger,
                      coalesce, parse_json, reply_col):
        while not self._stop.is_set():
            if self.channel_state(ch) != BREAKER_CLOSED:
                # quarantined: parked requests re-dispersed at trip
                # time and placement avoids this channel — idle until
                # the probe re-admits it
                time.sleep(0.02)
                continue
            batch = self.get_batch(ch, max_batch, timeout=0.05,
                                   linger=linger, coalesce=coalesce)
            if not batch:
                continue
            self._score_channel_batch(ch, batch, pipeline_fn,
                                      parse_json, reply_col)

    def _score_channel_batch(self, ch: int, batch: List[CachedRequest],
                             pipeline_fn, parse_json, reply_col):
        """Score one channel's micro-batch (with one-shot failover via
        :meth:`score_on_channel`) and reply; a batch that failed on TWO
        channels gets an explicit 500 — never a hang, never a silent
        drop."""
        def run():
            table = requests_to_table(batch)
            if parse_json:
                table = parse_request(table)
            out = pipeline_fn(table)
            if self._canary_table is None:
                # first known-good input: one row is all the probe
                # needs (copied so the slice doesn't pin the batch)
                snap = Table({c: table[c][:1].copy()
                              for c in table.columns})
                with self._breaker_lock:
                    if self._canary_table is None:
                        self._canary_table = snap
            return out

        err: Optional[BaseException] = None
        t0 = time.monotonic()
        try:
            out = self.score_on_channel(
                ch, run, rids=[cr.rid for cr in batch],
                trace_ids=[cr.span.trace_id for cr in batch])
        except Exception as e:  # noqa: BLE001 - channel loop must survive
            err = e
        dt = time.monotonic() - t0
        if _SLOW_BATCH_S and dt > _SLOW_BATCH_S:
            _bb.record("slow_batch", channel=ch, level="warn",
                       server=self.server.name, seconds=round(dt, 6),
                       size=len(batch),
                       rids=[cr.rid for cr in batch[:8]],
                       trace_ids=[cr.span.trace_id for cr in batch[:8]])
        if err is None:
            try:
                send_replies(self.server, out, reply_col)
            except Exception as e:  # noqa: BLE001 - bad reply col etc.
                err = e
        if err is not None:
            for cr in batch:
                self.server.reply_to(cr.rid, HTTPResponseData(
                    status_code=500, reason="channel scoring error",
                    entity=repr(err).encode()))
        for cr in batch:
            cr.span.finish("ok" if err is None else "error")
        for ep in sorted({cr.epoch for cr in batch}):
            self.server.commit(ep, exact=True)

    def drain(self, timeout_ms: float = 5000.0) -> bool:
        """Graceful drain across ALL channels — delegates to
        :meth:`WorkerServer.drain` (requests fanned out onto channel
        queues still park their connections in the shared server's
        routing table, so its convergence check covers them). Returns
        True when fully drained; call :meth:`stop` after either way."""
        return self.server.drain(timeout_ms)

    def stop(self):
        self._stop.set()
        self._probe_wake.set()
        self._distributor.join(timeout=2)
        for t in self._serve_threads:
            t.join(timeout=5)
        self._serve_threads = []
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2)
        # requests still parked on channel queues when the scorers exit
        # get an explicit 503 + Retry-After (counted), never a silent
        # drop into reply_timeout; the shared server's own intake queue
        # is shed the same way by server.stop() via the registry
        # removal below
        for ch in range(self.channels.n_channels):
            self.server.fail_queued(q=self.channels.channel(ch))
        for i in range(self._n_channel_gauges):
            _tm.unregister("serving_channel_depth",
                           server=self.server.name, channel=str(i))
            _tm.unregister("serving_channel_breaker_state",
                           server=self.server.name, channel=str(i))
        self._n_channel_gauges = 0
        with self.server._lock:
            self.server._dist_owner = None
        HTTPSourceStateHolder.remove(self.server.name)


# ---------------------------------------------------------------------------
# source/sink as table operations (IOImplicits + ServingUDFs analogues)
# ---------------------------------------------------------------------------

ID_COL = "id"
REQUEST_COL = "request"


def requests_to_table(batch: List[CachedRequest]) -> Table:
    """Micro-batch of requests -> Table (ref: HTTPInputPartitionReader row
    conversion :698; columns: id, request)."""
    ids = np.array([cr.rid for cr in batch], dtype=object)
    reqs = np.empty(len(batch), dtype=object)
    reqs[:] = [cr.request for cr in batch]
    return Table({ID_COL: ids, REQUEST_COL: reqs})


def parse_request(table: Table, as_json: bool = True,
                  output_col: str = "value") -> Table:
    """``.parseRequest`` fluent helper (ref: IOImplicits.scala:20-189)."""
    vals = np.empty(table.num_rows, dtype=object)
    for i, req in enumerate(table[REQUEST_COL]):
        body = req.entity or b""
        if as_json:
            try:
                vals[i] = json.loads(body.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                vals[i] = None
        else:
            vals[i] = body
    return table.with_column(output_col, vals)


def make_reply(value: Any, status: int = 200,
               content_type: str = "application/json") -> HTTPResponseData:
    """``ServingUDFs.makeReplyUDF`` analogue (ref: ServingUDFs.scala:17-36)."""
    from synapseml_tpu.core.param import _json_default

    if isinstance(value, (bytes, bytearray)):
        body = bytes(value)
    elif isinstance(value, str):
        body = value.encode("utf-8")
    else:
        # _json_default handles numpy scalars/arrays nested anywhere
        body = json.dumps(value, default=_json_default).encode("utf-8")
    return HTTPResponseData(status_code=status,
                            headers={"Content-Type": content_type},
                            entity=body)


def send_replies(server: WorkerServer, table: Table,
                 reply_col: str = "reply", id_col: str = ID_COL) -> int:
    """``ServingUDFs.sendReplyUDF`` analogue (ref: ServingUDFs.scala:37-51,
    HTTPDataWriter.write)."""
    sent = 0
    for rid, rep in zip(table[id_col], table[reply_col]):
        if not isinstance(rep, HTTPResponseData):
            rep = make_reply(rep)
        if server.reply_to(rid, rep):
            sent += 1
    return sent


class ContinuousServer:
    """The serving query: source -> pipeline -> reply sink in a loop thread
    (the ``spark.readStream.server() ... writeStream.server()`` pattern,
    ref: IOImplicits.scala + HTTPv2Suite).

    ``pipeline_fn``: Table(id, request, value) -> Table with ``reply_col``.
    """

    def __init__(self, name: str, pipeline_fn: Callable[[Table], Table],
                 host: str = "127.0.0.1", port: Optional[int] = None,
                 max_batch: int = 64, parse_json: bool = True,
                 reply_col: str = "reply", reply_timeout: float = 60.0,
                 batch_linger: float = 0.0, pipelined: bool = True,
                 scoring_workers: int = 1, batch_coalesce: float = 0.0,
                 ready: bool = True, max_errors: int = 256,
                 deadline_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 retry_transient: int = 1,
                 retry_backoff: float = 0.05,
                 retry_rng: Optional[Any] = None,
                 retry_after_s: Optional[float] = None):
        """``batch_linger``: seconds to keep collecting after the first
        request of a batch arrives. A few ms turns concurrent clients'
        requests into ONE scored micro-batch (one device round trip
        amortized over the batch) instead of serial singletons.

        ``batch_coalesce`` (default 0 = off): deadline-based coalescing —
        hold the batch open until its FIRST request is this many seconds
        old (arrival-anchored, see :func:`_drain_queue`). Unlike linger,
        a request that already sat in the queue that long pays no added
        wait, so concurrent low-QPS clients coalesce into one scored
        micro-batch while worst-case added latency stays bounded by the
        window (bench r05: 32 clients amortized to 3.31 ms/request
        against a 0.33 ms roundtrip floor — coalescing is what closes
        that gap without taxing a lone client).

        Multi-chip scoring is a property of the *pipeline*, not the
        server: build the model with ``ONNXModel.devices`` (the
        ``main()`` container entry does this for ``--devices``), or pin
        per-channel devices via :func:`device_for_channel`.

        ``pipelined``: run collection and scoring as a staged pipeline
        (a collector thread drains + lingers on batch k+1 WHILE the device
        scores batch k, and keeps coalescing for as long as every scorer is
        busy — adaptive linger). ``False`` restores the strictly serial
        drain->score loop.

        ``scoring_workers``: concurrent scorer threads (pipelined mode).
        Default 1: ``pipeline_fn`` is never called concurrently unless
        the caller opts in (>1 requires a thread-safe pipeline — jitted
        jax fns are; ad-hoc host state may not be).
        On a remote/tunneled device the per-batch wall time is dominated
        by dispatch ROUND-TRIP latency, not device compute — N workers
        keep N micro-batches in flight, so throughput scales toward
        N/RTT while per-request latency stays one RTT (replies are
        per-request ids; epochs commit independently, so ordering is
        preserved per epoch, as in the reference's partition-parallel
        HTTPSourceV2 writers).

        Pipelined mode is a THREE-stage pipeline: collect -> score ->
        reply. Reply serialization + socket writes + epoch commits for
        batch k run on a dedicated reply thread while the scorer already
        scores batch k+1 — and since the scorer itself feeds the
        executor's async submit/drain pipeline (runtime/executor.py),
        host staging, H2D, device compute, and D2H fetch of consecutive
        micro-batches all overlap instead of alternating.

        ``ready=False`` starts the embedded server with its /health
        readiness gate CLOSED (503): the caller warms the compile cache
        first, then flips ``self.server.set_ready(True)`` — so traffic
        never lands on a compiling chip (the ``main()`` --warmup flow).

        Robustness knobs (docs/robustness.md): ``deadline_ms`` is the
        default per-request deadline (clients override per request via
        the ``X-Deadline-Ms`` header); a request already expired at
        batch-form time is shed 504 BEFORE scoring. ``max_queue`` sheds
        429 at enqueue past that backlog. ``retry_transient`` bounds
        how many times a :class:`PipelineBrokenError` from the scoring
        pipeline is retried (with ``retry_backoff``-scaled jittered
        sleep) against the supervision-restarted executor pipeline
        before the batch takes the 500 path. ``retry_rng``: the PRNG
        behind the jitter — inject a seeded ``random.Random`` so retry
        timing is deterministic under test (``SYNAPSEML_RETRY_SEED``
        is the env route, see :func:`_retry_rng`). ``retry_after_s``
        overrides the server's Retry-After hint on shed replies."""
        self.server = HTTPSourceStateHolder.get_or_create_server(
            name, host, port, reply_timeout=reply_timeout, ready=ready,
            default_deadline_ms=deadline_ms, max_queue=max_queue)
        if not ready:
            # the registry may have returned an EXISTING server (ctor
            # kwargs ignored): close the gate explicitly so a reused name
            # still holds /health at 503 through warmup
            self.server.set_ready(False)
        if deadline_ms is not None:
            self.server.default_deadline_ms = deadline_ms
        if max_queue is not None:
            self.server.max_queue = max_queue
        if retry_after_s is not None:
            self.server.retry_after_s = retry_after_s
        self.name = name
        self.pipeline_fn = pipeline_fn
        self.max_batch = max_batch
        self.batch_linger = batch_linger
        self.batch_coalesce = batch_coalesce
        self.parse_json = parse_json
        self.reply_col = reply_col
        self.pipelined = pipelined
        self.scoring_workers = max(1, int(scoring_workers))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._collector: Optional[threading.Thread] = None
        self._extra_scorers: List[threading.Thread] = []
        self._handoff: Optional["queue.Queue"] = None
        self._reply_q: Optional["queue.Queue"] = None
        self._reply_thread: Optional[threading.Thread] = None
        # appended from every scorer thread AND the reply thread; guarded
        # so concurrent failures can't lose entries (list.append happens
        # to be GIL-atomic today, but the discipline is the contract).
        # BOUNDED: under sustained failure the list used to grow without
        # limit — now the oldest entry is dropped past ``max_errors`` and
        # the drop is counted (serving_errors_dropped_total), so a
        # long-lived server keeps the *recent* errors and a flat memory
        # profile
        self._err_lock = make_lock("ContinuousServer._err_lock")
        self.errors: List[str] = []  # synlint: shared
        self.max_errors = max(1, int(max_errors))
        self.errors_dropped = 0  # synlint: shared
        self._m_errors = _tm.counter("serving_errors_total", server=name)
        self._m_err_dropped = _tm.counter("serving_errors_dropped_total",
                                          server=name)
        self._m_shed = _tm.counter("serving_shed_total", server=name)
        self._m_score_s = _tm.histogram("serving_score_seconds",
                                        server=name)
        self.retry_transient = max(0, int(retry_transient))
        self.retry_backoff = float(retry_backoff)
        self._retry_rng = _retry_rng(retry_rng)
        self._m_deadline_shed = _tm.counter("serving_deadline_shed_total",
                                            server=name)
        self._m_retry = _tm.counter("serving_retry_total", server=name)
        self._m_bisect = _tm.counter("serving_poison_bisect_total",
                                     server=name)
        self._m_poison = _tm.counter("serving_poison_requests_total",
                                     server=name)
        # per-thread restart counters (supervision), registered lazily
        # like the per-status reply counters
        self._m_restarts: Dict[str, _tm.Counter] = {}

    def _record_error(self, exc: BaseException):
        self._m_errors.inc()
        with self._err_lock:
            if len(self.errors) >= self.max_errors:
                del self.errors[0]
                self.errors_dropped += 1
                self._m_err_dropped.inc()
            self.errors.append(repr(exc))
        _slog.log("error", "serving_error", server=self.name,
                  error=repr(exc)[:400])

    def _restart_counter(self, thread: str) -> "_tm.Counter":
        c = self._m_restarts.get(thread)
        if c is None:
            c = self._m_restarts.setdefault(thread, _tm.counter(
                "serving_thread_restarts_total", server=self.name,
                thread=thread))
        return c

    def _supervised(self, thread: str, fn: Callable, *args):
        """:func:`_supervise_loop` around one serving-stage loop: a
        dead scorer/reply/collector thread is recorded, counted, and
        restarted — never a silently wedged stage."""

        def on_restart(e: BaseException):
            self._record_error(e)
            self._restart_counter(thread).inc()
            _bb.record("thread_restart", level="error",
                       server=self.name, thread=thread,
                       error=repr(e)[:200])

        _supervise_loop(lambda: fn(*args), self._stop, on_restart)

    @property
    def url(self) -> str:
        return self.server.url

    def _score_only(self, batch: List[CachedRequest]):
        """Stage 2 of the pipeline: score one micro-batch WITHOUT sending
        replies. Returns ``(out_table, error)`` — exactly one is None.

        The batch's trace spans become the scorer thread's ambient span
        context for the duration of ``pipeline_fn``: any
        ``BatchedExecutor.submit`` the pipeline makes (ONNXModel et al.)
        captures them, so the executor's stage/compute/drain stages land
        on each request's span without any pipeline-fn API change."""
        t0 = time.monotonic()
        token = None
        if _tm.enabled():
            for cr in batch:
                if cr.drained:
                    cr.span.note("batch_form", t0 - cr.drained)
            token = _tm.set_current_spans(cr.span for cr in batch)
        try:
            _F_LAT_SCORE.fire()
            table = requests_to_table(batch)
            if self.parse_json:
                table = parse_request(table)
            return self.pipeline_fn(table), None
        except Exception as e:  # noqa: BLE001 - serving loop must survive
            self._record_error(e)
            return None, e
        finally:
            if token is not None:
                _tm.reset_current_spans(token)
            dt = time.monotonic() - t0
            self._m_score_s.observe(dt)
            if _SLOW_BATCH_S and dt > _SLOW_BATCH_S:
                _bb.record("slow_batch", level="warn",
                           server=self.name, seconds=round(dt, 6),
                           size=len(batch), stage="score",
                           rids=[cr.rid for cr in batch[:8]],
                           trace_ids=[cr.span.trace_id
                                      for cr in batch[:8]])

    def _reply_scored(self, batch: List[CachedRequest], out, err,
                      err_status: int = 500,
                      commit_epochs: Optional[List[int]] = None):
        """Stage 3: reply-send + exact epoch commits for one scored batch.
        A pipelined batch may merge several drain epochs (each already
        recorded for replay), so every distinct epoch is committed —
        exact commits, because concurrent workers finish epochs out of
        order and a cumulative commit of a later epoch would erase an
        earlier in-flight epoch's replay history. ``err_status`` is the
        reply code for a failed batch: 500 for pipeline errors, 400 for
        a poison request the bisection isolated. ``commit_epochs``
        overrides WHICH epochs commit (``()`` = none): bisection
        segments of one batch share epochs, so only the last segment
        commits them — committing per segment would prune replay
        history for requests still unreplied in sibling segments."""
        t0 = time.monotonic()
        try:
            if err is None:
                try:
                    _F_REPLY.fire()
                    send_replies(self.server, out, self.reply_col)
                    return
                except Exception as e:  # noqa: BLE001 - bad reply col etc.
                    self._record_error(e)
                    err = e
                    err_status = 500
            for cr in batch:
                self.server.reply_to(cr.rid, HTTPResponseData(
                    status_code=err_status,
                    reason=("bad request" if err_status == 400
                            else "pipeline error"),
                    entity=repr(err).encode()))
        finally:
            dt = time.monotonic() - t0
            for cr in batch:
                cr.span.note("reply", dt)
                cr.span.finish("ok" if err is None else "error")
            eps = (sorted({cr.epoch for cr in batch})
                   if commit_epochs is None else commit_epochs)
            for ep in eps:
                self.server.commit(ep, exact=True)

    def _shed_expired(self, batch: List[CachedRequest]
                      ) -> List[CachedRequest]:
        """Wasted-work elimination at batch-form time: a request whose
        deadline already passed gets 504 NOW — scoring it would burn
        device time on an answer nobody is waiting for. Returns the
        still-live remainder; epochs only covered by shed requests are
        committed here (shed requests are replied, so they are not
        replayable either way)."""
        now = time.monotonic()
        live: List[CachedRequest] = []
        expired: List[CachedRequest] = []
        for cr in batch:
            (expired if cr.deadline is not None and cr.deadline <= now
             else live).append(cr)
        if expired:
            self._m_deadline_shed.inc(len(expired))
            _bb.record("shed_deadline", level="warn", server=self.name,
                       n=len(expired),
                       rids=[cr.rid for cr in expired[:8]],
                       trace_ids=[cr.span.trace_id
                                  for cr in expired[:8]])
            # Retry-After rides the shed 504 too: a deadline-expired
            # request usually means the replica is saturated — backing
            # off beats an immediate re-hammer that will expire again
            hdrs = {"Retry-After": self.server._retry_after_value()}
            for cr in expired:
                self.server.reply_to(cr.rid, HTTPResponseData(
                    status_code=504, reason="deadline exceeded before "
                    "scoring", headers=hdrs))
                cr.span.finish("shed")
            live_eps = {cr.epoch for cr in live}
            for ep in sorted({cr.epoch for cr in expired} - live_eps):
                self.server.commit(ep, exact=True)
        return live

    def _bisect_score(self, batch: List[CachedRequest]):
        """Poison isolation: recursively re-score halves (log2 n levels)
        until the failing request(s) are singletons. Healthy halves
        reply 200 with their real scores; an isolated poison request
        replies 400 — one bad payload no longer fails its neighbors."""
        out, err = self._score_only(batch)
        if err is None:
            return [(batch, out, None, 200)]
        if isinstance(err, PipelineBrokenError):
            # the pipeline died MID-bisection: that is transient
            # infrastructure failure, not a poison payload — 500, never
            # a client-blaming 400, and stop burning re-scores against
            # a dead pipeline
            return [(batch, None, err, 500)]
        if len(batch) == 1:
            # confirm before blaming the client: under probabilistic
            # faults (chaos) a TRANSIENT failure can land on a healthy
            # singleton's re-score — one more score must fail too
            # before this counts as poison; a flake scores 200
            out, err2 = self._score_only(batch)
            if err2 is None:
                return [(batch, out, None, 200)]
            if isinstance(err2, PipelineBrokenError):
                return [(batch, None, err2, 500)]
            self._m_poison.inc()
            _bb.record("poison_isolated", rid=batch[0].rid,
                       level="warn", server=self.name,
                       error=repr(err2)[:200])
            return [(batch, None, err2, 400)]
        mid = len(batch) // 2
        return (self._bisect_score(batch[:mid])
                + self._bisect_score(batch[mid:]))

    def _score_resilient(self, batch: List[CachedRequest]):
        """Score one micro-batch through the full degradation ladder:
        (1) a transient :class:`PipelineBrokenError` (an executor
        pipeline thread died; supervision restarts it) gets
        ``retry_transient`` bounded retries with jittered backoff;
        (2) any other error on a batch of n>1 is bisected to isolate
        the poison request(s); (3) what remains fails with its status.
        Returns ``[(sub_batch, out, err, err_status, commit_epochs),
        ...]`` segments ready for :meth:`_reply_scored` — only the LAST
        segment carries the batch's epochs to commit, so an epoch's
        replay history is never pruned while sibling segments are still
        unreplied (segments reply in order on one thread)."""
        segments = self._score_segments(batch)
        eps = sorted({cr.epoch for cr in batch})
        return [(b, o, e, st, eps if i == len(segments) - 1 else ())
                for i, (b, o, e, st) in enumerate(segments)]

    def _score_segments(self, batch: List[CachedRequest]):
        out, err = self._score_only(batch)
        for _ in range(self.retry_transient):
            if not isinstance(err, PipelineBrokenError):
                break
            self._m_retry.inc()
            time.sleep(self.retry_backoff
                       * (0.5 + self._retry_rng.random()))
            out, err = self._score_only(batch)
        if err is None:
            return [(batch, out, None, 200)]
        if isinstance(err, PipelineBrokenError) or len(batch) == 1:
            # still-broken pipeline fails the whole batch (bisecting
            # would just re-fail against the same dead pipeline)
            return [(batch, None, err, 500)]
        self._m_bisect.inc()
        _bb.record("poison_bisect", level="warn", server=self.name,
                   size=len(batch), error=repr(err)[:200],
                   rids=[cr.rid for cr in batch[:8]])
        mid = len(batch) // 2
        return (self._bisect_score(batch[:mid])
                + self._bisect_score(batch[mid:]))

    def _score_batch(self, batch: List[CachedRequest]):
        """Score + reply inline (the strictly serial path)."""
        batch = self._shed_expired(batch)
        if not batch:
            return
        for seg in self._score_resilient(batch):
            self._reply_scored(*seg)

    def _loop(self):
        while not self._stop.is_set():
            _F_KILL_SCORER.fire()
            batch = self.server.get_batch(self.max_batch, timeout=0.05,
                                          linger=self.batch_linger,
                                          coalesce=self.batch_coalesce)
            if not batch:
                continue
            self._score_batch(batch)

    def _fail_batch(self, batch: List[CachedRequest], status: int = 503,
                    reason: str = "server stopping"):
        """Fast-fail a drained-but-unscored batch (shutdown path): the
        clients would otherwise block until reply_timeout."""
        self._m_shed.inc(len(batch))
        for cr in batch:
            self.server.reply_to(cr.rid, HTTPResponseData(
                status_code=status, reason=reason))
            cr.span.finish("shed")
        for ep in sorted({cr.epoch for cr in batch}):
            self.server.commit(ep, exact=True)

    def _collect_loop(self, handoff: "queue.Queue"):
        """Stage 1: drain + linger concurrently with device scoring.
        While the scorer holds the handoff slot, the wait itself becomes
        extra coalescing time — the linger adapts to the service rate
        instead of being a fixed prepaid delay."""
        while not self._stop.is_set():
            _F_KILL_COLLECT.fire()
            batch = self.server.get_batch(self.max_batch, timeout=0.05,
                                          linger=self.batch_linger,
                                          coalesce=self.batch_coalesce)
            if not batch:
                continue
            placed = False
            while not self._stop.is_set():
                try:
                    handoff.put(batch, timeout=0.05)
                    placed = True
                    break
                except queue.Full:
                    if len(batch) < self.max_batch:
                        batch.extend(self.server.get_batch(
                            self.max_batch - len(batch), timeout=0.001))
            if not placed:
                # stop() raced us while the batch was in hand: it can't
                # see this batch in the handoff, so fail it here
                self._fail_batch(batch)

    def _score_loop(self, handoff: "queue.Queue"):
        """Stage 2: score, then hand the scored batch to the reply
        thread — the scorer starts on batch k+1 while batch k's replies
        serialize and its epochs commit on the reply thread."""
        while not self._stop.is_set():
            _F_KILL_SCORER.fire()
            try:
                batch = handoff.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = self._shed_expired(batch)
            if not batch:
                continue
            segments = self._score_resilient(batch)
            rq = self._reply_q
            if rq is None or self._stop.is_set():
                # reply stage not running — or stop() raced a long score
                # and the reply thread may already have exited: reply
                # inline so the batch's clients never hang
                for seg in segments:
                    self._reply_scored(*seg)
                continue
            for seg in segments:
                rq.put(seg)
            if self._stop.is_set():
                # stop() landed between the check and the put — the
                # reply thread may have seen an empty queue and exited
                # (or be about to). Wait out its exit, then drain any
                # leftovers here (get_nowait races with stop()'s own
                # drain safely — each item is taken once)
                self._reply_thread.join(timeout=10)
                while True:
                    try:
                        item = rq.get_nowait()
                    except queue.Empty:
                        break
                    self._reply_scored(*item)

    def _reply_loop(self):
        """Stage 3: reply-send + commits off the scoring threads. Exits
        only once stopped AND drained, so scored batches always reply."""
        while True:
            _F_KILL_REPLY.fire()
            try:
                item = self._reply_q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            self._reply_scored(*item)

    def _pipelined_loop(self):
        handoff: "queue.Queue[List[CachedRequest]]" = queue.Queue(
            maxsize=self.scoring_workers)
        self._handoff = handoff
        # bounded: a stalled reply sink backpressures scoring instead of
        # queueing scored-but-unreplied batches without limit
        self._reply_q = queue.Queue(maxsize=max(2, 2 * self.scoring_workers))
        # every stage thread runs under _supervised: a dead scorer/
        # reply/collector thread restarts (counted) instead of silently
        # wedging its stage of the pipeline
        self._reply_thread = threading.Thread(
            target=self._supervised, args=("reply", self._reply_loop),
            name=f"serving-reply-{self.name}", daemon=True)
        self._reply_thread.start()
        self._collector = threading.Thread(
            target=self._supervised,
            args=("collector", self._collect_loop, handoff),
            name=f"serving-collect-{self.name}", daemon=True)
        self._collector.start()
        for i in range(self.scoring_workers - 1):
            t = threading.Thread(
                target=self._supervised,
                args=("scorer", self._score_loop, handoff),
                name=f"serving-score-{self.name}-{i + 1}", daemon=True)
            t.start()
            self._extra_scorers.append(t)
        self._supervised("scorer", self._score_loop, handoff)

    def start(self) -> "ContinuousServer":
        target = (self._pipelined_loop if self.pipelined
                  else lambda: self._supervised("scorer", self._loop))
        # synlint: disable=RL001 - both branches of `target` run under
        # supervision: _pipelined_loop spawns _pipeline_thread stages,
        # the scorer lambda wraps _loop in _supervised
        self._thread = threading.Thread(
            target=target, name=f"serving-query-{self.name}", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout_ms: float = 5000.0) -> bool:
        """Graceful drain — delegates to :meth:`WorkerServer.drain`
        (the SIGTERM half of a k8s rolling restart; ``main()`` calls
        this on signal, then :meth:`stop`)."""
        return self.server.drain(timeout_ms)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._collector is not None:
            self._collector.join(timeout=5)
        for t in self._extra_scorers:
            t.join(timeout=5)
        # scorers are down: the reply thread drains what they queued and
        # exits (scored batches get their real replies, not 503s)
        if self._reply_thread is not None:
            self._reply_thread.join(timeout=5)
        if self._reply_q is not None:
            while True:
                try:
                    item = self._reply_q.get_nowait()
                except queue.Empty:
                    break
                self._reply_scored(*item)
        # batches parked in the handoff when the scorers exited would
        # leave their clients blocked until reply_timeout: fail them
        # fast with 503 (the old serial loop always finished its batch)
        if self._handoff is not None:
            while True:
                try:
                    batch = self._handoff.get_nowait()
                except queue.Empty:
                    break
                self._fail_batch(batch)
        HTTPSourceStateHolder.remove(self.name)


def _model_pipeline(model_path: str, devices=None, cache_dir=None,
                    tensor_parallel=1, partition_rules=None):
    """JSON {"features": [...]} -> ONNX-scored reply — the deployment
    entry's default pipeline (tools/k8s/chart serving template).
    ``devices`` dp-shards each scored micro-batch across that many chips
    (ONNXModel.devices -> runtime/executor.py); ``tensor_parallel`` > 1
    splits them into a dp×tp mesh with the weights placed over tp by the
    partition-rule registry (parallel/partition_rules.py) —
    ``partition_rules`` forwards per-model overrides or the 'megatron'
    preset; ``cache_dir`` enables the persistent compile cache +
    executable store (--cache-dir / SYNAPSEML_COMPILE_CACHE). Returns
    ``(pipeline, model)`` so ``main`` can drive ``model.warmup`` before
    the readiness gate."""
    import numpy as np

    from synapseml_tpu.onnx import ONNXModel
    from synapseml_tpu.runtime import compile_cache as _cc

    model = ONNXModel(model_path=model_path)
    if devices is not None:
        model.set(devices=devices)
    if tensor_parallel and int(tensor_parallel) > 1:
        model.set(tensor_parallel=int(tensor_parallel))
    if partition_rules is not None:
        model.set(partition_rules=partition_rules)
    if cache_dir is not None:
        model.set(compile_cache_dir=cache_dir)
    # every capture record carries the scoring model's content hash
    # (the compile-cache key ingredient): tools/replay.py recomputes
    # the same hash over the model file it is handed and refuses a
    # mismatch — replaying yesterday's incident against today's
    # weights would "diverge" meaninglessly
    _cap.set_model_hash(_cc.content_hash(model.model_payload or b""))
    feed = model.graph.input_names[0]
    # cast features to the graph's DECLARED input dtype — token-id
    # models (the tensor-parallel transformer smoke) feed int32/int64,
    # not float32; unknown/absent dtype keeps the float32 default
    feed_dtype, _ = getattr(model.graph, "input_info", {}).get(feed) \
        or (None, None)
    feed_np = np.dtype(feed_dtype) if feed_dtype is not None else np.float32

    def pipeline(table: Table) -> Table:
        feats = np.stack([np.asarray(v["features"], feed_np)
                          for v in table["value"]])
        scored = model.transform(Table({feed: feats},),)
        first_out = model.graph.output_names[0]
        replies = np.empty(table.num_rows, dtype=object)
        out_col = np.asarray(scored[first_out])
        for i in range(table.num_rows):
            replies[i] = make_reply({"output": out_col[i].tolist()})
        return table.with_column("reply", replies)

    # ONNXModel resolves feed_dict lazily; set it for the raw-name feed
    model.set(feed_dict={feed: feed})
    return pipeline, model


def main(argv=None):
    """``python -m synapseml_tpu.io.serving`` — the container entry the
    k8s serving chart runs: load SYNAPSEML_MODEL_PATH (or echo when
    unset), serve on --port with /health, block until signalled."""
    import argparse
    import os
    import signal

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8898)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--model", default=os.environ.get(
        "SYNAPSEML_MODEL_PATH"))
    ap.add_argument("--name", default="serving")
    ap.add_argument("--devices", default=os.environ.get(
        "SYNAPSEML_DEVICES"),
        help="data-parallel device spec: 'all' or an int chip count; "
             "unset = single device")
    ap.add_argument("--tensor-parallel", type=int,
                    default=int(os.environ.get(
                        "SYNAPSEML_TENSOR_PARALLEL", "1")),
        help="tensor-parallel ways: >1 splits --devices into a dp×tp "
             "mesh — weights are placed over tp by the partition-rule "
             "registry so the model need not fit one chip's HBM; "
             "replies stay byte-identical to tensor-parallel=1 under "
             "the default rules. Must divide the device count; "
             "requires --devices")
    ap.add_argument("--partition-rules", default=os.environ.get(
        "SYNAPSEML_PARTITION_RULES") or None,
        help="partition-rule overrides for --tensor-parallel: "
             "'megatron' (full column preset: max memory headroom, "
             "~1e-6 cross-shard drift breaks replay digests across "
             "reshardings) or a JSON list of [regex, axes] pairs "
             "matched ahead of the default reduction-free layout")
    ap.add_argument("--decode", action="store_true",
                    default=bool(os.environ.get("SYNAPSEML_DECODE", "")),
        help="decode serving mode: POST /generate admits autoregressive "
             "sequences into the continuous-batching scheduler "
             "(runtime/decode.py) with a paged device-resident KV cache "
             "— requires --model pointing at a share-buffer decoder "
             "graph (past_key/past_value + seqlens_k inputs). Geometry "
             "and capacity ride the SYNAPSEML_DECODE_*/SYNAPSEML_KV_* "
             "env knobs (docs/knobs.md); per-request max_new_tokens in "
             "the body, deadline via X-Deadline-Ms. The '/' scoring "
             "path serves echo in this mode")
    ap.add_argument("--coalesce-ms", type=float, default=float(os.environ.get(
        "SYNAPSEML_COALESCE_MS", "0")),
        help="deadline-based batching window in ms (0 = off)")
    ap.add_argument("--deadline-ms", type=float, default=float(os.environ.get(
        "SYNAPSEML_DEADLINE_MS", "0")),
        help="default per-request deadline in ms (clients override via "
             "the X-Deadline-Ms header); a request already expired at "
             "batch-form time is shed 504 before scoring. 0 = none")
    ap.add_argument("--max-queue", type=int, default=int(os.environ.get(
        "SYNAPSEML_MAX_QUEUE", "0")),
        help="admission control: shed requests 429 at enqueue once this "
             "many are already queued (0 = unbounded)")
    ap.add_argument("--drain-timeout-ms", type=float,
                    default=float(os.environ.get(
                        "SYNAPSEML_DRAIN_TIMEOUT_MS", "5000")),
        help="graceful-drain budget on SIGTERM: new requests get 503 + "
             "Retry-After immediately, accepted requests get up to this "
             "long to finish to a real reply before the process exits "
             "(k8s terminationGracePeriodSeconds must exceed it)")
    ap.add_argument("--cache-dir", default=os.environ.get(
        "SYNAPSEML_COMPILE_CACHE") or None,
        help="persistent compile-cache directory (mount a volume here so "
             "restarted replicas deserialize executables instead of "
             "recompiling); unset = off")
    ap.add_argument("--warmup", default=os.environ.get(
        "SYNAPSEML_WARMUP", ""),
        help="AOT-compile model buckets before going ready: 'auto' "
             "(the executor's full bucket ladder) or comma-separated "
             "bucket sizes; empty = no warmup. /health answers 503 "
             "until warmup completes, so traffic never lands on a "
             "compiling chip")
    ap.add_argument("--log", default=os.environ.get(
        "SYNAPSEML_LOG", ""),
        help="structured-log emission: 'json' (JSON lines on stderr), "
             "'text', or '0'/empty = silent (docs/observability.md, "
             "'Structured log schema')")
    ap.add_argument("--dump-dir", default=os.environ.get(
        "SYNAPSEML_DUMP_DIR") or None,
        help="flight-recorder dump directory (breaker trips, pipeline "
             "breaks, and SIGUSR2 snapshot ring+stacks+gauges here); "
             "default: <tmpdir>/synapseml_flight")
    args = ap.parse_args(argv)
    try:
        _slog.set_mode(args.log.strip().lower())
    except ValueError as e:
        print(f"error: --log {args.log!r}: {e}", flush=True)
        return 2
    if args.dump_dir:
        _bb.set_dump_dir(args.dump_dir)
    # kill -USR2 <pid> snapshots the flight recorder to the dump dir —
    # the operator's "what is this replica doing right now" surface
    _bb.install_signal_trigger()
    devices = args.devices or None  # unset env var arrives as ""
    if devices is not None:
        # fail fast on a bad spec — discovering it per request would
        # leave a "healthy" pod 500-ing every score (the same silent
        # degrade the missing-model check below exists to prevent)
        from synapseml_tpu.runtime.executor import resolve_devices
        try:
            if devices != "all":
                devices = int(devices)
            resolve_devices(devices)
        except ValueError as e:
            print(f"error: --devices {args.devices!r}: {e}", flush=True)
            return 2
    tp = int(args.tensor_parallel or 1)
    if tp > 1:
        # same fail-fast contract as --devices: a tp spec the mesh
        # cannot satisfy must kill the pod at boot, not 500 per score
        if devices is None:
            print("error: --tensor-parallel > 1 requires --devices",
                  flush=True)
            return 2
        from synapseml_tpu.runtime.executor import resolve_devices
        n = len(resolve_devices(devices))
        if n % tp:
            print(f"error: --tensor-parallel {tp} does not divide the "
                  f"{n}-device pool", flush=True)
            return 2
    partition_rules = args.partition_rules
    if partition_rules and partition_rules != "megatron":
        try:
            partition_rules = json.loads(partition_rules)
            if not isinstance(partition_rules, list):
                raise ValueError("expected a JSON list of [regex, axes]")
        except ValueError as e:
            print(f"error: --partition-rules {args.partition_rules!r}: "
                  f"{e}", flush=True)
            return 2

    if args.model and not os.path.exists(args.model):
        # a configured-but-missing model must NOT silently degrade to
        # echo: the pod would go Ready and serve request bodies as
        # "scores" — fail fast so k8s restarts against the mounted model
        print(f"error: model path {args.model!r} does not exist",
              flush=True)
        return 2
    if args.decode and not args.model:
        print("error: --decode requires --model (a share-buffer "
              "decoder graph)", flush=True)
        return 2
    model = None
    decode_sched = None
    if args.decode:
        from synapseml_tpu.onnx.importer import import_model
        from synapseml_tpu.runtime import compile_cache as _cc
        from synapseml_tpu.runtime.decode import DecodeScheduler

        with open(args.model, "rb") as f:
            payload = f.read()
        # replay refuses a model-hash mismatch — decode captures carry
        # the same fingerprint the scoring path stamps
        _cap.set_model_hash(_cc.content_hash(payload))
        graph = import_model(payload)
        decode_sched = DecodeScheduler(
            graph, name=args.name, cache_dir=args.cache_dir,
            cache_key=_cc.content_hash(payload))

        def pipeline(table: Table) -> Table:
            replies = np.empty(table.num_rows, dtype=object)
            for i, v in enumerate(table["value"]):
                replies[i] = make_reply(v)
            return table.with_column("reply", replies)
        what = (f"decode {args.model} [B={decode_sched.B} "
                f"S_pre={decode_sched.S_pre} page={decode_sched.page} "
                f"max_seq={decode_sched.max_seq} "
                f"kv_pages={decode_sched.kv.capacity_pages}]")
    elif args.model:
        pipeline, model = _model_pipeline(
            args.model, devices=devices, cache_dir=args.cache_dir,
            tensor_parallel=tp, partition_rules=partition_rules)
        what = f"scoring {args.model}"
        if devices is not None:
            what += f" [devices={devices}"
            what += f" tp={tp}]" if tp > 1 else "]"
    else:
        def pipeline(table: Table) -> Table:
            replies = np.empty(table.num_rows, dtype=object)
            for i, v in enumerate(table["value"]):
                replies[i] = make_reply(v)
            return table.with_column("reply", replies)
        what = "echo (no SYNAPSEML_MODEL_PATH)"

    do_warmup = bool(args.warmup) and model is not None
    # the server binds (and answers /health 503) BEFORE warmup: k8s sees
    # the pod alive-but-unready instead of probe-timing-out a silent one
    cs = ContinuousServer(args.name, pipeline, host=args.host,
                          port=args.port,
                          batch_coalesce=args.coalesce_ms / 1e3,
                          deadline_ms=args.deadline_ms or None,
                          max_queue=args.max_queue or None,
                          ready=not (do_warmup or decode_sched
                                     is not None))
    if decode_sched is not None:
        # decode warmup is NOT optional: every (S, T) signature plus
        # the merge/grow helpers must be compiled before the first
        # sequence, or steady-state steps land on a compiling chip and
        # the recompile sentinel fires
        # single-threaded startup: the readiness gate is still closed,
        # so no handler thread can read `decode` before this write
        cs.server.decode = decode_sched  # synlint: disable=CC001
        print(f"warming up [{what}] ...", flush=True)
        rep = decode_sched.warmup()
        decode_sched.start()
        print(f"warmup done: {len(rep['signatures'])} signatures",
              flush=True)
        cs.server.set_ready(True)
    if do_warmup:
        buckets = None if args.warmup == "auto" else \
            [int(b) for b in args.warmup.split(",") if b.strip()]
        print(f"warming up [{what}] buckets="
              f"{'auto' if buckets is None else buckets} "
              f"cache_dir={args.cache_dir!r} ...", flush=True)
        try:
            rep = model.warmup(buckets=buckets)
            print(f"warmup done: {rep!r}", flush=True)
        except Exception as e:  # noqa: BLE001 - degrade, never crash-loop
            # e.g. a graph input with dynamic non-batch dims warmup can't
            # synthesize: serve with lazy per-bucket compilation (today's
            # behavior) rather than CrashLoopBackOff the replica — the
            # cold-start optimization must never cost availability
            print(f"warmup skipped ({e!r}); serving with lazy "
                  "compilation", flush=True)
        cs.server.set_ready(True)
    cs.start()
    print(f"serving [{what}] on {cs.url} (GET /health ready)", flush=True)
    _slog.log("info", "server_start", server=args.name, url=cs.url,
              what=what, dump_dir=_bb.dump_dir())
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    # graceful lifecycle: readiness flips 503 (+ Retry-After on new
    # enqueues) IMMEDIATELY, accepted requests drain to real replies
    # under the deadline, then stop() 503s any stragglers explicitly —
    # a k8s rolling restart drops zero accepted requests
    print(f"SIGTERM: draining (budget {args.drain_timeout_ms:.0f}ms) ...",
          flush=True)
    drained = cs.drain(args.drain_timeout_ms)
    if decode_sched is not None:
        # in-flight sequences finish to real (streamed) replies under
        # the same budget; new /generate admissions were already shed
        # 503 by the drain gate
        drained = decode_sched.drain(
            args.drain_timeout_ms / 1e3) and drained
        decode_sched.close()
    print(f"drain {'complete' if drained else 'timed out'}; stopping",
          flush=True)
    cs.stop()
    # exact zero-drop accounting for the rolling-restart contract:
    # serving_requests_total counts every request the HTTP layer saw,
    # serving_replies_total counts every terminal reply (incremented
    # before the socket send, so a dead client still counts) — equal
    # numbers prove no admitted request exited without a reply. The
    # chaos CI sigterm check asserts on this line; client-side socket
    # errors can't distinguish a dropped admitted request from a
    # connection RST out of the never-accepted TCP backlog. Handler
    # threads woken by the drain/stop shed may not have been scheduled
    # yet when stop() returns (nothing joins daemon handlers), so wait
    # briefly for the counters to converge; a genuinely dropped request
    # has nothing left to wake it and still reports a mismatch
    def _accounting() -> Tuple[float, float]:
        counters = _tm.snapshot()["counters"]
        admitted = sum(v for k, v in counters.items()
                       if k.startswith("synapseml_serving_requests_total")
                       and f'server="{args.name}"' in k)
        replied = sum(v for k, v in counters.items()
                      if k.startswith("synapseml_serving_replies_total")
                      and f'server="{args.name}"' in k)
        return admitted, replied

    admitted, replied = _accounting()
    deadline = time.monotonic() + 2.0
    while admitted != replied and time.monotonic() < deadline:
        time.sleep(0.02)
        admitted, replied = _accounting()
    print(f"exit accounting: admitted={admitted:.0f} "
          f"replied={replied:.0f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
