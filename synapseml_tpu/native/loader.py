"""NativeLoader: build-on-demand + dlopen of the C++ host bridge.

Rebuild of the reference's NativeLoader
(ref: core/src/main/java/com/microsoft/ml/spark/core/env/NativeLoader.java:28-140
— extracts ``.so``/``.dll`` from jar resources into a temp dir and
``System.load``s them, OS-prefix aware). Here the artifact is built from
bundled C++ source with the system toolchain on first use and cached next
to the package (wheels could ship the prebuilt ``.so`` in the same slot);
``ctypes`` stands in for JNI. Everything degrades gracefully: callers
check :func:`available` and keep a pure-Python path.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

from synapseml_tpu.runtime.locksan import make_lock

logger = logging.getLogger("synapseml_tpu")

_SRC = os.path.join(os.path.dirname(__file__), "src", "synapse_native.cpp")
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_NAME = "libsynapse_native.so"
_ABI_VERSION = 1

_lock = make_lock("loader:_lock")
_state: dict = {"lib": None, "tried": False}


def _compile(out_path: str) -> bool:
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # build into a temp file then rename: concurrent processes race safely
    fd, tmp = tempfile.mkstemp(suffix=".so",
                               dir=os.path.dirname(out_path))
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native build unavailable: %s", e)
        os.unlink(tmp)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed: %s", proc.stderr[-2000:])
        os.unlink(tmp)
        return False
    os.replace(tmp, out_path)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f64p = ctypes.POINTER(ctypes.c_double)

    lib.synapse_abi_version.restype = ctypes.c_int
    lib.synapse_murmur3_32.restype = ctypes.c_uint32
    lib.synapse_murmur3_32.argtypes = [u8p, ctypes.c_uint64,
                                       ctypes.c_uint32]
    lib.synapse_murmur3_32_batch.restype = None
    lib.synapse_murmur3_32_batch.argtypes = [
        u8p, u64p, ctypes.c_uint64, ctypes.c_uint32, u32p]
    lib.synapse_parse_csv.restype = ctypes.c_uint64
    lib.synapse_parse_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char, f64p,
        ctypes.c_uint64, u64p]
    lib.synapse_unroll_chw.restype = None
    lib.synapse_unroll_chw.argtypes = [
        u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, f64p]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The dlopen entry point; returns None when no toolchain/artifact."""
    with _lock:
        if _state["tried"]:
            return _state["lib"]
        _state["tried"] = True
        path = os.path.join(_CACHE_DIR, _LIB_NAME)
        if not os.path.exists(path) and not _compile(path):
            return None
        try:
            lib = _bind(ctypes.CDLL(path))
            if lib.synapse_abi_version() != _ABI_VERSION:
                logger.warning("stale native build; recompiling")
                os.unlink(path)
                if not _compile(path):
                    return None
                lib = _bind(ctypes.CDLL(path))
            _state["lib"] = lib
        except OSError as e:
            logger.warning("native load failed: %s", e)
            _state["lib"] = None
        return _state["lib"]


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# numpy-facing wrappers
# ---------------------------------------------------------------------------

def murmur3_32(data: bytes, seed: int = 0) -> int:
    lib = load()
    if lib is None:
        raise RuntimeError("native bridge unavailable")
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
        else (ctypes.c_uint8 * 1)()
    return int(lib.synapse_murmur3_32(buf, len(data), seed & 0xFFFFFFFF))


def murmur3_32_batch(tokens, seed: int = 0) -> np.ndarray:
    """Hash a sequence of str/bytes tokens in one native call."""
    lib = load()
    if lib is None:
        raise RuntimeError("native bridge unavailable")
    blobs = [t.encode("utf-8") if isinstance(t, str) else bytes(t)
             for t in tokens]
    n = len(blobs)
    offsets = np.zeros(n + 1, np.uint64)
    for i, b in enumerate(blobs):
        offsets[i + 1] = offsets[i] + len(b)
    buffer = np.frombuffer(b"".join(blobs) or b"\x00", dtype=np.uint8).copy()
    out = np.zeros(n, np.uint32)
    lib.synapse_murmur3_32_batch(
        buffer.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, seed & 0xFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


def parse_csv_floats(text: bytes, delim: str = ",",
                     max_vals: Optional[int] = None):
    """(values[float64], n_rows) from delimiter-separated text; empty or
    non-numeric fields become NaN."""
    lib = load()
    if lib is None:
        raise RuntimeError("native bridge unavailable")
    if isinstance(text, str):
        text = text.encode("utf-8")
    cap = max_vals if max_vals is not None else text.count(
        delim.encode()) + text.count(b"\n") + 2
    out = np.zeros(cap, np.float64)
    rows = ctypes.c_uint64(0)
    n = lib.synapse_parse_csv(
        text, len(text), delim.encode()[0:1][0] if isinstance(delim, str)
        else delim,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), cap,
        ctypes.byref(rows))
    return out[:n], int(rows.value)


def unroll_chw(img: np.ndarray) -> np.ndarray:
    lib = load()
    if lib is None:
        raise RuntimeError("native bridge unavailable")
    arr = np.ascontiguousarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[..., None]
    h, w, c = arr.shape
    out = np.zeros(h * w * c, np.float64)
    lib.synapse_unroll_chw(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out
