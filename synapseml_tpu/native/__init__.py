"""Native host bridge: C++ hot loops behind ctypes, built on demand.

SURVEY.md §2.9: the reference's native layer (JNI jars + NativeLoader).
The TPU compute path is XLA; this layer accelerates host-side ingest and
hashing, with pure-Python fallbacks everywhere (check ``available()``).
"""
from synapseml_tpu.native.loader import (  # noqa: F401
    available,
    load,
    murmur3_32,
    murmur3_32_batch,
    parse_csv_floats,
    unroll_chw,
)
