// Native host bridge for synapseml_tpu.
//
// The reference ships prebuilt C++ engines behind JNI (SURVEY.md §2.9:
// lib_lightgbm, vw-jni, opencv — loaded by NativeLoader.java:28-140). The
// TPU compute path here is XLA, so the native layer covers the *host-side*
// hot loops instead: feature hashing over raw bytes (the JVM-side work of
// VowpalWabbitFeaturizer / HashingTF) and text ingest — exposed as a plain
// C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -shared -fPIC -o libsynapse_native.so synapse_native.cpp
// (done on demand by synapseml_tpu.native.loader, cached next to the
// source — the NativeLoader extract-and-dlopen analogue).

#include <cstdint>
#include <cstring>
#include <cstdlib>

extern "C" {

// ---------------------------------------------------------------------------
// MurmurHash3 x86_32 — bit-exact with synapseml_tpu.utils.hashing.murmur3_32
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

uint32_t synapse_murmur3_32(const uint8_t* data, uint64_t len, uint32_t seed) {
    const uint64_t nblocks = len / 4;
    uint32_t h = seed;
    const uint32_t c1 = 0xcc9e2d51u;
    const uint32_t c2 = 0x1b873593u;

    for (uint64_t i = 0; i < nblocks; i++) {
        uint32_t k;
        std::memcpy(&k, data + i * 4, 4);  // little-endian load
        k *= c1;
        k = rotl32(k, 15);
        k *= c2;
        h ^= k;
        h = rotl32(h, 13);
        h = h * 5 + 0xe6546b64u;
    }

    const uint8_t* tail = data + nblocks * 4;
    uint32_t k = 0;
    switch (len & 3) {
        case 3: k ^= (uint32_t)tail[2] << 16; [[fallthrough]];
        case 2: k ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
        case 1: k ^= (uint32_t)tail[0];
                k *= c1; k = rotl32(k, 15); k *= c2; h ^= k;
    }

    h ^= (uint32_t)len;
    h ^= h >> 16;
    h *= 0x85ebca6bu;
    h ^= h >> 13;
    h *= 0xc2b2ae35u;
    h ^= h >> 16;
    return h;
}

// Batch: hash n byte strings packed into one buffer with prefix offsets.
// offsets has n+1 entries; string i spans [offsets[i], offsets[i+1]).
void synapse_murmur3_32_batch(const uint8_t* buffer, const uint64_t* offsets,
                              uint64_t n, uint32_t seed, uint32_t* out) {
    for (uint64_t i = 0; i < n; i++) {
        out[i] = synapse_murmur3_32(buffer + offsets[i],
                                    offsets[i + 1] - offsets[i], seed);
    }
}

// ---------------------------------------------------------------------------
// Fast float CSV/TSV ingest (the SWIG chunked-array streaming analogue,
// SURVEY.md §3.1 HOT LOOP #1: row marshalling into native arrays)
// ---------------------------------------------------------------------------

// Parse up to max_vals doubles from delimiter-separated text. Returns the
// number of values written; *rows receives the number of newline-terminated
// rows consumed. Empty fields parse as NaN (missing), matching the
// engine's missing-value routing.
uint64_t synapse_parse_csv(const char* text, uint64_t len, char delim,
                           double* out, uint64_t max_vals, uint64_t* rows) {
    uint64_t nvals = 0;
    uint64_t nrows = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && nvals < max_vals) {
        const char* field_start = p;
        while (p < end && *p != delim && *p != '\n') p++;
        if (p == field_start) {
            out[nvals++] = __builtin_nan("");
        } else {
            char* parse_end = nullptr;
            double v = std::strtod(field_start, &parse_end);
            out[nvals++] = (parse_end == field_start)
                ? __builtin_nan("") : v;
        }
        if (p < end) {
            if (*p == '\n') nrows++;
            p++;
        }
    }
    // count a trailing row without a final newline
    if (len > 0 && text[len - 1] != '\n' && nvals > 0) nrows++;
    *rows = nrows;
    return nvals;
}

// ---------------------------------------------------------------------------
// UnrollImage: HWC uint8 -> CHW float64 (core/.../image/UnrollImage.scala
// layout), the per-image inner loop of the binary->vector path
// ---------------------------------------------------------------------------

void synapse_unroll_chw(const uint8_t* img, uint64_t h, uint64_t w,
                        uint64_t c, double* out) {
    for (uint64_t ch = 0; ch < c; ch++)
        for (uint64_t y = 0; y < h; y++)
            for (uint64_t x = 0; x < w; x++)
                out[ch * h * w + y * w + x] =
                    (double)img[(y * w + x) * c + ch];
}

int synapse_abi_version() { return 1; }

}  // extern "C"
