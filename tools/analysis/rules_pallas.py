"""PL rules: Pallas kernel hygiene.

PL001  pallas_call without a VMEM-budget guard in the wrapper
PL002  kernel wrapper with no interpret-mode parity test

TPU VMEM is ~16 MB/core and a ``pallas_call`` whose blocks exceed it
fails at *compile* time on hardware CI never sees (CPU CI runs
interpret mode). The discipline: the kernel module declares a budget
constant (name containing ``VMEM`` and ``BUDGET``) and every wrapper
that issues a ``pallas_call`` checks its block footprint against it —
PL001 fires when a wrapper references no such constant. PL002 walks
``tests/`` (fixture dirs excluded) for a file that names the wrapper
AND uses ``interpret`` — the parity test that keeps the kernel honest
off-TPU.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, List

from tools.analysis.engine import ModuleContext, Program, expr_text
from tools.analysis.findings import Finding

PACK = "pallas"

_BUDGET_RE = re.compile(r"VMEM.*BUDGET|BUDGET.*VMEM", re.IGNORECASE)


def _kernel_wrappers(ctx: ModuleContext) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for fn in ctx.nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        call_line = None
        checks_budget = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    expr_text(node.func).endswith("pallas_call"):
                call_line = call_line or node.lineno
            elif isinstance(node, ast.Name) and _BUDGET_RE.search(node.id):
                checks_budget = True
        if call_line is not None:
            out.append({"name": fn.name, "line": fn.lineno,
                        "call_line": call_line,
                        "checks_budget": checks_budget})
    return out


def summarize(ctx: ModuleContext) -> Dict[str, Any]:
    return {"kernels": _kernel_wrappers(ctx)}


def run_local(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for k in _kernel_wrappers(ctx):
        if not k["checks_budget"]:
            out.append(Finding(
                rule="PL001", path=ctx.relpath, line=k["call_line"],
                col=0, context=k["name"],
                message=f"pallas_call in {k['name']!r} without a VMEM "
                        "budget guard — declare a *_VMEM_BUDGET_* "
                        "constant and check the block footprint before "
                        "launching (OOM here fails at compile time, on "
                        "hardware CI never sees)"))
    return out


def _test_corpus(root: str) -> List[str]:
    """Text of every tests/*.py file (fixture trees excluded — a rule
    fixture naming a kernel is not a parity test)."""
    corpus: List[str] = []
    tests = os.path.join(root, "tests")
    for dirpath, dirs, files in os.walk(tests):
        dirs[:] = [d for d in dirs
                   if d not in ("fixtures", "__pycache__")]
        for fn in files:
            if fn.endswith(".py"):
                try:
                    with open(os.path.join(dirpath, fn),
                              encoding="utf-8") as fh:
                        corpus.append(fh.read())
                except OSError:  # pragma: no cover - racing deletion
                    continue
    return corpus


def run_global(prog: Program) -> List[Finding]:
    kernels = []
    for rel in sorted(prog.summaries):
        if rel.startswith("tests/"):
            continue
        pl = prog.summaries[rel].get(PACK)
        if pl:
            kernels.extend((rel, k) for k in pl.get("kernels", ()))
    if not kernels or not os.path.isdir(os.path.join(prog.root, "tests")):
        return []
    corpus = _test_corpus(prog.root)
    findings: List[Finding] = []
    for rel, k in kernels:
        name = k["name"]
        if name.startswith("_"):
            continue  # private helper; the public wrapper owns parity
        if any(name in text and "interpret" in text for text in corpus):
            continue
        findings.append(Finding(
            rule="PL002", path=rel, line=k["line"], col=0,
            context=name,
            message=f"kernel wrapper {name!r} has no interpret-mode "
                    "parity test under tests/ — add one (pallas_call("
                    "..., interpret=True) vs the reference "
                    "implementation) so CPU CI exercises the kernel"))
    return findings
