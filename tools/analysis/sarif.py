"""SARIF 2.1.0 output — the interchange format CI annotation UIs
ingest. Minimal and static: one run, one driver, stable rule ordering,
``partialFingerprints`` carrying the same line-independent fingerprint
the baseline uses (so an annotation survives unrelated edits exactly
as long as its baseline entry would)."""
from __future__ import annotations

import json
from typing import Dict, List, Sequence

from tools.analysis.findings import Finding

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings: List[Finding],
             notes: Sequence[Finding] = ()) -> Dict:
    """``notes`` are informational results (dynsan coverage
    annotations): same shape, SARIF level "note", and deliberately NOT
    part of the gate — they ride the report, not the exit code."""
    rules = sorted({f.rule for f in findings} | {f.rule for f in notes})
    results = []
    for f, level in [(f, "warning") for f in findings] + \
                    [(f, "note") for f in notes]:
        results.append({
            "ruleId": f.rule,
            "level": level,
            "message": {"text": f"{f.message} [{f.context}]"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
            "partialFingerprints": {"synlint/v1": f.fingerprint()},
        })
    return {
        "version": "2.1.0",
        "$schema": _SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "synlint",
                "informationUri": "docs/analysis.md",
                "rules": [{"id": r,
                           "shortDescription": {"text": r}}
                          for r in rules],
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def write_sarif(path: str, findings: List[Finding],
                notes: Sequence[Finding] = ()) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_sarif(findings, notes), fh, indent=1)
        fh.write("\n")
