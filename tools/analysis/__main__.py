"""CLI: ``python -m tools.analysis [paths] [--baseline F] [--fail-on-new]``.

Exit codes: 0 = clean (or every finding baselined under ``--fail-on-new``),
1 = findings (new findings under ``--fail-on-new``), 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tools.analysis.engine import analyze_paths
from tools.analysis.findings import (default_baseline_path, load_baseline,
                                     split_new, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="synlint: JAX-hygiene + concurrency static analysis "
                    "(rule catalog: docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: synapseml_tpu tools bench.py)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of intentionally-kept findings "
                         "(default: tools/analysis/baseline.json when it "
                         "exists)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 only for findings NOT in the baseline "
                         "(this is already the behavior whenever a "
                         "baseline is found; the flag documents intent "
                         "in CI invocations)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report and fail on every "
                         "finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as one JSON object on stdout")
    args = ap.parse_args(argv)

    paths = args.paths or ["synapseml_tpu", "tools", "bench.py"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"synlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    t0 = time.monotonic()
    findings = analyze_paths(paths)
    runtime_s = time.monotonic() - t0

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"synlint: wrote {len(findings)} findings to "
              f"{baseline_path}")
        return 0

    baseline = None
    if args.no_baseline:
        pass
    elif os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (json.JSONDecodeError, KeyError, OSError) as e:
            print(f"synlint: baseline {baseline_path} unreadable: {e}",
                  file=sys.stderr)
            return 2
    elif args.baseline:
        print(f"synlint: baseline {baseline_path} not found",
              file=sys.stderr)
        return 2

    if baseline is not None:
        new, matched = split_new(findings, baseline)
    else:
        new, matched = findings, 0

    if args.as_json:
        print(json.dumps({
            "findings_total": len(findings),
            "findings_new": len(new),
            "baselined": matched,
            "runtime_s": round(runtime_s, 3),
            "findings": [f.to_json() | {"line": f.line} for f in new],
        }))
    else:
        for f in new:
            print(f.render())
        tail = (f"synlint: {len(findings)} finding(s), {matched} "
                f"baselined, {len(new)} new "
                f"({runtime_s:.2f}s)")
        print(tail, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
