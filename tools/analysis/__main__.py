"""CLI: ``python -m tools.analysis [paths] [--fail-on-new] [...]``.

Exit codes: 0 = clean (or every finding baselined under ``--fail-on-new``),
1 = findings (new findings — or stale baseline entries — under
``--fail-on-new``), 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Set

from tools.analysis.cache import ResultCache, default_cache_path
from tools.analysis.engine import analyze_program, pack_of
from tools.analysis.findings import (default_baseline_path, load_baseline,
                                     load_baseline_entries, prune_baseline,
                                     split_new, stale_entries,
                                     write_baseline)
from tools.analysis.rules_env import KNOB_DOC, render_knob_table
from tools.analysis.sarif import write_sarif


def _changed_files(base: Optional[str]) -> Optional[Set[str]]:
    """Repo-relative paths changed vs ``base`` (plus untracked files),
    or None when git can't tell — an unknown diff must degrade to a
    full report, never to silence."""
    cmds = [["git", "diff", "--name-only", base or "HEAD", "--"],
            ["git", "ls-files", "--others", "--exclude-standard"]]
    changed: Set[str] = set()
    for cmd in cmds:
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30, check=True).stdout
        except (OSError, subprocess.SubprocessError):
            return None
        changed.update(line.strip() for line in out.splitlines()
                       if line.strip())
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="synlint: whole-program static analysis — JAX "
                    "hygiene, lock discipline, resource lifecycle, "
                    "error handling, env knobs, Pallas, and doc drift "
                    "(rule catalog: docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: synapseml_tpu tools bench.py)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of intentionally-kept findings "
                         "(default: tools/analysis/baseline.json when it "
                         "exists)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 only for findings NOT in the baseline, "
                         "and for stale baseline entries")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report and fail on every "
                         "finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose file/scope no "
                         "longer produces the finding, then exit 0")
    ap.add_argument("--cache", nargs="?", const=default_cache_path(),
                    default=None, metavar="FILE",
                    help="content-hash result cache (default location "
                         "when the flag is given bare: "
                         "./.synlint-cache.json)")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="GITREF",
                    help="report only findings in files changed vs "
                         "GITREF (default HEAD); the whole repo is "
                         "still analyzed so cross-module rules stay "
                         "sound")
    ap.add_argument("--observed", default=None, metavar="PATH",
                    help="locksan observed-graph artifact (a JSON file, "
                         "or a directory of locksan-*.json): cross-check "
                         "the runtime lock-order graph against the "
                         "static CC002 model (DS rules); coverage "
                         "annotations ride --json/--sarif as notes")
    ap.add_argument("--sarif", default=None, metavar="FILE",
                    help="also write findings (post-baseline) as SARIF "
                         "2.1.0 for CI annotations")
    ap.add_argument("--write-knob-table", action="store_true",
                    help=f"regenerate {KNOB_DOC} from the analyzed env "
                         "reads (Description column preserved) and "
                         "exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as one JSON object on stdout")
    args = ap.parse_args(argv)

    paths = args.paths or ["synapseml_tpu", "tools", "bench.py"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"synlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    cache = ResultCache(args.cache) if args.cache else None
    t0 = time.monotonic()
    findings, prog, stats = analyze_program(paths, cache=cache)
    runtime_s = time.monotonic() - t0
    if cache is not None:
        cache.save()

    coverage: List = []
    dynsan_stats: Optional[dict] = None
    if args.observed:
        from tools.analysis.rules_dynsan import cross_check, load_artifacts
        try:
            arts = load_artifacts(args.observed)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"synlint: --observed {args.observed}: {e}",
                  file=sys.stderr)
            return 2
        ds_findings, coverage = cross_check(prog, arts)
        ds_findings = [f for f in ds_findings
                       if not prog.suppressed(f.path, f.line, f.rule)]
        observed_edges = sum(len(a.get("edges", ())) for a in arts)
        dynsan_stats = {
            "artifacts": len(arts),
            "observed_edges": observed_edges,
            "model_gaps": sum(1 for f in ds_findings
                              if f.rule == "DS001"),
            "runtime_findings": sum(1 for f in ds_findings
                                    if f.rule != "DS001"),
            "coverage_gaps": len(coverage),
        }
        findings = sorted(findings + ds_findings,
                          key=lambda f: (f.path, f.line, f.rule))

    if args.write_knob_table:
        doc_path = os.path.join(prog.root, KNOB_DOC)
        existing = ""
        if os.path.exists(doc_path):
            with open(doc_path, encoding="utf-8") as fh:
                existing = fh.read()
        with open(doc_path, "w", encoding="utf-8") as fh:
            fh.write(render_knob_table(prog, existing))
        print(f"synlint: wrote {KNOB_DOC}")
        return 0

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"synlint: wrote {len(findings)} findings to "
              f"{baseline_path}")
        return 0
    if args.prune_baseline:
        if not os.path.exists(baseline_path):
            print(f"synlint: baseline {baseline_path} not found",
                  file=sys.stderr)
            return 2
        dropped = prune_baseline(baseline_path, findings,
                                 prog.summaries, prog.root)
        for entry in dropped:
            print(f"pruned: {entry['rule']} {entry['path']} "
                  f"[{entry['context']}]")
        print(f"synlint: pruned {len(dropped)} stale baseline "
              f"entr{'y' if len(dropped) == 1 else 'ies'}")
        return 0

    baseline = None
    stale: List[dict] = []
    if args.no_baseline:
        pass
    elif os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
            stale = stale_entries(load_baseline_entries(baseline_path),
                                  findings, prog.summaries, prog.root)
        except (json.JSONDecodeError, KeyError, OSError) as e:
            print(f"synlint: baseline {baseline_path} unreadable: {e}",
                  file=sys.stderr)
            return 2
    elif args.baseline:
        print(f"synlint: baseline {baseline_path} not found",
              file=sys.stderr)
        return 2

    if baseline is not None:
        new, matched = split_new(findings, baseline)
    else:
        new, matched = findings, 0

    if args.changed_only is not None:
        changed = _changed_files(args.changed_only)
        if changed is not None:
            new = [f for f in new if f.path in changed]

    if args.sarif:
        write_sarif(args.sarif, new, coverage)

    packs: dict = {}
    for f in findings:
        packs[pack_of(f.rule)] = packs.get(pack_of(f.rule), 0) + 1

    if args.as_json:
        print(json.dumps({
            "findings_total": len(findings),
            "findings_new": len(new),
            "baselined": matched,
            "stale_baseline": len(stale),
            "packs": packs,
            "cache": stats,
            "runtime_s": round(runtime_s, 3),
            "findings": [f.to_json() for f in new],
            **({"dynsan": {**dynsan_stats,
                           "coverage": [f.to_json() for f in coverage]}}
               if dynsan_stats is not None else {}),
        }))
    else:
        for f in new:
            print(f.render())
        if dynsan_stats is not None:
            print(f"dynsan: {dynsan_stats['artifacts']} artifact(s), "
                  f"{dynsan_stats['observed_edges']} observed edge(s), "
                  f"{dynsan_stats['model_gaps']} model gap(s), "
                  f"{dynsan_stats['runtime_findings']} runtime "
                  f"finding(s), {dynsan_stats['coverage_gaps']} static "
                  "edge(s) never observed", file=sys.stderr)
        for entry in stale:
            print(f"stale baseline entry: {entry['rule']} "
                  f"{entry['path']} [{entry['context']}] — run "
                  "--prune-baseline", file=sys.stderr)
        cache_note = (f", cache {stats['cache_hits']}/{stats['files']} "
                      "hits" if args.cache else "")
        print(f"synlint: {len(findings)} finding(s), {matched} "
              f"baselined, {len(new)} new{cache_note} "
              f"({runtime_s:.2f}s)", file=sys.stderr)
    if new:
        return 1
    if stale and args.fail_on_new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
