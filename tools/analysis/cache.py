"""Content-hash result cache for the analyzer.

One JSON file maps relpath -> {source hash, module summary, local
findings}. A hit skips parsing and every local rule for that file; the
summary still joins the whole-program pass, so cross-module rules run
over the full repo every time (they are cheap — the expensive part is
the per-file AST work).

The cache version is a hash of the analyzer's own sources: editing any
rule invalidates every entry, so a stale cache can never mask a new
rule's findings. Writes are tmp-then-``os.replace`` (the discipline
RL003 enforces elsewhere).
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

from tools.analysis.findings import Finding, from_json

CACHE_FORMAT = 1


def default_cache_path() -> str:
    return os.path.join(os.getcwd(), ".synlint-cache.json")


def analyzer_version() -> str:
    """Hash of every tools/analysis/*.py source, so rule edits
    invalidate the cache."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1(str(CACHE_FORMAT).encode())
    for name in sorted(os.listdir(here)):
        if name.endswith(".py"):
            with open(os.path.join(here, name), "rb") as fh:
                h.update(name.encode())
                h.update(fh.read())
    return h.hexdigest()[:16]


def _source_hash(source: str) -> str:
    return hashlib.sha1(source.encode()).hexdigest()[:16]


class ResultCache:
    def __init__(self, path: str, version: Optional[str] = None):
        self.path = path
        self.version = version or analyzer_version()
        self.entries: Dict[str, Dict] = {}
        self.dirty = False
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
            if data.get("version") == self.version:
                self.entries = data.get("entries", {})
        except (OSError, ValueError):
            pass  # absent/corrupt cache = cold cache

    def lookup(self, rel: str, source: str
               ) -> Optional[Tuple[Dict, List[Finding]]]:
        entry = self.entries.get(rel)
        if entry is None or entry.get("hash") != _source_hash(source):
            return None
        return entry["summary"], [from_json(d)
                                  for d in entry["findings"]]

    def store(self, rel: str, source: str, summary: Dict,
              findings: List[Finding]) -> None:
        self.entries[rel] = {"hash": _source_hash(source),
                             "summary": summary,
                             "findings": [f.to_json() for f in findings]}
        self.dirty = True

    def save(self) -> None:
        if not self.dirty:
            return
        payload = {"version": self.version, "entries": self.entries}
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
