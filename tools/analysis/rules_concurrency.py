"""CC rules: lock discipline across the package's threading sites.

CC001  shared field written without holding a lock
CC002  inconsistent lock acquisition order (potential deadlock)
CC003  blocking call while holding a lock

Model (heuristic, lexical — documented in docs/analysis.md):

- *Thread entries* are functions referenced as ``threading.Thread(
  target=...)``. Anything reachable from an entry through same-module
  calls (matched by bare/attribute name — over-approximate on purpose)
  runs off the creating thread.
- A write is *guarded* when it sits lexically inside a ``with <lock>:``
  block; lock-ness is detected from ``threading.Lock()``/``RLock()``
  assignments plus a name heuristic ("lock" in the identifier).
- A field is *shared* when written (outside ``__init__``) from two or
  more functions at least one of which is thread-reachable, or when its
  declaration carries a ``# synlint: shared`` annotation — the registry
  for fields whose sharing the call graph cannot see (cross-object
  handoffs, fields mutated through a non-``self`` receiver).
- Fields holding intrinsically thread-safe objects (``queue.Queue``,
  ``threading.Event``/``Semaphore``/locks) are exempt.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.engine import ModuleContext, expr_name, expr_text
from tools.analysis.findings import Finding

_LOCK_CTORS = re.compile(r"threading\.(R?Lock|Condition)\b|\b(R?Lock)\(\)")
_THREADSAFE_CTORS = re.compile(
    r"(queue|_queue)\.(Lifo|Priority)?Queue\(|threading\.(Event|Semaphore|"
    r"BoundedSemaphore|Barrier|R?Lock|Condition)\(|Event\(\)|Semaphore\(")
_MUTATION_METHODS = {"append", "appendleft", "extend", "insert", "remove",
                     "pop", "popleft", "popitem", "clear", "update", "add",
                     "discard", "setdefault"}
_BLOCKING_ATTRS = {"result", "sleep", "block_until_ready",
                   "device_get", "recv", "accept", "connect",
                   "sendall", "readline", "urlopen", "wait"}


class _Write:
    __slots__ = ("receiver", "attr", "fn", "node", "guarded", "in_init")

    def __init__(self, receiver: str, attr: str, fn: str, node: ast.AST,
                 guarded: bool, in_init: bool):
        self.receiver = receiver
        self.attr = attr
        self.fn = fn
        self.node = node
        self.guarded = guarded
        self.in_init = in_init


def _collect_lock_names(ctx: ModuleContext) -> Set[str]:
    names: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _LOCK_CTORS.search(expr_text(node.value)):
                for t in node.targets:
                    names.add(expr_name(t))
    return names


def _is_lock_expr(node: ast.AST, lock_names: Set[str]) -> bool:
    name = expr_name(node)
    return name in lock_names or "lock" in name.lower()


def _lock_id(node: ast.AST, cls: Optional[str]) -> str:
    """Lock identity for order tracking: class-qualified for ``self``
    receivers so two classes' ``_lock`` fields don't alias."""
    name = expr_name(node)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self" \
            and cls:
        return f"{cls}.{name}"
    return name


def _thread_entries(ctx: ModuleContext) -> Set[str]:
    entries: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Call) and \
                expr_text(node.func).endswith("Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    entries.add(expr_name(kw.value))
    return entries


def _call_graph(ctx: ModuleContext) -> Dict[str, Set[str]]:
    """fn-name -> names it calls (bare and attribute names). Name-based:
    cross-class collisions over-approximate reachability, which errs
    toward reporting — the right direction for a race detector."""
    graph: Dict[str, Set[str]] = {}
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            called: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    called.add(expr_name(sub.func))
                elif isinstance(sub, ast.Attribute):
                    # method handed around as a value (callbacks, targets)
                    called.add(sub.attr)
            graph.setdefault(node.name, set()).update(called)
    return graph


def _reachable(entries: Set[str], graph: Dict[str, Set[str]]) -> Set[str]:
    seen: Set[str] = set()
    frontier = [e for e in entries if e in graph]
    while frontier:
        fn = frontier.pop()
        if fn in seen:
            continue
        seen.add(fn)
        frontier.extend(c for c in graph.get(fn, ()) if c in graph)
    return seen


class _FnScan(ast.NodeVisitor):
    """One pass per function: attr writes with guard state, lock-order
    edges, blocking-calls-under-lock."""

    def __init__(self, ctx: ModuleContext, fn: ast.FunctionDef,
                 cls: Optional[str], lock_names: Set[str]):
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.lock_names = lock_names
        self.held: List[Tuple[str, str]] = []  # (lock id, full text)
        self.writes: List[_Write] = []
        self.edges: List[Tuple[str, str, str, str, ast.AST]] = []
        self.blocking: List[Tuple[ast.AST, str, str]] = []
        self._in_init = fn.name == "__init__"

    def scan(self):
        for stmt in self.fn.body:
            self.visit(stmt)
        return self

    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass  # nested defs scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef):
        pass  # nested classes (handler factories) scanned separately

    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            if _is_lock_expr(expr, self.lock_names):
                lid = _lock_id(expr, self.cls)
                text = expr_text(expr)
                if self.held:
                    outer_id, outer_text = self.held[-1]
                    self.edges.append(
                        (outer_id, lid, outer_text, text, node))
                self.held.append((lid, text))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def _record_write(self, target: ast.expr, node: ast.AST):
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute):
            self.writes.append(_Write(
                expr_text(base.value), base.attr, self.fn.name, node,
                bool(self.held), self._in_init))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record_write(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            self._record_write(t, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth in _MUTATION_METHODS and \
                    isinstance(node.func.value, (ast.Attribute,
                                                 ast.Subscript)):
                self._record_write(node.func.value, node)
            if self.held and self._is_blocking(node, meth):
                self.blocking.append((node, meth, self.held[-1][1]))
        elif isinstance(node.func, ast.Name) and self.held and \
                node.func.id == "sleep":
            self.blocking.append((node, "sleep", self.held[-1][1]))
        self.generic_visit(node)

    def _is_blocking(self, node: ast.Call, meth: str) -> bool:
        kwargs = {kw.arg for kw in node.keywords}
        if meth == "join":
            # Thread.join() / join(timeout=...) / join(5) block;
            # str.join(seq) and os.path.join(a, b) don't
            return (not node.args and (not kwargs or "timeout" in kwargs)) \
                or (len(node.args) == 1
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float)))
        if meth == "get":
            # queue.get() / get(timeout=) blocks; dict.get(key[, default])
            # carries positional args and neither kwarg
            return not node.args or bool({"timeout", "block"} & kwargs)
        if meth == "lower":
            return bool(node.args)  # str.lower() takes none
        if meth == "compile":
            recv = expr_text(node.func.value)
            return "lower(" in recv or "jit" in recv
        if meth == "acquire":
            return "blocking" not in kwargs and not (
                node.args and isinstance(node.args[0], ast.Constant)
                and node.args[0].value is False)
        return meth in _BLOCKING_ATTRS


def _class_functions(ctx: ModuleContext
                     ) -> List[Tuple[Optional[str], ast.FunctionDef]]:
    """Every function with its nearest enclosing class name (None for
    module-level functions)."""
    out: List[Tuple[Optional[str], ast.FunctionDef]] = []

    def walk(node: ast.AST, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, ast.FunctionDef):
                out.append((cls, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(ctx.tree, None)
    return out


def _shared_annotated_attrs(ctx: ModuleContext,
                            scans: Sequence[_FnScan]) -> Set[str]:
    """Attr names whose write line carries ``# synlint: shared``."""
    shared: Set[str] = set()
    lines = ctx.directives.shared
    if not lines:
        return shared
    for scan in scans:
        for w in scan.writes:
            span = range(w.node.lineno,
                         getattr(w.node, "end_lineno", w.node.lineno) + 1)
            if any(ln in lines for ln in span):
                shared.add(w.attr)
    return shared


def _threadsafe_attrs(ctx: ModuleContext) -> Set[str]:
    safe: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _THREADSAFE_CTORS.search(expr_text(node.value)):
                for t in node.targets:
                    safe.add(expr_name(t))
    return safe


def run(ctx: ModuleContext) -> List[Finding]:
    if "threading" not in ctx.source and "Thread" not in ctx.source:
        return []
    lock_names = _collect_lock_names(ctx)
    entries = _thread_entries(ctx)
    reachable = _reachable(entries, _call_graph(ctx)) if entries else set()
    scans = [_FnScan(ctx, fn, cls, lock_names).scan()
             for cls, fn in _class_functions(ctx)]
    findings: List[Finding] = []

    # -- CC001: unguarded shared writes --------------------------------
    shared_attrs = _shared_annotated_attrs(ctx, scans)
    safe_attrs = _threadsafe_attrs(ctx) | lock_names
    by_key: Dict[Tuple[Optional[str], str], List[_Write]] = {}
    for scan in scans:
        for w in scan.writes:
            if w.receiver == "self":
                by_key.setdefault((scan.cls, w.attr), []).append(w)
            else:
                by_key.setdefault((None, w.attr), []).append(w)
    for (cls, attr), writes in sorted(
            by_key.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])):
        if attr in safe_attrs:
            continue
        writers = {w.fn for w in writes if not w.in_init}
        multi = len(writers) >= 2 and bool(writers & reachable)
        if not multi and attr not in shared_attrs:
            continue
        for w in writes:
            if w.in_init or w.guarded:
                continue
            where = f"{cls}.{attr}" if cls else attr
            why = ("annotated `synlint: shared`" if attr in shared_attrs
                   else f"written from {len(writers)} functions incl. a "
                        "thread entry")
            findings.append(ctx.finding(
                "CC001", w.node,
                f"unguarded write to shared field {where} in "
                f"{w.fn!r} ({why}) — hold the owning lock"))

    # -- CC002: lock-order cycles ---------------------------------------
    adj: Dict[str, Dict[str, ast.AST]] = {}
    self_edges: List[Tuple[str, ast.AST]] = []
    for scan in scans:
        for outer, inner, otext, itext, node in scan.edges:
            if outer == inner:
                if otext == itext:
                    self_edges.append((otext, node))
                continue
            adj.setdefault(outer, {}).setdefault(inner, node)
    for text, node in self_edges:
        findings.append(ctx.finding(
            "CC002", node,
            f"lock {text} re-acquired while already held — deadlock for "
            "a non-reentrant Lock"))
    reported: Set[frozenset] = set()
    for a, inners in sorted(adj.items()):
        for b, node in sorted(inners.items()):
            if a in adj.get(b, {}):
                key = frozenset((a, b))
                if key not in reported:
                    reported.add(key)
                    findings.append(ctx.finding(
                        "CC002", node,
                        f"inconsistent lock order: {a} -> {b} here but "
                        f"{b} -> {a} elsewhere in this module — potential "
                        "deadlock; pick one order"))

    # -- CC003: blocking call under a lock ------------------------------
    for scan in scans:
        for node, meth, lock_text in scan.blocking:
            findings.append(ctx.finding(
                "CC003", node,
                f"blocking call .{meth}(...) while holding {lock_text} — "
                "move the wait outside the critical section"))
    return findings
