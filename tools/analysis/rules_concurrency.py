"""CC rules: lock discipline across the package's threading sites.

CC001  shared field written without holding a lock
CC002  inconsistent lock acquisition order (potential deadlock)
CC003  blocking call while holding a lock

Model (heuristic, lexical — documented in docs/analysis.md):

- *Thread entries* are functions referenced as ``threading.Thread(
  target=...)`` in ANY analyzed module. Anything reachable from an
  entry through the repo-wide name-based call graph runs off the
  creating thread (over-approximate on purpose).
- A write is *guarded* when it sits lexically inside a ``with <lock>:``
  block; lock-ness is detected from ``threading.Lock()``/``RLock()``
  and ``make_lock()``/``make_rlock()``/``make_condition()`` (the
  runtime/locksan.py factory every package lock is built through)
  assignments plus a name heuristic ("lock" in the identifier).
- A field is *shared* when written (outside ``__init__``) from two or
  more functions at least one of which is thread-reachable, or when its
  declaration carries a ``# synlint: shared`` annotation — the registry
  for fields whose sharing the call graph cannot see (cross-object
  handoffs, fields mutated through a non-``self`` receiver).
- Fields holding intrinsically thread-safe objects (``queue.Queue``,
  ``threading.Event``/``Semaphore``/locks) are exempt.

v2 adds the whole-program passes: lock identities are module/class
qualified, acquisition-order edges are unioned across modules, and a
call made *while holding a lock* is resolved through the caller's
import table so a lock taken in module A and re-acquired (or blocked
on) inside a helper in module B produces the CC002/CC003 finding that
single-file analysis provably cannot see.
"""
from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.engine import (ModuleContext, Program, expr_name,
                                   expr_text)
from tools.analysis.findings import Finding

PACK = "concurrency"

_LOCK_CTORS = re.compile(r"threading\.(R?Lock|Condition)\b|\b(R?Lock)\(\)"
                         r"|\bmake_(lock|rlock|condition)\(")
_THREADSAFE_CTORS = re.compile(
    r"(queue|_queue)\.(Lifo|Priority)?Queue\(|threading\.(Event|Semaphore|"
    r"BoundedSemaphore|Barrier|R?Lock|Condition)\(|Event\(\)|Semaphore\("
    r"|\bmake_(lock|rlock|condition)\(")
_MUTATION_METHODS = {"append", "appendleft", "extend", "insert", "remove",
                     "pop", "popleft", "popitem", "clear", "update", "add",
                     "discard", "setdefault"}
_BLOCKING_ATTRS = {"result", "sleep", "block_until_ready",
                   "device_get", "recv", "accept", "connect",
                   "sendall", "readline", "urlopen", "wait"}
# callee-chain depth for the interprocedural lock-closure walk: enough
# for wrapper -> helper -> primitive, bounded so aliasing noise can't
# snowball through the over-approximate name resolution
_CLOSURE_DEPTH = 3


class _Write:
    __slots__ = ("receiver", "attr", "fn", "node", "guarded", "in_init")

    def __init__(self, receiver: str, attr: str, fn: str, node: ast.AST,
                 guarded: bool, in_init: bool):
        self.receiver = receiver
        self.attr = attr
        self.fn = fn
        self.node = node
        self.guarded = guarded
        self.in_init = in_init


def _collect_lock_names(ctx: ModuleContext) -> Set[str]:
    names: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _LOCK_CTORS.search(expr_text(node.value)):
                for t in node.targets:
                    names.add(expr_name(t))
    return names


def _is_lock_expr(node: ast.AST, lock_names: Set[str]) -> bool:
    name = expr_name(node)
    return name in lock_names or "lock" in name.lower()


def _thread_entries(ctx: ModuleContext) -> Tuple[Set[str], Set[str]]:
    """(local entry names, resolvable target reprs). The names drive
    same-module reachability (v1 semantics); the reprs let a
    ``Thread(target=worker.loop)`` in module A seed reachability inside
    module B through A's import table."""
    entries: Set[str] = set()
    refs: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Call) and \
                expr_text(node.func).endswith("Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    entries.add(expr_name(kw.value))
                    ref = _callee_repr(kw.value) if \
                        isinstance(kw.value, (ast.Name, ast.Attribute)) \
                        else None
                    if ref:
                        refs.add(ref)
    return entries, refs


def _call_graph(ctx: ModuleContext) -> Dict[str, Set[str]]:
    """fn-name -> names it calls (bare and attribute names). Name-based:
    cross-class collisions over-approximate reachability, which errs
    toward reporting — the right direction for a race detector."""
    graph: Dict[str, Set[str]] = {}
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            called: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    called.add(expr_name(sub.func))
                elif isinstance(sub, ast.Attribute):
                    # method handed around as a value (callbacks, targets)
                    called.add(sub.attr)
            graph.setdefault(node.name, set()).update(called)
    return graph


def _callee_repr(func: ast.expr) -> Optional[str]:
    """Resolvable callee form: ``name``, ``alias.name``, ``self.name``.
    Deeper attribute chains return None — resolution would be guesswork."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return f"{func.value.id}.{func.attr}"
    return None


class _FnScan(ast.NodeVisitor):
    """One pass per function: attr writes with guard state, lock-order
    edges, blocking calls, and calls made while holding a lock."""

    def __init__(self, ctx: ModuleContext, fn: ast.FunctionDef,
                 cls: Optional[str], lock_names: Set[str]):
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.lock_names = lock_names
        self.held: List[Tuple[str, str]] = []  # (lock id, full text)
        self.writes: List[_Write] = []
        self.edges: List[Tuple[str, str, str, str, ast.AST]] = []
        self.self_edges: List[Tuple[str, ast.AST]] = []
        self.blocking: List[Tuple[ast.AST, str, str]] = []
        self.blocking_any: List[Tuple[str, int]] = []
        self.acquires: Set[str] = set()
        self.under_lock_calls: List[Tuple[str, str, str, int]] = []
        self.calls: Set[str] = set()
        self._in_init = fn.name == "__init__"

    def _lock_id(self, node: ast.AST) -> str:
        """Lock identity for order tracking: class-qualified for
        ``self`` receivers, module-qualified for bare module-level
        names, alias-qualified for imported-module attributes — so two
        classes' (or modules') ``_lock`` fields don't alias, while the
        SAME lock reached from two modules does unify."""
        name = expr_name(node)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            recv = node.value.id
            if recv == "self" and self.cls:
                return f"{self.cls}.{name}"
            mod = self.ctx.imports.get(recv)
            if mod:
                return f"{mod.rsplit('.', 1)[-1]}:{name}"
            return f"{recv}.{name}"
        if isinstance(node, ast.Name):
            fi = self.ctx.from_imports.get(name)
            if fi:
                return f"{fi[0].rsplit('.', 1)[-1]}:{fi[1]}"
            if name in self.lock_names:
                stem = self.ctx.module.rsplit(".", 1)[-1]
                return f"{stem}:{name}"
        return name

    def scan(self):
        for stmt in self.fn.body:
            self.visit(stmt)
        return self

    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass  # nested defs scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef):
        pass  # nested classes (handler factories) scanned separately

    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            if _is_lock_expr(expr, self.lock_names):
                lid = self._lock_id(expr)
                text = expr_text(expr)
                self.acquires.add(lid)
                if self.held:
                    outer_id, outer_text = self.held[-1]
                    if outer_id == lid:
                        if outer_text == text:
                            self.self_edges.append((text, node))
                    else:
                        self.edges.append(
                            (outer_id, lid, outer_text, text, node))
                self.held.append((lid, text))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def _record_write(self, target: ast.expr, node: ast.AST):
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute):
            self.writes.append(_Write(
                expr_text(base.value), base.attr, self.fn.name, node,
                bool(self.held), self._in_init))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record_write(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            self._record_write(t, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        callee = _callee_repr(node.func)
        if callee is not None:
            self.calls.add(callee)
            if self.held:
                self.under_lock_calls.append(
                    (self.held[-1][0], self.held[-1][1], callee,
                     node.lineno))
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth in _MUTATION_METHODS and \
                    isinstance(node.func.value, (ast.Attribute,
                                                 ast.Subscript)):
                self._record_write(node.func.value, node)
            if self._is_blocking(node, meth):
                self.blocking_any.append((meth, node.lineno))
                if self.held:
                    self.blocking.append((node, meth, self.held[-1][1]))
        elif isinstance(node.func, ast.Name) and node.func.id == "sleep":
            self.blocking_any.append(("sleep", node.lineno))
            if self.held:
                self.blocking.append((node, "sleep", self.held[-1][1]))
        self.generic_visit(node)

    def _is_blocking(self, node: ast.Call, meth: str) -> bool:
        kwargs = {kw.arg for kw in node.keywords}
        if meth == "join":
            # Thread.join() / join(timeout=...) / join(5) block;
            # str.join(seq) and os.path.join(a, b) don't
            return (not node.args and (not kwargs or "timeout" in kwargs)) \
                or (len(node.args) == 1
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float)))
        if meth == "get":
            # queue.get() / get(timeout=) blocks; dict.get(key[, default])
            # carries positional args and neither kwarg
            return not node.args or bool({"timeout", "block"} & kwargs)
        if meth == "lower":
            return bool(node.args)  # str.lower() takes none
        if meth == "compile":
            recv = expr_text(node.func.value)
            return "lower(" in recv or "jit" in recv
        if meth == "acquire":
            return "blocking" not in kwargs and not (
                node.args and isinstance(node.args[0], ast.Constant)
                and node.args[0].value is False)
        return meth in _BLOCKING_ATTRS


def _class_functions(ctx: ModuleContext
                     ) -> List[Tuple[Optional[str], ast.FunctionDef]]:
    """Every function with its nearest enclosing class name (None for
    module-level functions)."""
    out: List[Tuple[Optional[str], ast.FunctionDef]] = []

    def walk(node: ast.AST, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, ast.FunctionDef):
                out.append((cls, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(ctx.tree, None)
    return out


def _shared_annotated_attrs(ctx: ModuleContext,
                            scans: Sequence[_FnScan]) -> Set[str]:
    """Attr names whose write line carries ``# synlint: shared``."""
    shared: Set[str] = set()
    lines = ctx.directives.shared
    if not lines:
        return shared
    for scan in scans:
        for w in scan.writes:
            span = range(w.node.lineno,
                         getattr(w.node, "end_lineno", w.node.lineno) + 1)
            if any(ln in lines for ln in span):
                shared.add(w.attr)
    return shared


def _threadsafe_attrs(ctx: ModuleContext) -> Set[str]:
    safe: Set[str] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _THREADSAFE_CTORS.search(expr_text(node.value)):
                for t in node.targets:
                    safe.add(expr_name(t))
    return safe


def summarize(ctx: ModuleContext) -> Dict[str, Any]:
    """Everything the global passes need, JSON-able for the cache."""
    lock_names = _collect_lock_names(ctx)
    scans = [_FnScan(ctx, fn, cls, lock_names).scan()
             for cls, fn in _class_functions(ctx)]
    functions = []
    for scan in scans:
        functions.append({
            "name": scan.fn.name,
            "qual": ctx.context_for(scan.fn.body[0]) if scan.fn.body
                    else scan.fn.name,
            "cls": scan.cls,
            "line": scan.fn.lineno,
            "acquires": sorted(scan.acquires),
            "calls": sorted(scan.calls),
            "blocking": [[m, ln] for m, ln in scan.blocking_any],
            "blocking_under_lock": [
                [m, n.lineno, n.col_offset, lt]
                for n, m, lt in scan.blocking],
            "edges": [[o, i, ot, it, n.lineno, n.col_offset]
                      for o, i, ot, it, n in scan.edges],
            "self_edges": [[t, n.lineno, n.col_offset]
                           for t, n in scan.self_edges],
            "under_lock_calls": [list(t) for t in scan.under_lock_calls],
        })
    writes = []
    for scan in scans:
        for w in scan.writes:
            writes.append({
                "receiver": w.receiver, "attr": w.attr, "fn": w.fn,
                "cls": scan.cls, "line": w.node.lineno,
                "col": w.node.col_offset,
                "qual": ctx.context_for(w.node),
                "guarded": w.guarded, "in_init": w.in_init})
    entries, entry_refs = _thread_entries(ctx)
    return {
        "functions": functions,
        "writes": writes,
        "entries": sorted(entries),
        "entry_refs": sorted(entry_refs),
        "callgraph": {k: sorted(v)
                      for k, v in _call_graph(ctx).items()},
        "lock_names": sorted(lock_names),
        "safe_attrs": sorted(_threadsafe_attrs(ctx) | lock_names),
        "shared_attrs": sorted(_shared_annotated_attrs(ctx, scans)),
    }


def _reachable_by_module(prog: Program) -> Dict[str, Set[str]]:
    """relpath -> function names thread-reachable inside that module.

    Reachability is module-local over the name-based call graph (the
    repo-wide union drowns CC001 in aliasing noise — `build`/`name`
    collide everywhere); what IS cross-module is the *seeding*: a
    ``Thread(target=a.loop)`` in one module resolves through its import
    table and seeds ``loop`` in module ``a``."""
    seeds: Dict[str, Set[str]] = {
        rel: set(summary.get(PACK, {}).get("entries", ()))
        for rel, summary in prog.summaries.items()}
    for rel, summary in prog.summaries.items():
        for ref in summary.get(PACK, {}).get("entry_refs", ()):
            for trel, tfn in prog.resolve_call(summary, ref):
                seeds.setdefault(trel, set()).add(tfn["name"])
    out: Dict[str, Set[str]] = {}
    for rel, summary in prog.summaries.items():
        graph = {fn: set(called) for fn, called in
                 summary.get(PACK, {}).get("callgraph", {}).items()}
        seen: Set[str] = set()
        frontier = [e for e in seeds.get(rel, ()) if e in graph]
        while frontier:
            fn = frontier.pop()
            if fn in seen:
                continue
            seen.add(fn)
            frontier.extend(c for c in graph.get(fn, ()) if c in graph)
        out[rel] = seen
    return out


def _lock_closure(prog: Program, rel: str, fn: Dict[str, Any],
                  memo: Dict[Tuple[str, str], Set[str]],
                  depth: int = _CLOSURE_DEPTH) -> Set[str]:
    """Locks ``fn`` acquires directly or through resolvable callees
    (bounded depth, cycle-safe via the memo)."""
    key = (rel, fn["qual"])
    if key in memo:
        return memo[key]
    memo[key] = set(fn.get("acquires", ()))  # cycle guard: partial first
    acquired = set(fn.get("acquires", ()))
    if depth > 0:
        summary = prog.summaries.get(rel, {})
        for callee in fn.get("calls", ()):
            for trel, tfn in prog.resolve_call(summary, callee):
                if (trel, tfn["qual"]) == key:
                    continue
                acquired |= _lock_closure(prog, trel, tfn, memo, depth - 1)
    memo[key] = acquired
    return acquired


def _fn_blocks(prog: Program, rel: str, fn: Dict[str, Any]
               ) -> Optional[str]:
    """Short description of a direct blocking call in ``fn``, if any."""
    blocking = fn.get("blocking") or []
    if blocking:
        meth, line = blocking[0]
        return f".{meth}(...) at {rel}:{line}"
    return None


def static_adjacency(prog: Program,
                     findings: Optional[List[Finding]] = None
                     ) -> Dict[str, Dict[str, Tuple[str, int, int, str]]]:
    """The static CC002 acquisition-order model: lock id -> lock id ->
    (path, line, col, qualname) for every ordered pair the AST can see,
    directly nested or through the bounded interprocedural closure.
    This is the closure tools/analysis/rules_dynsan.py diffs the
    runtime-observed graph against. When ``findings`` is given, the
    same-lock re-acquisition findings the walk trips over are appended
    (run_global passes it; rules_dynsan doesn't — those findings are
    CC002's to report exactly once)."""
    adj: Dict[str, Dict[str, Tuple[str, int, int, str]]] = {}
    memo: Dict[Tuple[str, str], Set[str]] = {}
    for rel in sorted(prog.summaries):
        summary = prog.summaries[rel]
        cc = summary.get(PACK)
        if not cc:
            continue
        for fn in cc.get("functions", ()):
            if findings is not None:
                for text, line, col in fn.get("self_edges", ()):
                    findings.append(Finding(
                        rule="CC002", path=rel, line=line, col=col,
                        context=fn["qual"],
                        message=f"lock {text} re-acquired while already "
                                "held — deadlock for a non-reentrant "
                                "Lock"))
            for outer, inner, _ot, _it, line, col in fn.get("edges", ()):
                adj.setdefault(outer, {}).setdefault(
                    inner, (rel, line, col, fn["qual"]))
            for lid, ltext, callee, line in fn.get("under_lock_calls", ()):
                for trel, tfn in prog.resolve_call(summary, callee):
                    closure = _lock_closure(prog, trel, tfn, memo)
                    for lid2 in closure:
                        if lid2 == lid:
                            if findings is not None:
                                findings.append(Finding(
                                    rule="CC002", path=rel, line=line,
                                    col=0, context=fn["qual"],
                                    message=f"call {callee}(...) while "
                                            f"holding {ltext} re-acquires "
                                            f"it (via {trel}:"
                                            f"{tfn['line']}) — deadlock "
                                            "for a non-reentrant Lock"))
                        else:
                            adj.setdefault(lid, {}).setdefault(
                                lid2, (rel, line, 0, fn["qual"]))
    return adj


def run_global(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    reachable_by_mod = _reachable_by_module(prog)

    # -- CC001: unguarded shared writes (module-local reachability,
    #    cross-module thread-entry seeding) ------------------------------
    for rel in sorted(prog.summaries):
        cc = prog.summaries[rel].get(PACK)
        if not cc:
            continue
        reachable = reachable_by_mod.get(rel, set())
        shared_attrs = set(cc.get("shared_attrs", ()))
        safe_attrs = set(cc.get("safe_attrs", ()))
        by_key: Dict[Tuple[Optional[str], str], List[Dict]] = {}
        for w in cc.get("writes", ()):
            key = (w["cls"] if w["receiver"] == "self" else None, w["attr"])
            by_key.setdefault(key, []).append(w)
        for (cls, attr), writes in sorted(
                by_key.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])):
            if attr in safe_attrs:
                continue
            writers = {w["fn"] for w in writes if not w["in_init"]}
            multi = len(writers) >= 2 and bool(writers & reachable)
            if not multi and attr not in shared_attrs:
                continue
            for w in writes:
                if w["in_init"] or w["guarded"]:
                    continue
                where = f"{cls}.{attr}" if cls else attr
                why = ("annotated `synlint: shared`"
                       if attr in shared_attrs
                       else f"written from {len(writers)} functions incl. "
                            "a thread entry")
                findings.append(Finding(
                    rule="CC001", path=rel, line=w["line"], col=w["col"],
                    context=w["qual"],
                    message=f"unguarded write to shared field {where} in "
                            f"{w['fn']!r} ({why}) — hold the owning lock"))

    # -- CC002: lock-order cycles, direct + through resolved callees ----
    adj = static_adjacency(prog, findings)
    reported: Set[frozenset] = set()
    for a, inners in sorted(adj.items()):
        for b, (rel, line, col, qual) in sorted(inners.items()):
            if a in adj.get(b, {}):
                key = frozenset((a, b))
                if key not in reported:
                    reported.add(key)
                    other = adj[b][a]
                    findings.append(Finding(
                        rule="CC002", path=rel, line=line, col=col,
                        context=qual,
                        message=f"inconsistent lock order: {a} -> {b} "
                                f"here but {b} -> {a} at {other[0]}:"
                                f"{other[1]} — potential deadlock; pick "
                                "one order"))

    # -- CC003: blocking call under a lock (direct + one resolved hop) --
    for rel in sorted(prog.summaries):
        summary = prog.summaries[rel]
        cc = summary.get(PACK)
        if not cc:
            continue
        for fn in cc.get("functions", ()):
            for meth, line, col, lock_text in fn.get(
                    "blocking_under_lock", ()):
                findings.append(Finding(
                    rule="CC003", path=rel, line=line, col=col,
                    context=fn["qual"],
                    message=f"blocking call .{meth}(...) while holding "
                            f"{lock_text} — move the wait outside the "
                            "critical section"))
            for lid, ltext, callee, line in fn.get("under_lock_calls", ()):
                for trel, tfn in prog.resolve_call(summary, callee):
                    why = _fn_blocks(prog, trel, tfn)
                    if why and trel != rel or why and \
                            tfn["qual"] != fn["qual"]:
                        findings.append(Finding(
                            rule="CC003", path=rel, line=line, col=0,
                            context=fn["qual"],
                            message=f"call {callee}(...) while holding "
                                    f"{ltext} reaches blocking {why} — "
                                    "move the wait outside the critical "
                                    "section"))
                        break  # one finding per call site
    return findings
