"""RL rules: resource lifecycle.

RL001  threading.Thread started outside a supervision boundary
RL002  gauge_fn series registered in instance scope with no unregister
RL003  tmp-file write not finalized by an atomic rename

The runtime's contract since PR 6: every long-lived thread body runs
under a supervision boundary (``_supervise_loop`` / ``_supervised`` /
``_pipeline_thread``) so an escaped exception — including an injected
:class:`faults.ThreadKilled` — is recorded, counted, and restarted
instead of silently wedging a pipeline stage. ``gauge_fn`` hands the
telemetry registry a live callback: a registration with no matching
``unregister`` pins the object (and keeps exporting stale values) after
its owner stops. Durable files follow tmp-then-``os.replace`` so
readers never see a torn write.

All three are *local* rules; the supervision check is lexical on the
``target=`` expression, with ``# synlint: disable=RL001`` as the escape
hatch for deliberate fire-and-forget threads (state the reason in the
same comment).
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from tools.analysis.engine import ModuleContext, expr_text
from tools.analysis.findings import Finding

PACK = "lifecycle"

# a thread target is supervised when the target expression names a
# supervision wrapper (or a lambda closing over one)
_SUPERVISED_RE = re.compile(r"supervis|_pipeline_thread")
_ATOMIC_RE = re.compile(r"\bos\s*\.\s*(replace|rename)\b|\.rename\(")


def _rule_rl001(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    if ctx.relpath.startswith("tools/"):
        # CLI harnesses (loadgen, chaos driver, fleet controller) join
        # their worker threads and die with the process — the
        # supervision contract is a runtime-package discipline
        return out
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or \
                not expr_text(node.func).endswith("Thread"):
            continue
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        if target is None:
            continue
        text = expr_text(target)
        if _SUPERVISED_RE.search(text):
            continue
        out.append(ctx.finding(
            "RL001", node,
            f"thread target {text!r} started outside a supervision "
            "boundary (_supervised/_supervise_loop/_pipeline_thread) — "
            "an escaped exception or injected ThreadKilled ends it "
            "silently; wrap the body or annotate the deliberate "
            "fire-and-forget"))
    return out


def _literal_str_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _rule_rl002(ctx: ModuleContext) -> List[Finding]:
    unregistered_names: Set[str] = set()
    has_wildcard_unregister = False
    registrations = []
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        fname = expr_text(node.func)
        if fname.endswith("unregister"):
            name = _literal_str_arg(node)
            if name is None:
                # unregister(series_variable, ...) — a loop tearing
                # down a set of series; assume it covers the module
                has_wildcard_unregister = True
            else:
                unregistered_names.add(name)
        elif fname.endswith("gauge_fn"):
            registrations.append(node)
    out: List[Finding] = []
    for node in registrations:
        name = _literal_str_arg(node)
        if name is None:
            continue
        if ctx.context_for(node) == "<module>":
            continue  # module-level registration lives for the process
        if has_wildcard_unregister or name in unregistered_names:
            continue
        out.append(ctx.finding(
            "RL002", node,
            f"gauge_fn series {name!r} registered in instance scope "
            "with no unregister() in this module — the registry keeps "
            "the callback (and the object) alive and exports stale "
            "values after stop"))
    return out


def _is_tmp_write(node: ast.Call) -> Optional[str]:
    """Describe a tmp-file write: ``open(<...tmp...>, 'w')`` or a
    ``mkstemp`` call. Returns a short description or None."""
    fname = expr_text(node.func)
    if fname.endswith("mkstemp"):
        return "mkstemp(...)"
    if fname == "open" and len(node.args) >= 2:
        mode = node.args[1]
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
                and ("w" in mode.value or "x" in mode.value):
            path_text = expr_text(node.args[0])
            if "tmp" in path_text.lower():
                return f"open({path_text}, {mode.value!r})"
    return None


def _rule_rl003(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[int] = set()  # nested defs appear under both scans
    for fn in ctx.nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        writes = []
        finalized = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            desc = _is_tmp_write(node)
            if desc:
                writes.append((node, desc))
            elif _ATOMIC_RE.search(expr_text(node.func) + "("):
                finalized = True
        if writes and not finalized:
            for node, desc in writes:
                if node.lineno in seen:
                    continue
                seen.add(node.lineno)
                out.append(ctx.finding(
                    "RL003", node,
                    f"tmp-file write {desc} is not followed by an "
                    "atomic os.replace/rename in this function — a "
                    "crash mid-write leaves a torn or orphaned file"))
    return out


def run_local(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    out.extend(_rule_rl001(ctx))
    out.extend(_rule_rl002(ctx))
    out.extend(_rule_rl003(ctx))
    return out
