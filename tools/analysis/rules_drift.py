"""DR rules: observability-contract drift, unified under one gate.

DR001  metric registered in code with no catalog row in docs/observability.md
DR002  catalog row naming a series no analyzed code registers
DR003  committed Grafana dashboard out of sync with the catalog

This pack is tools/ci/metrics_doc_check.py folded into the analyzer:
the same AST collection (literal first argument of a ``counter`` /
``gauge`` / ``gauge_fn`` / ``histogram`` call with a gated prefix) now
happens in ``summarize`` — so it rides the content-hash cache — and
the doc side uses the SAME parser as the Grafana generator
(``tools.k8s.gen_dashboard.catalog_rows``), so a metric cannot satisfy
the gate yet be missing from the dashboard. metric drift, dashboard
drift, and knob drift (rules_env) all report through one
``--fail-on-new`` exit code.

The global pass only runs when the analysis actually covers
``synapseml_tpu/`` — a fixture-only run must not accuse the package of
drift it cannot see.
"""
from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List

from tools.analysis.engine import ModuleContext, Program
from tools.analysis.findings import Finding

PACK = "drift"

METRIC_DOC = os.path.join("docs", "observability.md")
DASHBOARD = os.path.join("tools", "k8s", "chart", "dashboards",
                         "serving-dashboard.json")
PREFIXES = ("serving_", "executor_", "faults_", "blackbox_", "device_",
            "fleet_", "process_", "trace_", "capture_", "gbdt_",
            "onnx_", "autotune_", "tp_", "kv_", "decode_", "locksan_")
REGISTER_FNS = {"counter", "gauge", "gauge_fn", "histogram"}


def summarize(ctx: ModuleContext) -> Dict[str, Any]:
    metrics: List[List[Any]] = []
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fnode = node.func
        fname = (fnode.attr if isinstance(fnode, ast.Attribute)
                 else fnode.id if isinstance(fnode, ast.Name) else None)
        if fname not in REGISTER_FNS:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith(PREFIXES):
            metrics.append([arg.value, node.lineno])
    return {"metrics": metrics}


def _doc_rows(root: str):
    """(catalog names, doc lines-by-name) via the dashboard generator's
    parser; None when the doc or parser is unavailable."""
    doc_path = os.path.join(root, METRIC_DOC)
    if not os.path.isfile(doc_path):
        return None
    try:
        from tools.k8s.gen_dashboard import catalog_rows
        rows = catalog_rows(doc_path)
    except (ImportError, SystemExit):
        return None
    names = {name for name, _labels, _kind, _meaning in rows
             if name.startswith(PREFIXES)}
    lines: Dict[str, int] = {}
    with open(doc_path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            for name in names:
                if name in line:
                    lines.setdefault(name, i)
    return rows, names, lines


def _dashboard_drift(root: str, rows) -> bool:
    path = os.path.join(root, DASHBOARD)
    if not os.path.isfile(path):
        return False  # chart not vendored in this checkout
    try:
        from tools.k8s.gen_dashboard import build
        with open(path, encoding="utf-8") as fh:
            committed = json.load(fh)
        return build(rows) != committed
    except (ImportError, OSError, ValueError):
        return True  # an unreadable committed dashboard IS drift


def run_global(prog: Program) -> List[Finding]:
    if not prog.covers("synapseml_tpu/"):
        return []
    parsed = _doc_rows(prog.root)
    if parsed is None:
        return [Finding(
            rule="DR002", path=METRIC_DOC, line=1, col=0,
            context="<doc>",
            message="metric catalog missing or unparseable — every "
                    "registered series needs a catalog row")]
    rows, doc_names, doc_lines = parsed
    code: Dict[str, List[str]] = {}
    for rel in sorted(prog.summaries):
        if not rel.startswith("synapseml_tpu/"):
            continue
        dr = prog.summaries[rel].get(PACK)
        if not dr:
            continue
        for name, line in dr.get("metrics", ()):
            code.setdefault(name, []).append(f"{rel}:{line}")
    findings: List[Finding] = []
    for name in sorted(set(code) - doc_names):
        rel, _, line = code[name][0].rpartition(":")
        findings.append(Finding(
            rule="DR001", path=rel, line=int(line), col=0,
            context="<module>",
            message=f"metric {name!r} registered here has no catalog "
                    f"row in {METRIC_DOC} — dashboards, alerts, and "
                    "the runbook all read the catalog"))
    # stale-row and dashboard checks accuse the DOC of naming things
    # the code lacks — only meaningful when the whole package was
    # analyzed, not a single-file or fixture run
    full_package = sum(rel.startswith("synapseml_tpu/")
                       for rel in prog.summaries) >= 20
    if not full_package:
        return findings
    for name in sorted(doc_names - set(code)):
        findings.append(Finding(
            rule="DR002", path=METRIC_DOC,
            line=doc_lines.get(name, 1), col=0, context="<doc>",
            message=f"catalog row {name!r} names a series no analyzed "
                    "code registers — stale row (or the registration "
                    "moved outside synapseml_tpu/)"))
    if _dashboard_drift(prog.root, rows):
        findings.append(Finding(
            rule="DR003", path=DASHBOARD, line=1, col=0,
            context="<dashboard>",
            message="committed dashboard differs from one generated "
                    "from the catalog — run python tools/k8s/"
                    "gen_dashboard.py"))
    return findings
