"""JH rules: JAX hygiene.

JH001  host-sync call inside a dispatch/drain hot path
JH002  Python ``if``/``while`` on a tracer value inside a jitted function
JH003  non-hashable / array-valued static arg (recompile or TypeError)
JH004  mutation of ``self``/globals inside a jitted function
JH005  donated buffer read after dispatch

None of these raise at runtime in the obvious way: they sync, silently
recompile per call, bake stale state into the trace, or read a deleted
buffer. Catching them is pattern matching on the AST — heuristic by
design, with ``# synlint: disable=`` as the escape hatch.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.engine import (ModuleContext, expr_text,
                                  walk_shallow)
from tools.analysis.findings import Finding

PACK = "jax"

# functions treated as dispatch-critical even without a `# synlint:
# hotpath` annotation — the executor pipeline's naming convention
_HOT_NAME_RE = re.compile(r"^_?(dispatch|drain)|^submit$")

# reading any of these off a tracer is static — not a tracer branch
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "device"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                 "callable", "id"}

_SYNC_METHODS = {"block_until_ready", "item", "tolist"}
_SYNC_CONVERTERS = {"float", "int", "bool"}


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    text = expr_text(node.func)
    if text == "jit" or text.endswith(".jit"):
        return True
    if text in ("partial", "functools.partial") and node.args:
        inner = expr_text(node.args[0])
        return inner == "jit" or inner.endswith(".jit")
    return False


def _jit_kwargs(node: ast.Call) -> Dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in node.keywords if kw.arg}


def _const_int_collection(node: Optional[ast.expr]) -> List[int]:
    """Literal ints out of ``static_argnums=0`` / ``(0, 2)`` / ``[1]``."""
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _const_str_collection(node: Optional[ast.expr]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    return [a.arg for a in (*args.posonlyargs, *args.args)]


class _JittedFn:
    def __init__(self, fn: ast.FunctionDef, static: Set[str],
                 jit_node: ast.AST):
        self.fn = fn
        self.static = static
        self.jit_node = jit_node


def _collect_jitted(ctx: ModuleContext) -> List[_JittedFn]:
    """Functions that are jit-compiled: decorated with (a partial of)
    ``jax.jit``, or wrapped by name in a ``jax.jit(f, ...)`` call."""
    by_name: Dict[str, ast.FunctionDef] = {}
    out: List[_JittedFn] = []
    claimed: Set[ast.FunctionDef] = set()
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                if _is_jit_call(dec) or expr_text(dec) in ("jax.jit", "jit"):
                    kw = _jit_kwargs(dec) if isinstance(dec, ast.Call) else {}
                    params = _param_names(node)
                    static = set(_const_str_collection(
                        kw.get("static_argnames")))
                    static |= {params[i] for i in _const_int_collection(
                        kw.get("static_argnums")) if i < len(params)}
                    out.append(_JittedFn(node, static, dec))
                    claimed.add(node)
    for node in ctx.nodes:
        if _is_jit_call(node) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name) and target.id in by_name:
                fn = by_name[target.id]
                if fn in claimed:
                    continue
                kw = _jit_kwargs(node)
                params = _param_names(fn)
                static = set(_const_str_collection(kw.get("static_argnames")))
                static |= {params[i] for i in _const_int_collection(
                    kw.get("static_argnums")) if i < len(params)}
                out.append(_JittedFn(fn, static, node))
                claimed.add(fn)
    return out


# -- JH001 ----------------------------------------------------------------

def _hot_functions(ctx: ModuleContext) -> List[ast.FunctionDef]:
    out = []
    for node in ctx.nodes:
        if not isinstance(node, ast.FunctionDef):
            continue
        if (node.lineno in ctx.directives.hotpath
                or _HOT_NAME_RE.search(node.name)):
            out.append(node)
    return out


def _tainted_names(fn: ast.FunctionDef) -> Set[str]:
    """Names locally assigned from device-producing calls (device_put,
    a jit/compiled callable) — the values a host conversion would sync."""
    tainted: Set[str] = set()
    device_re = re.compile(r"device_put|\bjit\b|_jit|compiled|\.aot\b|_aot")
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            text = expr_text(node.value.func)
            if device_re.search(text):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        tainted.update(e.id for e in t.elts
                                       if isinstance(e, ast.Name))
    return tainted


def _rule_jh001(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _hot_functions(ctx):
        tainted = _tainted_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth in _SYNC_METHODS:
                    out.append(ctx.finding(
                        "JH001", node,
                        f"host-sync call .{meth}() inside hot path "
                        f"{fn.name!r} stalls the dispatch pipeline"))
                    continue
                if meth in ("device_get",):
                    out.append(ctx.finding(
                        "JH001", node,
                        f"blocking D2H transfer ({expr_text(node.func)}) "
                        f"inside hot path {fn.name!r} — fetch belongs on "
                        "the drain side"))
                    continue
                if meth in ("asarray", "array") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in tainted:
                        out.append(ctx.finding(
                            "JH001", node,
                            f"np.{meth}({arg.id}) on a device value inside "
                            f"hot path {fn.name!r} forces a blocking D2H "
                            "copy"))
            elif isinstance(node.func, ast.Name):
                if node.func.id in _SYNC_CONVERTERS and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in tainted:
                        out.append(ctx.finding(
                            "JH001", node,
                            f"{node.func.id}({arg.id}) on a device value "
                            f"inside hot path {fn.name!r} blocks on the "
                            "device"))
    return out


# -- JH002 ----------------------------------------------------------------

def _traced_name_uses(test: ast.expr, traced: Set[str]) -> List[ast.Name]:
    """Name nodes in a branch test that read a traced value *as a
    value* — static accessors (.shape, len(), `is None`) excluded."""
    hits: List[ast.Name] = []

    def visit(node: ast.AST):
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return  # x.shape / x.dtype — static under trace
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            fname = expr_text(node.func)
            if fname in _STATIC_CALLS:
                return
            visit(node.func)  # x.sum() > n reads x through the receiver
            for a in node.args:
                visit(a)
            for kw in node.keywords:
                visit(kw.value)
            return
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` branches on python identity,
            # which is static for a tracer
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) \
                    and all(isinstance(c, ast.Constant)
                            for c in node.comparators):
                return
        if isinstance(node, ast.Name) and node.id in traced:
            hits.append(node)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits


def _rule_jh002(ctx: ModuleContext,
                jitted: Sequence[_JittedFn]) -> List[Finding]:
    out: List[Finding] = []
    for jf in jitted:
        traced = {p for p in _param_names(jf.fn)
                  if p not in jf.static and p != "self"}
        if not traced:
            continue
        for node in ast.walk(jf.fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for use in _traced_name_uses(node.test, traced):
                kind = "while" if isinstance(node, ast.While) else "if"
                out.append(ctx.finding(
                    "JH002", node,
                    f"python `{kind}` on traced value {use.id!r} inside "
                    f"jitted {jf.fn.name!r} — raises under trace or bakes "
                    "one branch in; use lax.cond/select or mark the arg "
                    "static"))
                break  # one finding per branch statement
    return out


# -- JH003 ----------------------------------------------------------------

_NONHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp)


def _is_arraylike_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    text = expr_text(node.func)
    return bool(re.search(r"(np|numpy|jnp)\.(as)?array|ones|zeros|arange",
                          text))


def _rule_jh003(ctx: ModuleContext,
                jitted: Sequence[_JittedFn]) -> List[Finding]:
    out: List[Finding] = []
    static_fns: Dict[str, Tuple[_JittedFn, List[int]]] = {}
    for jf in jitted:
        params = _param_names(jf.fn)
        idxs = [i for i, p in enumerate(params) if p in jf.static]
        if not idxs:
            continue
        # defaults of static params that can never hash
        defaults = jf.fn.args.defaults
        offset = len(params) - len(defaults)
        for i, d in enumerate(defaults):
            pos = offset + i
            if params[pos] in jf.static and (
                    isinstance(d, _NONHASHABLE) or _is_arraylike_call(d)):
                out.append(ctx.finding(
                    "JH003", d,
                    f"static arg {params[pos]!r} of jitted "
                    f"{jf.fn.name!r} defaults to a non-hashable value — "
                    "jit raises TypeError (or retraces per call); pass a "
                    "tuple or hashable config object"))
        static_fns[jf.fn.name] = (jf, idxs)
    # wrapper-name call sites: g = jax.jit(f, static_argnums=...); g(...)
    wrappers: Dict[str, Tuple[_JittedFn, List[int]]] = {}
    for node in ctx.nodes:
        if isinstance(node, ast.Assign) and _is_jit_call(node.value) \
                and node.value.args:
            target = node.value.args[0]
            if isinstance(target, ast.Name) and target.id in static_fns:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wrappers[t.id] = static_fns[target.id]
    callables = dict(wrappers)
    for name, (jf, idxs) in static_fns.items():
        if jf.jit_node in jf.fn.decorator_list or any(
                jf.jit_node is d for d in jf.fn.decorator_list):
            callables.setdefault(name, (jf, idxs))
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or not isinstance(node.func,
                                                            ast.Name):
            continue
        entry = callables.get(node.func.id)
        if entry is None or any(isinstance(a, ast.Starred)
                                for a in node.args):
            continue
        jf, idxs = entry
        for i in idxs:
            if i < len(node.args):
                arg = node.args[i]
                if isinstance(arg, _NONHASHABLE) or _is_arraylike_call(arg):
                    out.append(ctx.finding(
                        "JH003", arg,
                        f"non-hashable value passed for static arg "
                        f"#{i} of jitted {jf.fn.name!r} — TypeError at "
                        "call time (arrays: every call retraces)"))
    return out


# -- JH004 ----------------------------------------------------------------

def _rule_jh004(ctx: ModuleContext,
                jitted: Sequence[_JittedFn]) -> List[Finding]:
    out: List[Finding] = []
    module_globals = {t.id for node in ctx.tree.body
                      if isinstance(node, ast.Assign)
                      for t in node.targets if isinstance(t, ast.Name)}
    for jf in jitted:
        declared: Set[str] = set()
        for node in ast.walk(jf.fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
        for node in ast.walk(jf.fn):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    out.append(ctx.finding(
                        "JH004", node,
                        f"write to self.{base.attr} inside jitted "
                        f"{jf.fn.name!r} — runs once at trace time, then "
                        "the compiled program silently skips it"))
                elif isinstance(base, ast.Name) and base.id in declared:
                    out.append(ctx.finding(
                        "JH004", node,
                        f"write to global/nonlocal {base.id!r} inside "
                        f"jitted {jf.fn.name!r} — trace-time side effect, "
                        "not part of the compiled program"))
                elif isinstance(t, ast.Subscript) and \
                        isinstance(base, ast.Name) and \
                        base.id in module_globals:
                    out.append(ctx.finding(
                        "JH004", node,
                        f"subscript write to module global {base.id!r} "
                        f"inside jitted {jf.fn.name!r} — trace-time side "
                        "effect, not part of the compiled program"))
    return out


# -- JH005 ----------------------------------------------------------------

def _rule_jh005(ctx: ModuleContext) -> List[Finding]:
    """Within one function body: ``g = jax.jit(f, donate_argnums=...)``,
    ``g(x, ...)``, then a later read of ``x`` — the buffer may already be
    aliased into the output and deleted."""
    out: List[Finding] = []
    scopes = [n for n in ast.walk(ctx.tree)
              if isinstance(n, ast.FunctionDef)] + [ctx.tree]
    for scope in scopes:
        body = getattr(scope, "body", [])
        donating: Dict[str, List[int]] = {}
        donated_at: Dict[str, int] = {}  # arg name -> lineno of dispatch
        for stmt in body:
            # reassignment of a previously-donated name clears the taint
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        donated_at.pop(t.id, None)
                if _is_jit_call(stmt.value):
                    kw = _jit_kwargs(stmt.value)
                    nums = _const_int_collection(kw.get("donate_argnums"))
                    if nums:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                donating[t.id] = nums
                        continue
            # reads of donated names anywhere in this statement
            for node in walk_shallow(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in donated_at and \
                        node.lineno > donated_at[node.id]:
                    out.append(ctx.finding(
                        "JH005", node,
                        f"{node.id!r} was donated to a jitted call "
                        f"(line {donated_at[node.id]}) and read "
                        "afterwards — the buffer may be deleted; copy "
                        "first or don't donate"))
                    donated_at.pop(node.id, None)
            # new dispatches through a donating wrapper
            for node in walk_shallow(stmt):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in donating and \
                        not any(isinstance(a, ast.Starred)
                                for a in node.args):
                    for i in donating[node.func.id]:
                        if i < len(node.args) and \
                                isinstance(node.args[i], ast.Name):
                            donated_at[node.args[i].id] = node.lineno
    return out


def run_local(ctx: ModuleContext) -> List[Finding]:
    jitted = _collect_jitted(ctx)
    out: List[Finding] = []
    out.extend(_rule_jh001(ctx))
    out.extend(_rule_jh002(ctx, jitted))
    out.extend(_rule_jh003(ctx, jitted))
    out.extend(_rule_jh004(ctx, jitted))
    out.extend(_rule_jh005(ctx))
    return out
