"""DS rules: the runtime sanitizer's observed graph cross-validated
against the static CC002 model (the Coverity lesson: static findings
rot unless checked against real executions).

``synapseml_tpu/runtime/locksan.py`` labels every lock with its static
CC002 identity (``modstem:NAME`` / ``Class.attr``), so the observed
acquisition-order graph it dumps (``SYNAPSEML_LOCKSAN_OUT``) and the
adjacency :func:`tools.analysis.rules_concurrency.static_adjacency`
builds speak the same vocabulary and can be diffed edge by edge:

DS001  model gap: an edge the runtime OBSERVED but the static closure
       cannot reach — aliasing or callback indirection the AST can't
       see. Reported at the observed inner-acquire site; a
       ``# synlint: disable=DS001`` there declares the nesting
       understood (typical for leaf locks that may nest under
       anything).
DS002  runtime lock-order inversion (a cycle in the observed graph)
DS003  runtime blocking call while holding a lock (dynamic CC003)
DS004  deadlock watchdog event: a thread parked past the threshold on
       a lock whose holder was itself parked

Statically-claimed-but-never-observed edges are NOT findings — they
become *coverage annotations* (the smoke didn't drive that path), and
ride the report/SARIF as notes without failing the gate.

Artifacts come in through ``python -m tools.analysis --observed PATH``
(a file, or a directory of ``locksan-*.json`` from a multi-process
smoke). The fixture suite uses a sidecar convention instead: a module
``foo.py`` with ``foo.observed.json`` next to it is cross-checked by
the ordinary :func:`run_global` pass — that is what lets the
bad/good-twin fixtures exercise DS001 without a CLI flag.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Sequence, Tuple

from tools.analysis.engine import Program
from tools.analysis.findings import Finding
from tools.analysis.rules_concurrency import static_adjacency

PACK = "dynsan"

# findings kinds in the artifact -> rule id
_KIND_RULES = {"inversion": "DS002", "blocking": "DS003",
               "deadlock": "DS004"}


def load_artifacts(path: str) -> List[Dict[str, Any]]:
    """Load one artifact file, or every ``locksan-*.json`` under a
    directory (each process in a multi-process smoke dumps its own).
    Raises ``ValueError`` for an empty directory or a non-locksan
    payload — a missing artifact must fail loudly, or the cross-check
    silently passes on nothing."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "locksan-*.json")))
        if not files:
            raise ValueError(f"no locksan-*.json artifacts under {path}")
    else:
        files = [path]
    arts: List[Dict[str, Any]] = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            art = json.load(fh)
        if not isinstance(art, dict) or art.get("tool") != "locksan":
            raise ValueError(f"{f}: not a locksan observed-graph artifact")
        arts.append(art)
    return arts


def _rel_site(site: str, root: str) -> Tuple[str, int]:
    """``path:line`` from the artifact -> (repo-relative posix path,
    line). Runtime sites are absolute; fixture sidecars may already be
    relative."""
    path, _, line = str(site).rpartition(":")
    try:
        lineno = int(line)
    except ValueError:
        path, lineno = str(site), 0
    if os.path.isabs(path):
        try:
            path = os.path.relpath(path, root)
        except ValueError:  # different drive (windows) — keep absolute
            pass
    return path.replace(os.sep, "/"), lineno


def _merge_edges(arts: Sequence[Dict[str, Any]]
                 ) -> Dict[Tuple[str, str], Tuple[int, str]]:
    """(outer, inner) -> (summed count, first site) across artifacts."""
    merged: Dict[Tuple[str, str], Tuple[int, str]] = {}
    for art in arts:
        for e in art.get("edges", ()):
            key = (str(e.get("outer")), str(e.get("inner")))
            count = int(e.get("count", 1))
            site = str(e.get("site", "<unknown>:0"))
            prev = merged.get(key)
            merged[key] = (prev[0] + count, prev[1]) if prev \
                else (count, site)
    return merged


def _reaches(adj: Dict[str, Dict[str, Any]], start: str,
             goal: str) -> bool:
    """Static model reachability start => goal: an observed direct edge
    is *modeled* when the static closure orders the pair at all, even
    through intermediate locks."""
    stack, seen = [start], set()
    while stack:
        node = stack.pop()
        if node == goal:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(adj.get(node, ()))
    return False


def cross_check(prog: Program, arts: Sequence[Dict[str, Any]]
                ) -> Tuple[List[Finding], List[Finding]]:
    """Diff observed vs static. Returns ``(findings, coverage)``:
    findings are DS001 model gaps plus every runtime finding the
    sanitizer recorded (DS002/DS003/DS004); coverage is the list of
    statically-claimed-but-never-observed edges as note-level
    pseudo-findings (never part of the gate)."""
    adj = static_adjacency(prog)
    observed = _merge_edges(arts)
    findings: List[Finding] = []

    for (outer, inner), (_count, site) in sorted(observed.items()):
        if _reaches(adj, outer, inner):
            continue
        path, line = _rel_site(site, prog.root)
        findings.append(Finding(
            rule="DS001", path=path, line=line, col=0,
            context=f"{outer} -> {inner}",
            message=f"observed lock-order edge {outer} -> {inner} is "
                    "absent from the static CC002 model — aliasing or "
                    "indirection the AST can't see; teach the model, "
                    "fix the nesting, or annotate the acquire site"))

    for art in arts:
        for f in art.get("findings", ()):
            rule = _KIND_RULES.get(str(f.get("kind", "")))
            if rule is None:
                continue
            path, line = _rel_site(str(f.get("site", "<unknown>:0")),
                                   prog.root)
            detail = str(f.get("detail", f.get("kind")))
            ctx = str(f.get("lock") or
                      f"{f.get('outer')} -> {f.get('inner')}")
            findings.append(Finding(
                rule=rule, path=path, line=line, col=0, context=ctx,
                message=f"runtime sanitizer: {detail}"))

    coverage: List[Finding] = []
    for outer in sorted(adj):
        for inner, (rel, line, _col, qual) in sorted(adj[outer].items()):
            if (outer, inner) in observed:
                continue
            coverage.append(Finding(
                rule="DS900", path=rel, line=line, col=0, context=qual,
                message=f"static lock-order edge {outer} -> {inner} "
                        "never observed at runtime — the sanitized "
                        "smokes did not drive this path"))
    return findings, coverage


def _sidecar_artifacts(prog: Program) -> List[Dict[str, Any]]:
    arts: List[Dict[str, Any]] = []
    for rel in sorted(prog.summaries):
        if not rel.endswith(".py"):
            continue
        sidecar = os.path.join(prog.root, rel[:-3] + ".observed.json")
        if os.path.isfile(sidecar):
            try:
                arts.extend(load_artifacts(sidecar))
            except (ValueError, json.JSONDecodeError, OSError):
                continue  # a broken sidecar is a fixture bug, not ours
    return arts


def run_global(prog: Program) -> List[Finding]:
    """Fixture-convention pass: cross-check any module that ships a
    ``*.observed.json`` sidecar. The real CI artifact goes through the
    CLI's ``--observed`` instead (tools/analysis/__main__.py), which
    also reports coverage; this pass returns findings only."""
    arts = _sidecar_artifacts(prog)
    if not arts:
        return []
    findings, _coverage = cross_check(prog, arts)
    return findings
