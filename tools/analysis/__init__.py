"""synlint: repo-specific JAX-hygiene + concurrency static analysis.

Two rule families over the package's AST (docs/analysis.md is the rule
catalog):

- **JH (JAX hygiene)** — host syncs on hot paths, Python branching on
  tracer values inside jitted functions, non-hashable static args,
  mutation of ``self``/globals under jit, donated buffers read after
  dispatch. These are the silent TPU-stack killers: none of them raise;
  they recompile, sync, or corrupt instead.
- **CC (concurrency)** — shared fields written off-lock from thread-entry
  functions, inconsistent lock acquisition order (potential deadlock),
  and blocking calls made while holding a lock.

Usage::

    python -m tools.analysis synapseml_tpu tools bench.py --fail-on-new

Inline annotations (comments):

- ``# synlint: disable=JH001[,CC003]`` — suppress on this line (or on a
  bare comment line directly above).
- ``# synlint: shared`` — on a ``self.x = ...`` line: register the field
  as cross-thread shared; every later write must hold a lock (CC001).
- ``# synlint: hotpath`` — on a ``def`` line: treat the function as a
  dispatch-critical hot path for JH001.

Intentionally-kept findings live in ``tools/analysis/baseline.json``;
``--fail-on-new`` fails only on findings not in the baseline, so CI
catches regressions without forcing a big-bang cleanup.
"""
from tools.analysis.engine import analyze_paths
from tools.analysis.findings import Finding, load_baseline, write_baseline

__all__ = ["analyze_paths", "Finding", "load_baseline", "write_baseline"]
