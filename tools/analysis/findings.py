"""Finding records + the committed-baseline format.

A finding's identity must survive unrelated edits, so the fingerprint
hashes (rule, path, enclosing-scope qualname, message) — never the line
number. Identical findings in the same scope (e.g. two unguarded writes
to the same field in one method) are disambiguated by an occurrence
index at comparison time, not inside the fingerprint, so deleting one of
them never orphans the other's baseline entry.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple


@dataclasses.dataclass
class Finding:
    rule: str           # "JH001" ... "CC003"
    path: str           # repo-relative, posix separators
    line: int
    col: int
    context: str        # enclosing function qualname, or "<module>"
    message: str

    def fingerprint(self) -> str:
        blob = "|".join((self.rule, self.path, self.context, self.message))
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [{self.context}]")

    def to_json(self) -> Dict:
        return {"fingerprint": self.fingerprint(), "rule": self.rule,
                "path": self.path, "context": self.context,
                "message": self.message}


BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Counter:
    """Baseline as a multiset of fingerprints (a fingerprint may cover
    several identical findings in one scope)."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    counts: Counter = Counter()
    for entry in data.get("findings", []):
        counts[entry["fingerprint"]] += entry.get("count", 1)
    return counts


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    by_fp: Dict[str, Dict] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp in by_fp:
            by_fp[fp]["count"] += 1
        else:
            entry = f.to_json()
            entry["count"] = 1
            by_fp[fp] = entry
    payload = {
        "version": BASELINE_VERSION,
        "comment": "intentionally-kept synlint findings; regenerate with "
                   "python -m tools.analysis <paths> --write-baseline",
        "findings": sorted(by_fp.values(),
                           key=lambda e: (e["path"], e["rule"],
                                          e["context"], e["message"])),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")


def split_new(findings: List[Finding],
              baseline: Counter) -> Tuple[List[Finding], int]:
    """(new findings, number matched by the baseline). Occurrences of a
    fingerprint beyond its baselined count are new."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched
