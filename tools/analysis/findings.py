"""Finding records + the committed-baseline format.

A finding's identity must survive unrelated edits, so the fingerprint
hashes (rule, path, enclosing-scope qualname, message) — never the line
number. Identical findings in the same scope (e.g. two unguarded writes
to the same field in one method) are disambiguated by an occurrence
index at comparison time, not inside the fingerprint, so deleting one of
them never orphans the other's baseline entry.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple


@dataclasses.dataclass
class Finding:
    rule: str           # "JH001" ... "CC003"
    path: str           # repo-relative, posix separators
    line: int
    col: int
    context: str        # enclosing function qualname, or "<module>"
    message: str

    def fingerprint(self) -> str:
        blob = "|".join((self.rule, self.path, self.context, self.message))
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [{self.context}]")

    def to_json(self) -> Dict:
        return {"fingerprint": self.fingerprint(), "rule": self.rule,
                "path": self.path, "line": self.line, "col": self.col,
                "context": self.context, "message": self.message}


def from_json(data: Dict) -> Finding:
    """Inverse of :meth:`Finding.to_json` (cache deserialization)."""
    return Finding(rule=data["rule"], path=data["path"],
                   line=data.get("line", 0), col=data.get("col", 0),
                   context=data["context"], message=data["message"])


BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Counter:
    """Baseline as a multiset of fingerprints (a fingerprint may cover
    several identical findings in one scope)."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    counts: Counter = Counter()
    for entry in data.get("findings", []):
        counts[entry["fingerprint"]] += entry.get("count", 1)
    return counts


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    by_fp: Dict[str, Dict] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp in by_fp:
            by_fp[fp]["count"] += 1
        else:
            entry = f.to_json()
            entry["count"] = 1
            by_fp[fp] = entry
    payload = {
        "version": BASELINE_VERSION,
        "comment": "intentionally-kept synlint findings; regenerate with "
                   "python -m tools.analysis <paths> --write-baseline",
        "findings": sorted(by_fp.values(),
                           key=lambda e: (e["path"], e["rule"],
                                          e["context"], e["message"])),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")


def load_baseline_entries(path: str) -> List[Dict]:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh).get("findings", [])


def stale_entries(entries: List[Dict], findings: Iterable[Finding],
                  analyzed: Iterable[str], root: str) -> List[Dict]:
    """Baseline entries that no longer match anything: zero current
    occurrences of the fingerprint AND we can actually tell (the entry's
    file was analyzed this run, or no longer exists at all) — a subset
    run must not condemn entries it never looked at."""
    current = Counter(f.fingerprint() for f in findings)
    analyzed_set = set(analyzed)
    out = []
    for entry in entries:
        if current.get(entry["fingerprint"], 0) > 0:
            continue
        path = entry.get("path", "")
        if path in analyzed_set or \
                not os.path.exists(os.path.join(root, path)):
            out.append(entry)
    return out


def prune_baseline(path: str, findings: List[Finding],
                   analyzed: Iterable[str], root: str) -> List[Dict]:
    """Drop stale entries and cap surviving counts at the current
    occurrence count. Returns the dropped entries."""
    entries = load_baseline_entries(path)
    dropped = stale_entries(entries, findings, analyzed, root)
    dead = {e["fingerprint"] for e in dropped}
    current = Counter(f.fingerprint() for f in findings)
    kept = []
    for entry in entries:
        fp = entry["fingerprint"]
        if fp in dead:
            continue
        if current.get(fp, 0) and entry.get("count", 1) > current[fp]:
            entry = dict(entry, count=current[fp])
        kept.append(entry)
    payload = {
        "version": BASELINE_VERSION,
        "comment": "intentionally-kept synlint findings; regenerate with "
                   "python -m tools.analysis <paths> --write-baseline",
        "findings": kept,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return dropped


def split_new(findings: List[Finding],
              baseline: Counter) -> Tuple[List[Finding], int]:
    """(new findings, number matched by the baseline). Occurrences of a
    fingerprint beyond its baselined count are new."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    matched = 0
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            matched += 1
        else:
            new.append(f)
    return new, matched
