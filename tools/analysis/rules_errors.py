"""EH rules: error-handling hygiene — the static complement of the
chaos suite (tests/test_chaos*.py).

EH001  except:/except BaseException that swallows faults.ThreadKilled
EH002  silent broad except (no record, no re-raise, no stated reason)

:class:`synapseml_tpu.runtime.faults.ThreadKilled` is deliberately a
``BaseException`` subclass so injected kills escape every ``except
Exception`` handler and hit the supervision boundary. A bare ``except:``
or ``except BaseException`` that does not re-raise defeats that design:
the chaos framework kills a thread and the handler quietly eats it, so
the fault test passes while the recovery path was never exercised.

EH001 exempts the supervision boundaries themselves (function name
matching ``supervis``/``_pipeline_thread``) — absorbing the kill and
restarting *is* their job. EH002 flags ``except
Exception``-or-broader handlers whose body is pure ``pass``/
``continue``/``break`` with no trailing comment: a swallow nobody will
ever see. The fix is ``blackbox.record(...)`` (or a telemetry counter),
or a trailing comment stating why silence is correct.
"""
from __future__ import annotations

import ast
import re
from typing import List, Set

from tools.analysis.engine import ModuleContext, expr_text
from tools.analysis.findings import Finding

PACK = "errors"

_BOUNDARY_RE = re.compile(r"supervis|_pipeline_thread")
_BROAD = {"Exception", "BaseException"}


def _caught_types(handler: ast.ExceptHandler) -> Set[str]:
    if handler.type is None:
        return {"<bare>"}
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return {expr_text(t).rsplit(".", 1)[-1] for t in types}


def _reraises(handler: ast.ExceptHandler) -> bool:
    """A ``raise`` anywhere in the handler (not inside a nested def)."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _silent_body(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _has_trailing_comment(ctx: ModuleContext,
                          handler: ast.ExceptHandler) -> bool:
    line = ctx.lines[handler.lineno - 1] if \
        handler.lineno <= len(ctx.lines) else ""
    return "#" in line


def run_local(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ctx.nodes:
        if not isinstance(node, ast.ExceptHandler):
            continue
        caught = _caught_types(node)
        qual = ctx.context_for(node).rsplit(".", 1)[-1]
        kills = caught & {"<bare>", "BaseException"}
        if kills and not _reraises(node) and \
                not _BOUNDARY_RE.search(qual):
            what = "bare except:" if "<bare>" in caught else \
                "except BaseException"
            out.append(ctx.finding(
                "EH001", node,
                f"{what} in {qual!r} does not re-raise — it swallows "
                "faults.ThreadKilled and defeats chaos injection; "
                "catch Exception, or record and `raise`"))
            continue  # one finding per handler
        if caught & (_BROAD | {"<bare>"}) and _silent_body(node) and \
                not _has_trailing_comment(ctx, node):
            out.append(ctx.finding(
                "EH002", node,
                f"broad except in {qual!r} swallows the error with no "
                "blackbox.record, counter, or stated reason — record "
                "it, or justify the silence in a trailing comment"))
    return out
