"""Analysis driver: file walking, directive parsing, suppression, and
the whole-program pass.

v1 ran each rule file-by-file; v2 splits every rule pack into a *local*
pass (per-file findings, pure function of one :class:`ModuleContext`)
and an optional *global* pass over a :class:`Program` of serializable
per-module summaries. The summaries are what the content-hash result
cache stores (tools/analysis/cache.py) — an unchanged file contributes
its cached summary to the cross-module analysis without being re-parsed,
so CC001–CC003 can see through helper functions and cross-module lock
acquisitions while the CI job stays fast as the repo grows.

One :class:`ModuleContext` per file carries everything a local rule
needs — the AST, raw source lines, the ``# synlint:`` directive map, and
a node→enclosing-qualname map — so rules stay pure functions from
context to findings.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analysis.findings import Finding

_DIRECTIVE_RE = re.compile(
    r"#\s*synlint:\s*(disable(?:=(?P<rules>[A-Z0-9, ]+))?|shared|hotpath)",
    re.IGNORECASE)

ALL_RULES = "ALL"

# rule-id prefix -> pack name (what bench.py and --json report per pack)
RULE_PACKS = {"JH": "jax", "CC": "concurrency", "RL": "lifecycle",
              "EH": "errors", "EV": "env", "PL": "pallas", "DR": "drift",
              "DS": "dynsan", "SYN": "engine"}


def pack_of(rule: str) -> str:
    return RULE_PACKS.get(rule.rstrip("0123456789"), "other")


def _comment_lines(source: str) -> Dict[int, str]:
    """lineno -> comment text, from the token stream — directives in
    string literals/docstrings must NOT count (a doc mentioning the
    suppression syntax would otherwise suppress that line for real)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass  # ast.parse succeeded, so this is effectively unreachable
    return out


class Directives:
    """Per-line ``# synlint:`` annotations for one file."""

    def __init__(self, source: str):
        self.disable: Dict[int, Set[str]] = {}
        self.shared: Set[int] = set()
        self.hotpath: Set[int] = set()
        for i, text in sorted(_comment_lines(source).items()):
            if "synlint" not in text:
                continue
            for m in _DIRECTIVE_RE.finditer(text):
                word = m.group(1).lower()
                if word.startswith("disable"):
                    rules = m.group("rules")
                    ids = ({r.strip().upper() for r in rules.split(",")
                            if r.strip()} if rules else {ALL_RULES})
                    self.disable.setdefault(i, set()).update(ids)
                elif word == "shared":
                    self.shared.add(i)
                elif word == "hotpath":
                    self.hotpath.add(i)


def build_suppress_map(directives: Directives, lines: Sequence[str],
                       tree: ast.AST) -> Dict[int, Set[str]]:
    """Resolve directives to the exact lines they suppress.

    A directive suppresses its own line; a directive on a *bare comment*
    line suppresses the line below. A decorated ``def``/``class`` is one
    statement spread over several lines, so a suppression landing
    anywhere in the decorator span (including the classic "bare comment
    above the first decorator") covers the whole span *and* the ``def``
    line — the v1 bug was anchoring only to the decorator line, which
    silently failed to suppress findings reported at the ``def``.
    """
    sup: Dict[int, Set[str]] = {}

    def bare_comment(ln: int) -> bool:
        return 1 <= ln <= len(lines) and \
            lines[ln - 1].lstrip().startswith("#")

    for line, ids in directives.disable.items():
        sup.setdefault(line, set()).update(ids)
        if bare_comment(line):
            # a directive opening a comment BLOCK (rationale may take
            # several lines) covers through the first code line below
            ln = line
            while bare_comment(ln) and ln <= len(lines):
                ln += 1
                sup.setdefault(ln, set()).update(ids)
    for node in ast.walk(tree):
        decs = getattr(node, "decorator_list", None)
        if not decs:
            continue
        first = min(d.lineno for d in decs)
        span = range(first, node.lineno + 1)
        ids = set()
        for ln in span:
            ids |= sup.get(ln, set())
        if ids:
            for ln in span:
                sup.setdefault(ln, set()).update(ids)
    return sup


def suppressed_in(sup: Dict[int, Set[str]], line: int, rule: str) -> bool:
    ids = sup.get(line)
    return bool(ids) and (ALL_RULES in ids or rule in ids)


def module_name_for(relpath: str) -> str:
    """Dotted module path for a repo-relative file path."""
    mod = relpath.replace(os.sep, "/")
    if mod.endswith(".py"):
        mod = mod[:-3]
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


class ModuleContext:
    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.module = module_name_for(self.relpath)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.directives = Directives(source)
        self.suppress = build_suppress_map(self.directives, self.lines,
                                           self.tree)
        # flat node list: rules iterate this instead of re-walking the
        # tree (ast.walk per rule made the whole run O(rules * nodes))
        self.nodes = list(ast.walk(self.tree))
        self.qualnames: Dict[ast.AST, str] = {}
        self._map_qualnames(self.tree, "")
        self.imports: Dict[str, str] = {}        # alias -> dotted module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, orig)
        self._map_imports()

    def _map_qualnames(self, node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                self.qualnames[child] = qn
                self._map_qualnames(child, qn)
            else:
                self._map_qualnames(child, prefix)

    def _map_imports(self):
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name)
                    # `from pkg import submodule` also binds a module
                    self.imports.setdefault(
                        alias.asname or alias.name,
                        f"{node.module}.{alias.name}")

    def context_for(self, node: ast.AST) -> str:
        """Qualname of the innermost def/class whose span contains the
        node (line-range containment — cheap and good enough)."""
        return self.context_for_line(getattr(node, "lineno", 0))

    def context_for_line(self, target: int) -> str:
        best, best_span = "<module>", None
        for scope, qn in self.qualnames.items():
            lo = scope.lineno
            hi = getattr(scope, "end_lineno", lo)
            if lo <= target <= hi:
                span = hi - lo
                if best_span is None or span < best_span:
                    best, best_span = qn, span
        return best

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       context=self.context_for(node), message=message)


def walk_shallow(node: ast.AST):
    """Yield ``node`` and descendants WITHOUT entering nested function/
    class definitions — scope-local traversal for scope-local rules."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                stack.append(child)


def expr_name(node: ast.AST) -> str:
    """Stable short identity for a lock/receiver expression: the final
    attribute (``self._lock`` -> ``_lock``) or the bare name — so the
    same field reached through different receivers unifies."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return "<expr>"


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return "<expr>"


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd path must not silently analyze nothing — that
            # reads as "clean" to whoever wired the command
            raise FileNotFoundError(f"synlint: no such path: {p}")
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


# -- whole-program view ----------------------------------------------------

class Program:
    """Every analyzed module's summary plus name-resolution helpers.

    Summaries are plain JSON-able dicts (cache-persistable). Resolution
    is import-based: ``mod.fn()`` resolves through the caller's import
    table, bare ``fn()`` through its from-imports, then same-module
    functions — deliberately NOT bare-name matching across the whole
    repo, which would drown the cross-module rules in aliasing noise.
    """

    def __init__(self, root: str):
        self.root = root
        self.summaries: Dict[str, Dict[str, Any]] = {}  # relpath -> summary
        self._by_module: Dict[str, str] = {}            # dotted -> relpath
        self._by_stem: Dict[str, List[str]] = {}        # basename -> relpaths

    def add(self, summary: Dict[str, Any]):
        rel = summary["path"]
        self.summaries[rel] = summary
        mod = summary.get("module") or module_name_for(rel)
        self._by_module[mod] = rel
        stem = mod.rsplit(".", 1)[-1]
        self._by_stem.setdefault(stem, []).append(rel)

    def module_path(self, dotted: str) -> Optional[str]:
        """relpath of an analyzed module named ``dotted`` (exact dotted
        match, then suffix match, then bare-stem match)."""
        if dotted in self._by_module:
            return self._by_module[dotted]
        tail = "." + dotted
        hits = [rel for mod, rel in self._by_module.items()
                if mod.endswith(tail)]
        if len(hits) == 1:
            return hits[0]
        stems = self._by_stem.get(dotted.rsplit(".", 1)[-1], [])
        return stems[0] if len(stems) == 1 else None

    def functions(self, rel: str) -> List[Dict[str, Any]]:
        return self.summaries.get(rel, {}).get("concurrency", {}).get(
            "functions", [])

    def resolve_call(self, summary: Dict[str, Any], callee: str
                     ) -> List[Tuple[str, Dict[str, Any]]]:
        """Resolve a recorded callee (``"name"`` or ``"alias.name"``)
        to [(relpath, function-record)] candidates."""
        rel = summary["path"]
        out: List[Tuple[str, Dict[str, Any]]] = []
        if "." in callee:
            alias, name = callee.split(".", 1)
            if alias in ("self", "cls"):
                # same-module method (class identity approximated)
                out.extend((rel, fn) for fn in self.functions(rel)
                           if fn["name"] == name)
                return out
            mod = summary.get("imports", {}).get(alias)
            target = self.module_path(mod) if mod else None
            if target:
                out.extend((target, fn) for fn in self.functions(target)
                           if fn["name"] == name)
            return out
        # bare name: from-import first, then same module
        fi = summary.get("from_imports", {}).get(callee)
        if fi:
            target = self.module_path(fi[0])
            if target:
                out.extend((target, fn) for fn in self.functions(target)
                           if fn["name"] == fi[1])
                if out:
                    return out
        out.extend((rel, fn) for fn in self.functions(rel)
                   if fn["name"] == callee)
        return out

    def suppressed(self, path: str, line: int, rule: str) -> bool:
        sup = self.summaries.get(path, {}).get("suppress", {})
        ids = sup.get(str(line)) or sup.get(line)
        return bool(ids) and (ALL_RULES in ids or rule in ids)

    def covers(self, prefix: str) -> bool:
        """True when any analyzed file sits under ``prefix`` — repo-wide
        drift rules only make sense when the package was analyzed."""
        return any(rel.startswith(prefix) for rel in self.summaries)


def _packs():
    from tools.analysis import (rules_concurrency, rules_drift,
                                rules_dynsan, rules_errors, rules_env,
                                rules_jax, rules_lifecycle, rules_pallas)

    return (rules_jax, rules_concurrency, rules_lifecycle, rules_errors,
            rules_env, rules_pallas, rules_drift, rules_dynsan)


def summarize_module(ctx: ModuleContext) -> Dict[str, Any]:
    summary: Dict[str, Any] = {
        "path": ctx.relpath,
        "module": ctx.module,
        "suppress": {str(k): sorted(v) for k, v in ctx.suppress.items()},
        "scopes": sorted(set(ctx.qualnames.values())),
        "imports": dict(ctx.imports),
        "from_imports": {k: list(v) for k, v in ctx.from_imports.items()},
    }
    for pack in _packs():
        fn = getattr(pack, "summarize", None)
        if fn is not None:
            summary[pack.PACK] = fn(ctx)
    return summary


def run_local_rules(ctx: ModuleContext) -> List[Finding]:
    raw: List[Finding] = []
    for pack in _packs():
        fn = getattr(pack, "run_local", None)
        if fn is not None:
            raw.extend(fn(ctx))
    raw.sort(key=lambda f: (f.line, f.col, f.rule))
    return [f for f in raw
            if not suppressed_in(ctx.suppress, f.line, f.rule)]


def analyze_program(paths: Sequence[str], root: Optional[str] = None,
                    cache=None) -> Tuple[List[Finding], Program,
                                         Dict[str, int]]:
    """Run local rules per file (cache-served when the content hash
    matches) then global rules over the assembled Program. Returns
    (findings, program, stats). Unparseable files yield a single SYN000
    finding instead of crashing the run."""
    root = root or os.getcwd()
    prog = Program(root)
    findings: List[Finding] = []
    stats = {"files": 0, "cache_hits": 0, "cache_misses": 0}
    for fpath in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fpath),
                              root).replace(os.sep, "/")
        stats["files"] += 1
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                rule="SYN000", path=rel, line=1, col=0,
                context="<module>",
                message=f"unparseable file: {e.__class__.__name__}"))
            prog.add({"path": rel, "suppress": {}, "scopes": []})
            continue
        entry = cache.lookup(rel, source) if cache is not None else None
        if entry is not None:
            stats["cache_hits"] += 1
            summary, local = entry
        else:
            stats["cache_misses"] += 1
            try:
                ctx = ModuleContext(fpath, rel, source)
            except SyntaxError as e:
                local = [Finding(
                    rule="SYN000", path=rel, line=1, col=0,
                    context="<module>",
                    message=f"unparseable file: {e.__class__.__name__}")]
                summary = {"path": rel, "module": module_name_for(rel),
                           "suppress": {}, "scopes": []}
            else:
                local = run_local_rules(ctx)
                summary = summarize_module(ctx)
            if cache is not None:
                cache.store(rel, source, summary, local)
        prog.add(summary)
        findings.extend(local)
    for pack in _packs():
        fn = getattr(pack, "run_global", None)
        if fn is None:
            continue
        findings.extend(
            f for f in fn(prog)
            if not prog.suppressed(f.path, f.line, f.rule))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, prog, stats


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> List[Finding]:
    """v1-compatible entry point: findings only, no cache."""
    findings, _prog, _stats = analyze_program(paths, root=root)
    return findings
