"""Analysis driver: file walking, directive parsing, suppression.

One :class:`ModuleContext` per file carries everything a rule needs —
the AST, raw source lines, the ``# synlint:`` directive map, and a
node→enclosing-qualname map — so rules stay pure functions from context
to findings.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.analysis.findings import Finding

_DIRECTIVE_RE = re.compile(
    r"#\s*synlint:\s*(disable(?:=(?P<rules>[A-Z0-9, ]+))?|shared|hotpath)",
    re.IGNORECASE)

ALL_RULES = "ALL"


def _comment_lines(source: str) -> Dict[int, str]:
    """lineno -> comment text, from the token stream — directives in
    string literals/docstrings must NOT count (a doc mentioning the
    suppression syntax would otherwise suppress that line for real)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass  # ast.parse succeeded, so this is effectively unreachable
    return out


class Directives:
    """Per-line ``# synlint:`` annotations for one file."""

    def __init__(self, source: str):
        self.disable: Dict[int, Set[str]] = {}
        self.shared: Set[int] = set()
        self.hotpath: Set[int] = set()
        for i, text in sorted(_comment_lines(source).items()):
            if "synlint" not in text:
                continue
            for m in _DIRECTIVE_RE.finditer(text):
                word = m.group(1).lower()
                if word.startswith("disable"):
                    rules = m.group("rules")
                    ids = ({r.strip().upper() for r in rules.split(",")
                            if r.strip()} if rules else {ALL_RULES})
                    self.disable.setdefault(i, set()).update(ids)
                elif word == "shared":
                    self.shared.add(i)
                elif word == "hotpath":
                    self.hotpath.add(i)

    def suppressed(self, line: int, rule: str,
                   lines: Sequence[str]) -> bool:
        """A finding is suppressed by a directive on its own line, or on
        a bare comment line directly above it."""
        for cand in (line, line - 1):
            ids = self.disable.get(cand)
            if not ids:
                continue
            if cand == line - 1 and not lines[cand - 1].lstrip().startswith("#"):
                continue  # code line above: its directive is its own
            if ALL_RULES in ids or rule in ids:
                return True
        return False


class ModuleContext:
    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.directives = Directives(source)
        # flat node list: rules iterate this instead of re-walking the
        # tree (ast.walk per rule made the whole run O(rules * nodes))
        self.nodes = list(ast.walk(self.tree))
        self.qualnames: Dict[ast.AST, str] = {}
        self._map_qualnames(self.tree, "")

    def _map_qualnames(self, node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                self.qualnames[child] = qn
                self._map_qualnames(child, qn)
            else:
                self._map_qualnames(child, prefix)

    def context_for(self, node: ast.AST) -> str:
        """Qualname of the innermost def/class whose span contains the
        node (line-range containment — cheap and good enough)."""
        best, best_span = "<module>", None
        target = getattr(node, "lineno", 0)
        for scope, qn in self.qualnames.items():
            lo = scope.lineno
            hi = getattr(scope, "end_lineno", lo)
            if lo <= target <= hi:
                span = hi - lo
                if best_span is None or span < best_span:
                    best, best_span = qn, span
        return best

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       context=self.context_for(node), message=message)


def walk_shallow(node: ast.AST):
    """Yield ``node`` and descendants WITHOUT entering nested function/
    class definitions — scope-local traversal for scope-local rules."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                stack.append(child)


def expr_name(node: ast.AST) -> str:
    """Stable short identity for a lock/receiver expression: the final
    attribute (``self._lock`` -> ``_lock``) or the bare name — so the
    same field reached through different receivers unifies."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return "<expr>"


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return "<expr>"


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd path must not silently analyze nothing — that
            # reads as "clean" to whoever wired the command
            raise FileNotFoundError(f"synlint: no such path: {p}")
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> List[Finding]:
    """Run every rule over every ``.py`` under ``paths``; suppressed
    findings are already filtered. Unparseable files yield a single
    SYN000 finding instead of crashing the run."""
    from tools.analysis import rules_concurrency, rules_jax

    root = root or os.getcwd()
    findings: List[Finding] = []
    for fpath in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fpath), root)
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
            ctx = ModuleContext(fpath, rel, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                rule="SYN000", path=rel.replace(os.sep, "/"), line=1,
                col=0, context="<module>",
                message=f"unparseable file: {e.__class__.__name__}"))
            continue
        raw: List[Finding] = []
        raw.extend(rules_jax.run(ctx))
        raw.extend(rules_concurrency.run(ctx))
        raw.sort(key=lambda f: (f.line, f.col, f.rule))
        findings.extend(
            f for f in raw
            if not ctx.directives.suppressed(f.line, f.rule, ctx.lines))
    return findings
