"""Generate the committed speech fixture (tests/fixtures/utterances.wav).

A canonical 16 kHz mono 16-bit WAV with three tone-burst "utterances"
separated by silence — the smallest input exercising the whole speech
scenario chain: WavStream format asserts -> energy endpointer (3
segments) -> on-device log-mel AudioFeaturizer -> recurrent model ->
per-utterance rows (ref: SpeechToTextSDK.scala + AudioStreams.scala:94;
the reference streams such audio to the Azure SDK).

Deterministic (fixed freqs/amplitudes, no RNG): regeneration is
bit-for-bit reproducible.

Run from the repo root:  python tools/make_audio_fixture.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from synapseml_tpu.cognitive.speech import pcm_to_wav  # noqa: E402

SR = 16000
FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures")


def build_pcm() -> np.ndarray:
    def tone(freq, ms, amp):
        t = np.arange(int(SR * ms / 1000)) / SR
        # short fade-in/out so segment boundaries are clean
        env = np.minimum(1.0, np.minimum(t, t[::-1]) / 0.01)
        return amp * env * np.sin(2 * np.pi * freq * t)

    def silence(ms):
        return np.zeros(int(SR * ms / 1000))

    x = np.concatenate([
        silence(200), tone(440.0, 300, 0.30),
        silence(450), tone(880.0, 420, 0.22),
        silence(500), tone(330.0, 350, 0.35),
        silence(250)])
    return (x * 32767).astype("<i2")


def main():
    os.makedirs(FIXTURES, exist_ok=True)
    wav = pcm_to_wav(build_pcm(), SR)
    path = os.path.join(FIXTURES, "utterances.wav")
    with open(path, "wb") as fh:
        fh.write(wav)
    print(f"wrote {path} ({len(wav)} bytes)")


if __name__ == "__main__":
    sys.exit(main())
