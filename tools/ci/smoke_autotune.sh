#!/usr/bin/env bash
# Round-16 autotuner canary (runtime/autotune.py, docs/perf.md "Round
# 16 — the autotuner"): the registry's contract suite runs under a hard
# wall, then the fleet-sharing cycle is proven ACROSS PROCESSES on one
# cache volume — process A probes a demo lane once (reference python
# loop vs numpy sum, bit-equal, numpy deterministically faster) and
# persists the verdict; process B serves the SAME choice with zero
# probes; SYNAPSEML_AUTOTUNE=0 serves the reference with zero probes
# and zero table I/O, the route counter proving every decision. Kill
# switch and fleet sharing are load-bearing, not decorative.
#
# Usage: tools/ci/smoke_autotune.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."

timeout -k 10 "${SMOKE_TIMEOUT:-420}" env JAX_PLATFORMS=cpu \
  python -m pytest tests/test_autotune.py -q -p no:cacheprovider

CACHE_DIR="$(mktemp -d)"
KILL_DIR="$(mktemp -d)"
trap 'rm -rf "$CACHE_DIR" "$KILL_DIR"' EXIT

DEMO_LANE='
import json, os, sys
import numpy as np
from synapseml_tpu.runtime import autotune

def _py_sum(rargs, args):
    def run(x):
        total = np.int64(0)
        for v in x.tolist():
            total += np.int64(v)
        return np.int64(total)
    return run

def _np_sum(rargs, args):
    return lambda x: x.sum(dtype=np.int64)

lane = autotune.register_lane(
    "smoke_sum",
    key_fn=lambda n: f"smoke|{n}",
    candidates={"python": _py_sum, "numpy": _np_sum},
    reference="python",
    args_fn=lambda n: (np.arange(n, dtype=np.int64),),
)
choice = lane.route(200_000)
from synapseml_tpu.runtime import telemetry
counters = telemetry.snapshot()["counters"]
routed = counters.get(
    "synapseml_autotune_route_total"
    "{choice=\"%s\",lane=\"smoke_sum\"}" % choice, 0)
print(json.dumps({"choice": choice, "probes": lane.probes,
                  "counter": routed,
                  "table": os.path.exists(lane.table.path())}))
'

# Phase A: first process pays the probe and persists the verdict
A=$(timeout -k 10 120 env JAX_PLATFORMS=cpu \
      SYNAPSEML_TPU_CACHE_DIR="$CACHE_DIR" python -c "$DEMO_LANE" | tail -1)
echo "phase A: $A"
python - "$A" <<'PY'
import json, sys
got = json.loads(sys.argv[1])
assert got["probes"] == 1, got
assert got["choice"] == "numpy", got   # bit-equal and measurably faster
assert got["counter"] >= 1, got
assert got["table"], got               # verdict persisted for the fleet
print("phase A ok: probed once, numpy won, verdict on disk")
PY

# Phase B: a FRESH process on the same volume serves the verdict with
# zero probes — the fleet-shared half of the contract
B=$(timeout -k 10 120 env JAX_PLATFORMS=cpu \
      SYNAPSEML_TPU_CACHE_DIR="$CACHE_DIR" python -c "$DEMO_LANE" | tail -1)
echo "phase B: $B"
python - "$B" <<'PY'
import json, sys
got = json.loads(sys.argv[1])
assert got["probes"] == 0, got
assert got["choice"] == "numpy", got
print("phase B ok: zero probes, same choice adopted from the volume")
PY

# Phase C: kill switch — reference serves, zero probes, zero table I/O
C=$(timeout -k 10 120 env JAX_PLATFORMS=cpu SYNAPSEML_AUTOTUNE=0 \
      SYNAPSEML_TPU_CACHE_DIR="$KILL_DIR" python -c "$DEMO_LANE" | tail -1)
echo "phase C: $C"
python - "$C" <<'PY'
import json, sys
got = json.loads(sys.argv[1])
assert got["probes"] == 0, got
assert got["choice"] == "python", got  # the reference, by fiat
assert got["counter"] >= 1, got        # decisions still counted
assert not got["table"], got           # no table I/O under the switch
print("phase C ok: kill switch serves the reference, zero probes")
PY

echo "autotune smoke ok"
