"""Decode-serving proof (docs/serving.md "Decode serving"): the round-19
contracts on a REAL --decode serving subprocess, on a FORCED 8-device
CPU platform (smoke_decode.sh sets XLA_FLAGS), under a KV capacity tiny
enough that continuous-batching traffic MUST evict and recompute —

1. mixed prefill/decode traffic: concurrent streamed + non-streamed
   /generate clients with varied prompt/output lengths, so every
   scheduler iteration mixes prefill chunks with single-token steps;
2. streamed replies carry the provenance headers BEFORE the first
   token (X-Request-Id echo + W3C traceparent), then per-token NDJSON
   lines and a final line whose digest equals the non-stream digest
   for the same prompt;
3. the PR-10 recompile sentinel (executor_recompiles_total) reads ZERO
   after warmup across admissions, retirements, evictions and
   recomputes — the fixed compile geometry held;
4. the tiny SYNAPSEML_KV_CAPACITY_BYTES forces evictions
   (kv_evictions_total > 0, kv_recomputes_total > 0) and an evicted
   sequence's re-prefilled reply must be BIT-IDENTICAL to the same
   prompt scored solo before the storm (digest equality — greedy
   decode over position-exact recompute);
5. after a SIGTERM drain, the captured non-stream traffic replays
   against a FRESH decode replica (normal capacity, no evictions) via
   tools/replay.py --serve: every record reproduces its digest, and a
   deliberately perturbed record makes the harness exit 2.

Driven by tools/ci/smoke_decode.sh under a hard timeout: a wedged
warmup, a starved admission queue, or a livelocked eviction loop hangs
rather than fails, so it becomes a fast exit-124.
"""
import base64
import hashlib
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

# tiny_decoder KV economics: 2 layers x (K+V) x 2 kv-heads x 8 head-dim
# x f32 = 256 B/token -> page(8) = 2 KiB. 12 pages ~ 2.5 sequences of
# the ~35-token totals below: with 4 batch slots the cache CANNOT hold
# a full batch, so decode-step growth must evict (the livelock-free
# path: admission never evicts, growth does).
KV_CAPACITY = str(12 * 8 * 256)


def series_total(text: str, name: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def get(url: str, timeout: float = 15.0):
    with urllib.request.urlopen(urllib.request.Request(url),
                                timeout=timeout) as r:
        return r.status, r.read()


def generate(base: str, tokens, max_new, stream=False, rid=None,
             timeout: float = 120.0):
    """One /generate POST -> (status, body_bytes, headers_dict)."""
    obj = {"tokens": tokens, "max_new_tokens": max_new}
    if stream:
        obj["stream"] = True
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(base + "/generate",
                                 data=json.dumps(obj).encode(),
                                 method="POST", headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, body, dict(e.headers.items()) if e.headers else {}


def prompt_for(i: int, n: int):
    return [(i * 7 + k * 3) % 50 + 1 for k in range(n)]


def launch(model_path, cache_dir, dump_dir, name, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "synapseml_tpu.io.serving",
         "--host", "127.0.0.1", "--port", "0", "--name", name,
         "--model", model_path, "--decode", "--cache-dir", cache_dir,
         "--dump-dir", dump_dir, "--drain-timeout-ms", "8000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    url_box, url_found = {}, threading.Event()

    def read_stdout():
        for line in proc.stdout:
            sys.stdout.write("  [srv] " + line)
            if not url_found.is_set():
                m = re.search(r"serving \[.*\] on (http://\S+/)", line)
                if m:
                    url_box["url"] = m.group(1)
                    url_found.set()

    threading.Thread(target=read_stdout, daemon=True).start()
    if not url_found.wait(420.0):
        proc.kill()
        raise RuntimeError(f"{name}: never announced its URL")
    return proc, url_box["url"].rstrip("/")


def main() -> int:
    from synapseml_tpu.onnx import zoo

    work = tempfile.mkdtemp(prefix="decode_proof_")
    model_path = os.path.join(work, "tiny_decoder.onnx")
    with open(model_path, "wb") as fh:
        fh.write(zoo.tiny_decoder())
    cache_dir = os.path.join(work, "cache")
    cap_dir = os.path.join(work, "capture")

    env = dict(os.environ)
    env.pop("SYNAPSEML_FAULTS", None)
    env.setdefault("PYTHONPATH", os.getcwd())
    env["SYNAPSEML_CAPTURE_HEAD_SAMPLE"] = "1.0"  # keep every reply
    env["SYNAPSEML_DECODE_MAX_BATCH"] = "4"
    env["SYNAPSEML_DECODE_PREFILL_CHUNK"] = "8"
    env["SYNAPSEML_KV_PAGE"] = "8"
    env["SYNAPSEML_DECODE_MAX_SEQ"] = "64"
    env["SYNAPSEML_KV_CAPACITY_BYTES"] = KV_CAPACITY

    proc, base = launch(model_path, cache_dir, cap_dir,
                        "decode_smoke", env)
    capture_file = os.path.join(cap_dir, f"capture-{proc.pid}.jsonl")
    try:
        print(f"decode replica up at {base}", flush=True)
        _, m0 = get(base + "/metrics")
        if series_total(m0.decode(),
                        "synapseml_executor_recompiles_total") != 0:
            print("FAIL: recompiles nonzero straight after warmup")
            return 1

        # solo references BEFORE the storm: prompts the concurrent
        # phase re-sends; their digests must not move under eviction
        ref = {}
        for i in (0, 1):
            st, body, hdr = generate(base, prompt_for(i, 24), 12)
            digest = hdr.get("X-Output-Digest")
            if st != 200 or not digest or digest != hashlib.sha256(
                    body).hexdigest():
                print(f"FAIL: solo reference {i}: status {st}, "
                      f"digest {digest!r}")
                return 1
            ref[i] = digest

        # streamed provenance: headers precede the first token line
        st, sbody, shdr = generate(base, prompt_for(0, 24), 12,
                                   stream=True, rid="rid-stream-0")
        if st != 200 or shdr.get("X-Request-Id") != "rid-stream-0" \
                or not shdr.get("traceparent"):
            print(f"FAIL: streamed reply provenance: status {st}, "
                  f"headers {shdr}")
            return 1
        lines = sbody.decode().strip().split("\n")
        fin = json.loads(lines[-1])
        toks = [json.loads(ln)["t"] for ln in lines[:-1]]
        if not fin.get("done") or fin.get("n") != len(toks):
            print(f"FAIL: streamed framing: {lines[-1]!r}, "
                  f"{len(toks)} token lines")
            return 1
        if fin.get("digest") != ref[0]:
            print(f"FAIL: streamed digest {fin.get('digest')!r} != "
                  f"non-stream {ref[0]!r} for the same prompt")
            return 1
        print("stream provenance ok (rid + traceparent + "
              "digest-carrying final line)", flush=True)

        # the storm: 12 concurrent mixed-length clients (every 3rd
        # streamed) against a ~2.5-sequence cache — guaranteed
        # eviction/recompute churn; clients 0/1 re-send the reference
        # prompts mid-storm
        results = [None] * 12

        def client(i):
            if i < 2:
                toks, n = prompt_for(i, 24), 12
            else:
                toks, n = prompt_for(i, 8 + (i % 3) * 8), 6 + (i % 4) * 4
            results[i] = (i, *generate(base, toks, n,
                                       stream=(i % 3 == 2)))

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        if any(r is None for r in results):
            print("FAIL: a storm client hung")
            return 1
        bad = [(i, st) for i, st, _b, _h in results if st != 200]
        if bad:
            print(f"FAIL: storm statuses: {bad}")
            return 1
        for i in (0, 1):
            _, _, body, hdr = results[i]
            if hdr.get("X-Output-Digest") != ref[i]:
                print(f"FAIL: prompt {i} digest moved under eviction "
                      f"churn: {hdr.get('X-Output-Digest')!r} != "
                      f"{ref[i]!r} — recompute is NOT bit-identical")
                return 1

        _, m1 = get(base + "/metrics")
        after = m1.decode()
        recompiles = series_total(
            after, "synapseml_executor_recompiles_total")
        evictions = series_total(after, "synapseml_kv_evictions_total")
        recomputes = series_total(after,
                                  "synapseml_kv_recomputes_total")
        prefills = series_total(
            after, 'synapseml_decode_steps_total{phase="prefill"')
        decodes = series_total(
            after, 'synapseml_decode_steps_total{phase="decode"')
        if recompiles != 0:
            print(f"FAIL: {recompiles:.0f} post-warmup recompiles — "
                  "the fixed compile geometry leaked")
            return 1
        if evictions < 1 or recomputes < 1:
            print(f"FAIL: the tiny cache did not churn (evictions="
                  f"{evictions:.0f} recomputes={recomputes:.0f}) — "
                  "the eviction path went untested")
            return 1
        if prefills < 1 or decodes < 1:
            print("FAIL: traffic was not mixed prefill/decode")
            return 1
        print(f"storm ok: 12/12 scored, {evictions:.0f} evictions, "
              f"{recomputes:.0f} recomputes, digests stable, "
              "0 recompiles", flush=True)

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=40)
        if rc != 0:
            print(f"FAIL: serving exited {rc}")
            return 1

        # --- live replay against a FRESH replica --------------------
        # normal capacity (no evictions): the captured digests — some
        # produced THROUGH an evict/recompute cycle — must reproduce
        # on a clean cache. Streamed records are dropped (their digest
        # rides the final NDJSON line, not the header --serve
        # compares); so are admission 429s.
        replay_file = os.path.join(work, "replay.jsonl")
        kept = 0
        with open(capture_file, encoding="utf-8") as src, \
                open(replay_file, "w", encoding="utf-8") as dst:
            for line in src:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("status_code") != 200:
                    continue
                raw = rec.get("payload")
                if raw is None:
                    try:
                        raw = base64.b64decode(
                            rec.get("payload_b64") or "").decode()
                    except (ValueError, UnicodeDecodeError):
                        continue
                try:
                    payload = json.loads(raw)
                except (ValueError, json.JSONDecodeError):
                    continue
                if payload.get("stream"):
                    continue
                dst.write(json.dumps(rec) + "\n")
                kept += 1
        if kept < 8:
            print(f"FAIL: only {kept} non-stream 200s captured")
            return 1

        env2 = dict(env)
        env2["SYNAPSEML_KV_CAPACITY_BYTES"] = ""
        proc2, base2 = launch(model_path, cache_dir, os.path.join(
            work, "capture2"), "decode_replay", env2)
        try:
            rp = subprocess.run(
                [sys.executable, "tools/replay.py", replay_file,
                 "--serve", base2 + "/generate"],
                capture_output=True, text=True, env=env, timeout=420)
            print(rp.stdout.strip(), flush=True)
            if rp.returncode != 0:
                print(f"FAIL: live replay exited {rp.returncode}: "
                      f"{rp.stderr[-1500:]}")
                return 1

            # a perturbed record must exit 2 with the rid named
            perturbed = os.path.join(work, "perturbed.jsonl")
            flipped = None
            with open(replay_file, encoding="utf-8") as src, \
                    open(perturbed, "w", encoding="utf-8") as dst:
                for line in src:
                    rec = json.loads(line)
                    if flipped is None:
                        rec["output_digest"] = "0" * 64
                        flipped = rec["rid"]
                    dst.write(json.dumps(rec) + "\n")
            rp2 = subprocess.run(
                [sys.executable, "tools/replay.py", perturbed,
                 "--serve", base2 + "/generate"],
                capture_output=True, text=True, env=env, timeout=420)
            if rp2.returncode != 2 or flipped not in rp2.stdout:
                print(f"FAIL: perturbed replay exited "
                      f"{rp2.returncode} (wanted 2) or did not name "
                      f"rid {flipped}: {rp2.stdout[-800:]}")
                return 1
        finally:
            if proc2.poll() is None:
                proc2.send_signal(signal.SIGTERM)
                try:
                    proc2.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc2.kill()

        # --- the A/B tripwire: continuous must beat static ----------
        # in-process (no server), CI-sized; >= 1.2x is the policy-
        # inversion bound, headroom under the bench's measured 1.82x
        from bench import bench_decode_serving

        (cont_tps, stat_tps, *_rest, detail) = bench_decode_serving()
        ratio = cont_tps / max(stat_tps, 1e-9)
        if ratio < 1.2:
            print(f"FAIL: continuous batching only {ratio:.2f}x static "
                  f"({detail}) — iteration-level admission regressed")
            return 1
        print(f"decode proof ok: digests stable across "
              f"{recomputes:.0f} recomputes, 0 recompiles, {kept} "
              f"records replayed bit-identical on a fresh replica, "
              f"perturbed rid {flipped[:8]}... exits 2, continuous "
              f"{ratio:.2f}x static")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
