"""Chaos smoke: serve a real executor-backed pipeline with PROBABILISTIC
compute faults injected via the ``SYNAPSEML_FAULTS`` env var (set by
tools/ci/smoke_chaos.sh before the interpreter starts, so the
import-time env path itself is under test), drive concurrent load, then
deterministically kill the executor's drain thread mid-flight.

Asserts (docs/robustness.md):
- every client gets a terminal response — no request ever hangs;
- non-faulted requests still succeed (correct payloads, and bisection
  re-scores mean most faulted batches recover too);
- a request with an already-expired deadline is shed 504;
- after a drain-thread kill, supervision restarts the pipeline and the
  serving retry masks the break (client sees 200);
- GET /metrics shows the injections, restarts, and sheds.

Driven under a hard timeout: a wedged pipeline hangs rather than fails,
so it becomes a fast exit-124 instead of a stuck job.
"""
import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np

REQUESTS_PER_CLIENT = 25
CLIENTS = 4


def post(url, obj, headers=None, timeout=60):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, None


def series_total(text: str, name: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def main() -> int:
    spec = os.environ.get("SYNAPSEML_FAULTS", "")
    if "compute" not in spec:
        print("SYNAPSEML_FAULTS must arm a compute fault "
              f"(got {spec!r}) — run via tools/ci/smoke_chaos.sh")
        return 2

    from synapseml_tpu.io.serving import ContinuousServer, make_reply
    from synapseml_tpu.runtime import faults as flt
    from synapseml_tpu.runtime.executor import BatchedExecutor

    assert "compute" in flt.active(), \
        "env-armed fault did not survive import"

    ex = BatchedExecutor(lambda x: (x * 3.0 + 1.0,), min_bucket=8)

    def pipeline(table):
        feats = np.stack([np.asarray(v["x"], np.float32)
                          for v in table["value"]])
        (out,) = ex(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"y": out[i].tolist()})
        return table.with_column("reply", replies)

    cs = ContinuousServer("chaos_smoke", pipeline, max_batch=16,
                          batch_linger=0.002, retry_transient=1).start()
    try:
        url = cs.url
        host = url.split("//")[1].rstrip("/")

        # -- phase 1: concurrent load under probabilistic compute faults
        results = [[None] * REQUESTS_PER_CLIENT for _ in range(CLIENTS)]

        def client(ci):
            for i in range(REQUESTS_PER_CLIENT):
                results[ci][i] = post(url, {"x": [float(ci), float(i)]})

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                print("FAIL: a load client hung — some request never "
                      "got a terminal response")
                return 1

        flat = [r for row in results for r in row]
        codes = sorted({st for st, _ in flat})
        n_ok = sum(1 for st, _ in flat if st == 200)
        bad = [st for st, _ in flat if st not in (200, 400, 500, 504)]
        if bad:
            print(f"FAIL: unexpected statuses {bad}")
            return 1
        if n_ok == 0:
            print("FAIL: zero non-faulted requests succeeded")
            return 1
        for (st, body), (ci, i) in zip(
                flat, ((c, i) for c in range(CLIENTS)
                       for i in range(REQUESTS_PER_CLIENT))):
            if st == 200 and body["y"] != [ci * 3.0 + 1.0, i * 3.0 + 1.0]:
                print(f"FAIL: wrong payload for ({ci},{i}): {body}")
                return 1

        # -- phase 2: pre-expired deadline is shed before scoring
        st, _ = post(url, {"x": [1.0, 1.0]},
                     headers={"X-Deadline-Ms": "0.01"})
        if st != 504:
            print(f"FAIL: expired-deadline request got {st}, wanted 504")
            return 1

        # -- phase 3: deterministic drain-thread kill mid-flight; the
        # serving retry resubmits against the supervision-restarted
        # pipeline, so the CLIENT still sees 200
        flt.deactivate("compute")  # isolate the kill from random faults
        flt.activate("thread_kill.drain", times=1)
        st, body = post(url, {"x": [2.0, 2.0]})
        if st != 200 or body["y"] != [7.0, 7.0]:
            print(f"FAIL: post-kill request got {st} {body}, wanted "
                  "200 [7.0, 7.0] via retry against restarted pipeline")
            return 1

        conn_req = urllib.request.Request(f"http://{host}/metrics")
        with urllib.request.urlopen(conn_req, timeout=30) as r:
            metrics = r.read().decode()
        checks = {
            "synapseml_faults_injected_total": 1,
            "synapseml_executor_pipeline_restarts_total": 1,
            "synapseml_serving_deadline_shed_total": 1,
            "synapseml_serving_retry_total": 1,
        }
        for name, floor in checks.items():
            got = series_total(metrics, name)
            if got < floor:
                print(f"FAIL: {name} = {got}, wanted >= {floor}")
                return 1

        print(f"chaos smoke ok: {n_ok}/{len(flat)} loaded requests "
              f"succeeded under {spec!r} (codes seen: {codes}), "
              f"restarts="
              f"{series_total(metrics, 'synapseml_executor_pipeline_restarts_total'):.0f}, "
              f"injected="
              f"{series_total(metrics, 'synapseml_faults_injected_total'):.0f}")
        return 0
    finally:
        cs.stop()
        ex.close(wait=False)


if __name__ == "__main__":
    sys.exit(main())
