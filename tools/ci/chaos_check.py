"""Chaos smoke: serve a real executor-backed pipeline with PROBABILISTIC
compute faults injected via the ``SYNAPSEML_FAULTS`` env var (set by
tools/ci/smoke_chaos.sh before the interpreter starts, so the
import-time env path itself is under test), drive concurrent load, then
deterministically kill the executor's drain thread mid-flight.

Asserts (docs/robustness.md):
- every client gets a terminal response — no request ever hangs;
- non-faulted requests still succeed (correct payloads, and bisection
  re-scores mean most faulted batches recover too);
- a request with an already-expired deadline is shed 504;
- after a drain-thread kill, supervision restarts the pipeline and the
  serving retry masks the break (client sees 200);
- GET /metrics shows the injections, restarts, and sheds;
- channel kill under open-loop load (phase 4): with ``compute.channel0``
  armed at prob 1.0 on a 2-channel DistributedServer, loadgen traffic
  keeps flowing — requests on the broken channel fail over to the
  healthy sibling (200, bit-identical), the breaker trips
  CLOSED->OPEN, the half-open probe re-admits the channel once the
  fault is disarmed, and goodput recovers to 100% (asserted via the
  loadgen CLI's --out JSON results + SLO assertion mode);
- the trip auto-produces a FLIGHT DUMP (runtime/blackbox.py) whose
  events include the trip, the failover, and the redisperse with
  matching rids/channel ids plus per-thread stacks, and
  /debug/threads + /debug/flight serve the live picture;
- SIGTERM rolling restart (phase 5): a real serving subprocess under
  loadgen traffic drains on SIGTERM — every accepted request gets a
  real reply, new requests get 503 + Retry-After, the process exits 0
  within its --drain-timeout-ms budget, and its structured JSON log
  (SYNAPSEML_LOG=json) reconstructs a request's life by rid.

Driven under a hard timeout: a wedged pipeline hangs rather than fails,
so it becomes a fast exit-124 instead of a stuck job.
"""
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

REQUESTS_PER_CLIENT = 25
CLIENTS = 4


def post(url, obj, headers=None, timeout=60):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, None


def series_total(text: str, name: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def channel_kill_phase() -> int:
    """Phase 4: kill one channel of a DistributedServer under open-loop
    loadgen traffic; assert failover (200, bit-identical), breaker
    CLOSED->OPEN->HALF_OPEN->CLOSED, goodput recovery, zero hangs —
    AND the incident-diagnosis loop (docs/observability.md): the trip
    auto-produces a flight-recorder dump whose events include the
    trip, the failover, and the redisperse with matching rids/channel
    ids; /debug/threads lists every live scorer thread; and the
    healthy-phase goodput run goes through the loadgen CLI's JSON
    results + SLO assertion mode instead of in-process stdout.
    Requires the ``compute`` family DISARMED (phase 3 does that) so the
    only fault in play is the channel-scoped one."""
    import glob
    import subprocess
    import tempfile

    from synapseml_tpu.io.serving import (BREAKER_CLOSED,
                                          DistributedServer, make_reply)
    from synapseml_tpu.runtime import blackbox as bb
    from synapseml_tpu.runtime import faults as flt
    from tools.loadgen import run_load

    def pipeline(table):
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply(
                {"y": [x * 3.0 + 1.0 for x in v["x"]]})
        return table.with_column("reply", replies)

    # fresh flight-recorder state: phase 3's pipeline-break dump must
    # not eat the trip dump's debounce window, and the dump dir must be
    # ours to glob
    dump_dir = tempfile.mkdtemp(prefix="chaos_flight_")
    bb.set_dump_dir(dump_dir)
    bb.reset()

    ds = DistributedServer("chaos_channels", n_channels=2,
                           breaker_threshold=2, probe_interval=0.1)
    ds.serve(pipeline, max_batch=16, linger=0.002)
    try:
        # latency + exception: each channel0 attempt stalls 150ms THEN
        # fails, so the trip catches requests parked on the channel —
        # the redisperse the flight dump must name rids for
        flt.activate("compute.channel0", prob=1.0, latency_ms=150,
                     exc=flt.FaultInjected)
        # open-loop load against the half-broken server: every request
        # must reach a terminal status, and failover means they succeed
        s = run_load(ds.url, rps=120, duration_s=2.0, shapes=[2, 4, 8],
                     seed=11, timeout=30.0)
        if s["hung"]:
            print(f"FAIL[ch]: {s['hung']} loadgen requests never got a "
                  "terminal response")
            return 1
        bad = [c for c in s["by_status"]
               if c not in ("200", "500", "503")]
        if bad:
            print(f"FAIL[ch]: unexpected statuses {bad} under channel "
                  f"kill ({s['by_status']})")
            return 1
        if s["by_status"].get("200", 0) == 0:
            print(f"FAIL[ch]: zero successes under channel kill "
                  f"({s['by_status']})")
            return 1
        # bit-identity while the fault is STILL armed: a request routed
        # to the broken channel fails over and scores the same numbers
        # a healthy channel produces
        for k in range(6):
            st, body = post(ds.url, {"x": [float(k), 2.0]})
            want = [k * 3.0 + 1.0, 7.0]
            if st != 200 or body["y"] != want:
                print(f"FAIL[ch]: under armed channel0 fault got "
                      f"{st} {body}, wanted 200 {want}")
                return 1
        # quarantined = NOT CLOSED: the trip-woken probe may be
        # mid-pass (HALF_OPEN) at observation time, and the armed
        # fault fails its canary so CLOSED is unreachable
        if ds.channel_state(0) == BREAKER_CLOSED:
            print(f"FAIL[ch]: channel0 breaker state "
                  f"{ds.channel_state(0)}, wanted quarantined "
                  "(OPEN/HALF_OPEN)")
            return 1
        # disarm -> the half-open probe must re-admit the channel
        flt.deactivate("compute.channel0")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                ds.channel_state(0) != BREAKER_CLOSED:
            time.sleep(0.05)
        if ds.channel_state(0) != BREAKER_CLOSED:
            print("FAIL[ch]: probe never re-admitted channel0 after "
                  "the fault was disarmed")
            return 1
        # goodput recovers to 100% on the healed pair — driven through
        # the loadgen CLI in SLO assertion mode, its JSON results file
        # (not stdout) the source of truth: exit 0 means the run met
        # --slo-p99-ms AND --slo-availability on top of zero hangs
        results_json = os.path.join(dump_dir, "loadgen_results.json")
        lg = subprocess.run(
            [sys.executable, os.path.join("tools", "loadgen.py"),
             "--url", ds.url, "--rps", "120", "--duration", "1.0",
             "--shapes", "2", "--seed", "12", "--timeout", "30",
             "--out", results_json,
             "--slo-p99-ms", "2000", "--slo-availability", "0.99"],
            capture_output=True, text=True, timeout=120)
        if lg.returncode != 0:
            print(f"FAIL[ch]: loadgen SLO assertion mode exited "
                  f"{lg.returncode} on the healthy phase:\n"
                  f"{lg.stdout}\n{lg.stderr}")
            return 1
        with open(results_json) as fh:
            s2 = json.load(fh)
        if not s2.get("slo", {}).get("pass"):
            print(f"FAIL[ch]: loadgen results file carries a failed "
                  f"SLO verdict: {s2.get('slo')}")
            return 1
        if s2["hung"] or s2["by_status"].get("200", 0) != s2["scheduled"]:
            print(f"FAIL[ch]: goodput did not recover after re-admit "
                  f"({s2['by_status']}, hung={s2['hung']})")
            return 1

        # -- the incident loop: the trip must have auto-produced a
        # flight dump naming the trip, the failover, and the
        # redisperse, rid/channel-correlated (docs/observability.md)
        dumps = sorted(glob.glob(
            os.path.join(dump_dir, "flight-*breaker_trip*.json")))
        if not dumps:
            print(f"FAIL[ch]: breaker trip produced no flight dump in "
                  f"{dump_dir} (found: "
                  f"{os.listdir(dump_dir)})")
            return 1
        with open(dumps[-1]) as fh:
            flight = json.load(fh)
        evs = flight.get("events", [])

        def _of(kind):
            return [e for e in evs if e.get("event") == kind]

        trips = [e for e in _of("breaker_trip") if e.get("channel") == 0]
        fails_ev = [e for e in _of("failover") if e.get("channel") == 0]
        reds = [e for e in _of("redisperse") if e.get("channel") == 0]
        if not trips:
            print(f"FAIL[ch]: flight dump has no channel-0 "
                  f"breaker_trip event ({[e.get('event') for e in evs]})")
            return 1
        if not fails_ev or not fails_ev[0].get("rids"):
            print(f"FAIL[ch]: flight dump has no rid-carrying "
                  f"channel-0 failover event ({fails_ev})")
            return 1
        if fails_ev[0].get("to_channel") != 1:
            print(f"FAIL[ch]: failover event names to_channel="
                  f"{fails_ev[0].get('to_channel')}, wanted 1")
            return 1
        if not reds or not reds[-1].get("rids"):
            print(f"FAIL[ch]: flight dump has no rid-carrying "
                  f"channel-0 redisperse event ({reds})")
            return 1
        if not flight.get("threads"):
            print("FAIL[ch]: flight dump carries no thread stacks")
            return 1
        dump_threads = {t["name"] for t in flight["threads"]}
        if not any(n.startswith("chan-scorer-chaos_channels")
                   for n in dump_threads):
            print(f"FAIL[ch]: flight dump thread stacks miss the "
                  f"channel scorers ({sorted(dump_threads)})")
            return 1

        # -- /debug/threads must list every live scorer/pipeline thread
        host = ds.url.split("//")[1].rstrip("/")
        with urllib.request.urlopen(
                urllib.request.Request(f"http://{host}/debug/threads"),
                timeout=30) as r:
            live_threads = {t["name"] for t in json.loads(r.read())}
        want_threads = {f"chan-scorer-chaos_channels-{ch}"
                        for ch in range(2)} | {"dist-chaos_channels"}
        missing_t = want_threads - live_threads
        if missing_t:
            print(f"FAIL[ch]: /debug/threads missing live threads "
                  f"{sorted(missing_t)} (got {sorted(live_threads)})")
            return 1
        # -- and /debug/flight serves the same picture live
        with urllib.request.urlopen(
                urllib.request.Request(f"http://{host}/debug/flight"),
                timeout=30) as r:
            live_flight = json.loads(r.read())
        if not live_flight.get("events") or not live_flight.get("threads"):
            print("FAIL[ch]: /debug/flight returned an empty snapshot")
            return 1

        with urllib.request.urlopen(
                urllib.request.Request(f"http://{host}/metrics"),
                timeout=30) as r:
            metrics = r.read().decode()
        # transition COUNTERS, not the gauge: the probe's
        # OPEN->HALF_OPEN->CLOSED bounce is faster than any scrape
        floors = {
            'synapseml_serving_failover_total': 1,
            'synapseml_serving_channel_trips_total': 1,
        }
        for st_name in ("open", "half_open", "closed"):
            floors['synapseml_serving_breaker_transitions_total{'
                   f'channel="0",server="chaos_channels",'
                   f'state="{st_name}"}}'] = 1
        for name, floor in floors.items():
            got = series_total(metrics, name)
            if got < floor:
                print(f"FAIL[ch]: {name} = {got}, wanted >= {floor}")
                return 1
        print(f"channel-kill ok: {s['by_status'].get('200', 0)}"
              f"/{s['scheduled']} under armed channel0 fault, "
              f"failovers="
              f"{series_total(metrics, 'synapseml_serving_failover_total'):.0f}, "
              f"goodput recovered {s2['by_status'].get('200', 0)}"
              f"/{s2['scheduled']}")
        return 0
    finally:
        flt.deactivate("compute.channel0")
        ds.stop()


def sigterm_phase() -> int:
    """Phase 5: SIGTERM a REAL serving subprocess (echo pipeline) under
    open-loop loadgen traffic. Every request started before the signal
    gets a real reply (200 — or 503 if it raced the drain flip); new
    requests during drain get 503 + Retry-After; the process exits 0
    within its --drain-timeout-ms budget. Zero dropped accepted
    requests is THE rolling-restart contract the k8s preStop/
    terminationGracePeriodSeconds wiring depends on."""
    from tools.loadgen import run_load

    env = dict(os.environ)
    env.pop("SYNAPSEML_FAULTS", None)  # the child serves clean
    env.setdefault("PYTHONPATH", os.getcwd())
    # structured logging end-to-end: the child emits the JSON-lines
    # schema (per-request debug events included) on stderr; this check
    # asserts a grep-by-rid reconstructs a request's life
    env["SYNAPSEML_LOG"] = "json"
    env["SYNAPSEML_LOG_LEVEL"] = "debug"
    proc = subprocess.Popen(
        [sys.executable, "-m", "synapseml_tpu.io.serving",
         "--host", "127.0.0.1", "--port", "0", "--name", "chaos_drain",
         "--drain-timeout-ms", "4000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    try:
        # one reader thread for the child's whole stdout: readline
        # blocks, so waiting on an Event (not the read loop) is what
        # makes the announce deadline real — and continuous reading
        # means the child never blocks on a full pipe either
        lines: list = []
        url_box: dict = {}
        url_found = threading.Event()

        def read_stdout():
            for line in proc.stdout:
                lines.append(line)
                if not url_found.is_set():
                    m = re.search(r"serving \[.*\] on (http://\S+/)",
                                  line)
                    if m:
                        url_box["url"] = m.group(1)
                        url_found.set()

        t_reader = threading.Thread(target=read_stdout, daemon=True)
        t_reader.start()
        if not url_found.wait(60.0):
            print("FAIL[term]: serving subprocess never announced its "
                  "URL")
            return 1
        url = url_box["url"]

        t_sig = {}
        completions = []
        lock = threading.Lock()

        def on_result(i, status, dt):
            with lock:
                completions.append((i, status, time.monotonic() - dt))

        def fire_sigterm():
            time.sleep(0.8)
            t_sig["t"] = time.monotonic()
            proc.send_signal(signal.SIGTERM)

        killer = threading.Thread(target=fire_sigterm, daemon=True)
        killer.start()
        s = run_load(url, rps=100, duration_s=1.6, shapes=[2, 4],
                     seed=21, timeout=30.0, on_result=on_result)
        killer.join(timeout=10)
        rc = proc.wait(timeout=20)
        if rc != 0:
            print(f"FAIL[term]: serving subprocess exited {rc}, "
                  "wanted 0")
            return 1
        if s["hung"]:
            print(f"FAIL[term]: {s['hung']} requests never got a "
                  "terminal record")
            return 1
        # client side: a request started before SIGTERM that got an
        # HTTP reply must have gotten a REAL one (200, or 503 if it
        # raced the drain flip) — a 500/504 here is a drop. Socket
        # 'error' records are NOT classified from the client: under
        # load a connection can land in the TCP backlog, never reach
        # the HTTP layer, and get RST when the listener closes — the
        # server never admitted it. Admitted-request drops are caught
        # EXACTLY by the child's exit accounting below.
        dropped = [(i, st) for i, st, started in completions
                   if started < t_sig["t"]
                   and st not in (200, 503, "error")]
        if dropped:
            print(f"FAIL[term]: accepted-before-SIGTERM requests "
                  f"dropped: {dropped[:5]}")
            return 1
        n_ok = s["by_status"].get("200", 0)
        n_drained = s["by_status"].get("503", 0)
        if n_ok == 0:
            print(f"FAIL[term]: zero requests succeeded before drain "
                  f"({s['by_status']})")
            return 1
        if n_drained == 0:
            print(f"FAIL[term]: zero requests saw the drain 503 "
                  f"({s['by_status']}) — SIGTERM landed after the "
                  "load window?")
            return 1
        t_reader.join(timeout=10)  # child exited: stdout hits EOF
        out = "".join(lines)
        if "drain complete" not in out:
            print(f"FAIL[term]: child never logged drain completion:\n"
                  f"{out[-2000:]}")
            return 1
        # server side, exact: every request the HTTP layer admitted
        # committed a terminal reply before exit — THE zero-drop
        # invariant (the counter commits before the socket send, so a
        # client whose connection broke still counts as replied)
        m_acct = re.search(r"exit accounting: admitted=(\d+) "
                           r"replied=(\d+)", out)
        if not m_acct:
            print(f"FAIL[term]: child printed no exit accounting:\n"
                  f"{out[-2000:]}")
            return 1
        admitted, replied = int(m_acct.group(1)), int(m_acct.group(2))
        if admitted != replied:
            print(f"FAIL[term]: {admitted - replied} admitted requests "
                  f"never got a reply (admitted={admitted}, "
                  f"replied={replied})")
            return 1
        # structured-log rid round trip: the child's JSON lines must
        # let a grep by rid reconstruct a request's life — at least
        # one rid with BOTH its "request" and "reply" events
        by_rid: dict = {}
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("rid"):
                by_rid.setdefault(rec["rid"], set()).add(
                    rec.get("event"))
        correlated = [r for r, evs in by_rid.items()
                      if {"request", "reply"} <= evs]
        if not correlated:
            print(f"FAIL[term]: no rid in the child's structured log "
                  f"carries both request and reply events "
                  f"({len(by_rid)} rids seen)")
            return 1
        print(f"sigterm ok: {n_ok} replied, {n_drained} drained-503, "
              f"admitted={admitted}=replied, "
              f"statuses={s['by_status']}, clean exit inside budget")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def fleet_phase() -> int:
    """Fleet chaos (``--fleet``; its own CI job, tools/ci/
    smoke_fleet.sh): the closed telemetry->control loop end to end, on
    REAL serving subprocesses sharing one ExecutableStore.

    1. A fleet controller (tools/fleet/controller.py) brings up 2
       model-scoring replicas sequentially — the first seeds the shared
       compile cache, the second must HYDRATE from it (audit: zero
       recompiles, store hits > 0).
    2. An open-loop Poisson ramp (tools/loadgen.py --targets, one
       arrival clock round-robined across both replicas) pushes duty
       cycle over the policy line -> the controller scales 2->3; the
       new replica must warm-boot recompile-free from the store.
    3. A replica is SIGKILLed mid-load; loadgen's LB-style next-target
       retry keeps the run's SLO assertion (availability >= 0.99)
       green while the controller reaps the corpse.
    4. Distributed tracing (round 16) across the kill: a doomed
       request (pre-expired deadline -> 504, the SLO-breach retention
       class) rides a KNOWN traceparent into the victim right before
       the SIGKILL; after the kill its retry leg rides the SAME
       traceparent into a surviving sibling. GET /fleet/trace/<id>
       must stitch ONE trace with two legs on two replica ids — the
       victim's from the shared TraceArchive (the process is gone; the
       archive testifies), the sibling's live — and the same trace_id
       must appear in the victim's captured structured log and as a
       latency-bucket exemplar on the sibling's OpenMetrics
       exposition. loadgen's --out `slowest` array is consumed the way
       an operator would: its top entry's trace_id resolves via
       /fleet/trace.
    5. The ramp ends; duty collapses -> the controller scales down via
       SIGTERM graceful drain, and the drained child's own exit
       accounting proves zero admitted requests dropped.

    Every scale decision must land in the flight-recorder ring, the
    structured log, and /fleet/metrics — the forensics triple the
    observability PRs built, now driven by a controller instead of an
    operator."""
    import io
    import tempfile

    from synapseml_tpu.onnx import zoo
    from synapseml_tpu.runtime import autoscale as aut
    from synapseml_tpu.runtime import blackbox as bb
    from synapseml_tpu.runtime import structlog as slog
    from tools.fleet.controller import (FleetController,
                                        LocalProcessBackend)

    def get_json(url):
        with urllib.request.urlopen(urllib.request.Request(url),
                                    timeout=10) as r:
            return json.loads(r.read())

    def get_text(url):
        with urllib.request.urlopen(urllib.request.Request(url),
                                    timeout=10) as r:
            return r.read().decode()

    def series_sum(metrics, name, **labels):
        return sum(v for lbl, v in metrics.get(name, ())
                   if all(lbl.get(k) == want
                          for k, want in labels.items()))

    work = tempfile.mkdtemp(prefix="chaos_fleet_")
    model_path = os.path.join(work, "model.onnx")
    with open(model_path, "wb") as fh:
        fh.write(zoo.mlp([16, 32], num_classes=4, seed=0))
    cache_dir = os.path.join(work, "cache")
    # ONE shared forensics dir for the whole fleet: every replica's
    # flight dumps AND trace-archive JSONL land here (--dump-dir), and
    # the controller's /fleet/trace stitches archived legs from it —
    # the surface that survives the SIGKILL below
    flight_dir = os.path.join(work, "flight")
    stderr_dir = os.path.join(work, "stderr")
    replica_env = dict(os.environ)
    replica_env["SYNAPSEML_LOG"] = "json"
    replica_env["SYNAPSEML_LOG_LEVEL"] = "debug"

    bb.reset()
    log_buf = io.StringIO()
    prev_log = slog.set_mode("json", stream=log_buf)

    # CI-shaped policy: the thresholds are tightened so ANY sustained
    # scored traffic reads as saturation on a 2-core runner (duty on a
    # tiny MLP never hits production's 0.75) — the phase proves the
    # LOOP, production tunes the numbers (docs/deployment.md)
    policy = aut.FleetPolicy(
        min_replicas=1, max_replicas=3, duty_high=0.003,
        duty_low=0.0005, burn_high=10.0, up_consecutive=2,
        down_consecutive=16, up_cooldown_s=2.0, down_cooldown_s=2.0,
        stale_after_s=5.0)
    backend = LocalProcessBackend(
        model=model_path, cache_dir=cache_dir, warmup="auto",
        announce_timeout_s=300.0, dump_dir=flight_dir,
        stderr_dir=stderr_dir, env=replica_env)
    controller = FleetController(backend, policy, interval_s=0.4,
                                 initial_replicas=2,
                                 archive_dir=flight_dir)
    base = controller.serve()
    lg_proc = None
    try:
        t0 = time.monotonic()
        controller.start(wait_ready_s=300.0)
        if len(controller.replicas) != 2:
            print(f"FAIL[fleet]: bring-up gave "
                  f"{len(controller.replicas)} replicas, wanted 2")
            return 1
        print(f"fleet up (2 replicas) in {time.monotonic() - t0:.1f}s",
              flush=True)

        # replica 2 must have HYDRATED from the store replica 1 seeded
        status = get_json(base + "/fleet/status")
        hydr = {h["replica"]: h for h in status["hydrations"]}
        second = controller.replicas[1].name
        if hydr.get(second, {}).get("outcome") != "warm":
            print(f"FAIL[fleet]: replica 2 hydration not warm: "
                  f"{hydr.get(second)}")
            return 1

        # open-loop ramp across BOTH replicas: one Poisson clock, LB
        # stand-in round-robin, SLO assertion armed (the loadgen CLI
        # is the source of truth — its --out JSON is what we judge)
        urls = [r.url for r in controller.replicas]
        results_json = os.path.join(work, "fleet_loadgen.json")
        # rps is sized to the CI box, NOT to saturation: the duty
        # thresholds above read any sustained scoring as "scale up",
        # while an overloaded 2-core runner would park hundreds of
        # requests on the victim's queue — a kill then resets parked
        # connections en masse and the failover retries land on an
        # equally saturated sibling (observed: 60 rps -> p99 16s,
        # availability 0.92). The kill resilience being proven is the
        # LB retry path, not overload shedding — chaos phases 1-5 own
        # saturation behavior.
        lg_proc = subprocess.Popen(
            [sys.executable, os.path.join("tools", "loadgen.py"),
             "--targets", ",".join(urls), "--payload-key", "features",
             "--shapes", "16", "--rps", "25", "--duration", "40",
             "--seed", "5", "--timeout", "15",
             "--out", results_json,
             "--slo-availability", "0.99"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)

        # milestone 1: duty crosses the line -> scale-up to 3
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            status = get_json(base + "/fleet/status")
            if len(status["replicas"]) >= 3:
                break
            time.sleep(0.3)
        else:
            print(f"FAIL[fleet]: controller never scaled 2->3 under "
                  f"load (status: {status['aggregates']}, decisions "
                  f"{status['decisions'][-3:]})")
            return 1
        third = controller.replicas[-1]
        print(f"scaled up to 3 ({third.name}) at "
              f"{time.monotonic() - t0:.1f}s", flush=True)

        # milestone 2: the scale-up replica warm-boots from the shared
        # store — ready, ZERO post-warmup recompiles (cache_skew
        # included), zero store skew, store hits prove the bytes came
        # from a sibling's compiles
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            status = get_json(base + "/fleet/status")
            rec = {r["name"]: r for r in status["replicas"]}
            if rec.get(third.name, {}).get("state") == "ready":
                break
            time.sleep(0.3)
        else:
            print(f"FAIL[fleet]: scale-up replica never went ready "
                  f"({status['replicas']})")
            return 1
        m3 = aut.parse_prometheus(
            get_text(third.url.rstrip("/") + "/metrics"))
        recompiles = series_sum(m3,
                                "synapseml_executor_recompiles_total")
        skew = series_sum(m3,
                          "synapseml_compile_cache_store_skew_total")
        hits = series_sum(m3,
                          "synapseml_compile_cache_store_hits_total")
        if recompiles != 0 or skew != 0 or hits < 1:
            print(f"FAIL[fleet]: scale-up replica not a warm boot: "
                  f"recompiles={recompiles} store_skew={skew} "
                  f"store_hits={hits}")
            return 1
        status = get_json(base + "/fleet/status")
        hydr = {h["replica"]: h for h in status["hydrations"]}
        if hydr.get(third.name, {}).get("outcome") != "warm":
            print(f"FAIL[fleet]: scale-up hydration audit not warm: "
                  f"{hydr.get(third.name)}")
            return 1
        print(f"warm boot verified: {third.name} recompiles=0 "
              f"store_hits={hits:.0f}", flush=True)

        # milestone 3: kill a loaded replica MID-LOAD (SIGKILL — a
        # crash, not a drain); loadgen's next-target retry is the LB,
        # the controller reaps the corpse. Right before the kill, a
        # DOOMED first trace leg lands on the victim: a pre-expired
        # deadline rides a known traceparent in and is shed 504 — the
        # SLO-breach retention class — so the victim's TraceArchive
        # (on the shared dir) and its captured structured log both
        # hold the trace when the process dies. The retry leg below
        # reuses the traceparent on a sibling, exactly what loadgen's
        # LB stand-in does on a socket death.
        from synapseml_tpu.runtime import tracearchive as tarch

        victim = controller.replicas[0]
        doomed_tid = "deadbeefcafef00d" * 2
        doomed_tp = f"00-{doomed_tid}-00000000000000aa-01"
        doomed_payload = {"features": [0.5] * 16}
        st, _ = post(victim.url, doomed_payload,
                     headers={"traceparent": doomed_tp,
                              "X-Deadline-Ms": "0.01"})
        if st != 504:
            print(f"FAIL[fleet]: doomed leg on {victim.name} got {st},"
                  " wanted a 504 deadline shed")
            return 1
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                not tarch.scan(doomed_tid, directory=flight_dir):
            time.sleep(0.1)
        victim_legs = tarch.scan(doomed_tid, directory=flight_dir)
        if not victim_legs or victim_legs[0].get("retention") != \
                "slo_breach":
            print(f"FAIL[fleet]: the 504 leg never reached the trace "
                  f"archive under the slo_breach rule ({victim_legs})")
            return 1
        victim.proc.kill()
        print(f"killed {victim.name} mid-load (doomed trace "
              f"{doomed_tid[:8]}... archived first)", flush=True)

        out, _ = lg_proc.communicate(timeout=120)
        if lg_proc.returncode != 0:
            print(f"FAIL[fleet]: loadgen SLO assertion failed under "
                  f"replica kill (exit {lg_proc.returncode}):\n{out}")
            return 1
        with open(results_json) as fh:
            summary = json.load(fh)
        if summary["hung"]:
            print(f"FAIL[fleet]: {summary['hung']} loadgen requests "
                  "never got a terminal record")
            return 1
        if not summary.get("slo", {}).get("pass"):
            print(f"FAIL[fleet]: loadgen SLO verdict failed: "
                  f"{summary.get('slo')}")
            return 1
        if summary.get("failover_retries", 0) < 1:
            print(f"FAIL[fleet]: kill landed but zero failover "
                  f"retries recorded ({summary.get('per_target')})")
            return 1
        print(f"SLO green through the kill: "
              f"{summary['by_status'].get('200', 0)}"
              f"/{summary['scheduled']} ok, "
              f"{summary['failover_retries']} failovers", flush=True)

        # milestone 3b: the distributed-tracing loop across the kill.
        # The retry leg rides the SAME traceparent into a survivor,
        # then /fleet/trace must stitch ONE trace from the sibling's
        # live span and the dead victim's archived 504 leg — two legs,
        # two replica ids, one trace_id.
        survivors = [r for r in controller.replicas
                     if r.alive() and getattr(r, "url", None)]
        if not survivors:
            print("FAIL[fleet]: no surviving replica for the retry leg")
            return 1
        sibling = survivors[0]
        st, _ = post(sibling.url, doomed_payload,
                     headers={"traceparent": doomed_tp})
        if st != 200:
            print(f"FAIL[fleet]: retry leg on {sibling.name} got {st},"
                  " wanted 200")
            return 1
        try:
            stitched = get_json(base + f"/fleet/trace/{doomed_tid}")
        except urllib.error.HTTPError as e:
            print(f"FAIL[fleet]: /fleet/trace/{doomed_tid} answered "
                  f"{e.code}")
            return 1
        legs = stitched.get("legs", [])
        leg_replicas = {leg.get("replica") for leg in legs}
        if len(legs) < 2 or len(leg_replicas) < 2:
            print(f"FAIL[fleet]: stitched trace has {len(legs)} legs "
                  f"on replicas {sorted(leg_replicas)}, wanted >=2 "
                  f"legs on >=2 replicas ({stitched})")
            return 1
        if any(leg.get("trace_id") != doomed_tid for leg in legs):
            print(f"FAIL[fleet]: stitched legs disagree on trace_id "
                  f"({legs})")
            return 1
        if not any(leg.get("source") == "archive"
                   and leg.get("replica") == victim.name
                   for leg in legs):
            print(f"FAIL[fleet]: the dead victim's leg did not come "
                  f"from the trace archive ({legs})")
            return 1
        # the victim's captured structured log still names the trace —
        # grep-by-trace works on a corpse's log
        with open(victim.stderr_path, encoding="utf-8") as fh:
            victim_log = fh.read()
        if doomed_tid not in victim_log:
            print(f"FAIL[fleet]: victim structured log carries no "
                  f"{doomed_tid} line ({victim.stderr_path})")
            return 1
        # ...and the sibling's OpenMetrics exposition links a latency
        # bucket to the same trace via an exemplar
        om = urllib.request.urlopen(urllib.request.Request(
            sibling.url.rstrip("/") + "/metrics",
            headers={"Accept": "application/openmetrics-text"}),
            timeout=10).read().decode()
        if f'trace_id="{doomed_tid}"' not in om:
            print(f"FAIL[fleet]: {sibling.name} OpenMetrics exposition "
                  f"carries no exemplar for the failover trace")
            return 1
        # operator jump-off: loadgen's slowest array resolves straight
        # to /fleet/trace (entries on the dead victim excluded — its
        # unarchived healthy spans died with it)
        surviving_ok = [e for e in summary.get("slowest", [])
                        if e["status"] == "200"
                        and e["target"] != victim.url]
        if not surviving_ok:
            print(f"FAIL[fleet]: loadgen slowest array unusable "
                  f"({summary.get('slowest')})")
            return 1
        top = surviving_ok[0]
        try:
            jump = get_json(base + f"/fleet/trace/{top['trace_id']}")
        except urllib.error.HTTPError as e:
            print(f"FAIL[fleet]: slowest entry {top} did not resolve "
                  f"via /fleet/trace ({e.code})")
            return 1
        if not jump.get("legs"):
            print(f"FAIL[fleet]: slowest entry {top} stitched zero "
                  f"legs")
            return 1
        print(f"trace stitched across the kill: {len(legs)} legs on "
              f"{sorted(leg_replicas)}, victim leg from the archive; "
              f"slowest [{top['latency_s'] * 1e3:.1f}ms {top['rid'][:8]}"
              f"...] resolves via /fleet/trace", flush=True)

        # milestone 4: the ramp is over — duty collapses and the
        # controller scales down via SIGTERM graceful drain; the
        # child's exit accounting is the zero-drop proof
        deadline = time.monotonic() + 60.0
        term = None
        while time.monotonic() < deadline:
            status = get_json(base + "/fleet/status")
            terms = [t for t in status["terminations"]
                     if t.get("reason") == "duty_cycle"]
            if terms:
                term = terms[0]
                break
            time.sleep(0.5)
        if term is None:
            print(f"FAIL[fleet]: no scale-down after the ramp "
                  f"(decisions {status['decisions'][-3:]})")
            return 1
        if term.get("exit_code") != 0 or not term.get("zero_dropped"):
            print(f"FAIL[fleet]: scale-down drain not clean: {term}")
            return 1
        print(f"scale-down drained clean: {term}", flush=True)

        # forensics triple: every scale action in /fleet/metrics, the
        # flight-recorder ring, and the structured log
        fm = aut.parse_prometheus(get_text(base + "/fleet/metrics"))
        ups = series_sum(fm, "synapseml_fleet_scale_events_total",
                         direction="up")
        downs = series_sum(fm, "synapseml_fleet_scale_events_total",
                           direction="down")
        if ups < 3 or downs < 1:  # 2 initial + >=1 duty up, >=1 down
            print(f"FAIL[fleet]: scale-event counters wrong "
                  f"(up={ups}, down={downs})")
            return 1
        if series_sum(fm, "synapseml_process_rss_bytes") <= 0:
            print("FAIL[fleet]: controller process self-telemetry "
                  "missing from /fleet/metrics")
            return 1
        ring = [e.get("event") for e in bb.snapshot()["events"]]
        for want in ("fleet_scale", "fleet_hydration",
                     "fleet_replica_died", "fleet_drain"):
            if want not in ring:
                print(f"FAIL[fleet]: flight-recorder ring has no "
                      f"{want} event ({sorted(set(ring))})")
                return 1
        log_events = set()
        for line in log_buf.getvalue().splitlines():
            try:
                log_events.add(json.loads(line).get("event"))
            except json.JSONDecodeError:
                continue
        if "fleet_scale" not in log_events:
            print(f"FAIL[fleet]: structured log carries no "
                  f"fleet_scale event ({sorted(log_events)})")
            return 1
        print(f"fleet chaos ok: 2->3 warm scale-up, SLO green "
              f"through a replica kill, drain-clean scale-down "
              f"(up={ups:.0f} down={downs:.0f} events)", flush=True)
        return 0
    finally:
        if lg_proc is not None and lg_proc.poll() is None:
            lg_proc.kill()
        controller.stop(drain_replicas=True)
        slog.set_mode(prev_log[0], level=prev_log[1])


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="chaos CI gate")
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the fleet autoscaling chaos phase "
                         "(no SYNAPSEML_FAULTS needed; its own CI "
                         "job, tools/ci/smoke_fleet.sh)")
    args = ap.parse_args(argv)
    if args.fleet:
        return fleet_phase()
    spec = os.environ.get("SYNAPSEML_FAULTS", "")
    if "compute" not in spec:
        print("SYNAPSEML_FAULTS must arm a compute fault "
              f"(got {spec!r}) — run via tools/ci/smoke_chaos.sh")
        return 2

    from synapseml_tpu.io.serving import ContinuousServer, make_reply
    from synapseml_tpu.runtime import faults as flt
    from synapseml_tpu.runtime.executor import BatchedExecutor

    assert "compute" in flt.active(), \
        "env-armed fault did not survive import"

    ex = BatchedExecutor(lambda x: (x * 3.0 + 1.0,), min_bucket=8)

    def pipeline(table):
        feats = np.stack([np.asarray(v["x"], np.float32)
                          for v in table["value"]])
        (out,) = ex(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"y": out[i].tolist()})
        return table.with_column("reply", replies)

    cs = ContinuousServer("chaos_smoke", pipeline, max_batch=16,
                          batch_linger=0.002, retry_transient=1).start()
    try:
        url = cs.url
        host = url.split("//")[1].rstrip("/")

        # -- phase 1: concurrent load under probabilistic compute faults
        results = [[None] * REQUESTS_PER_CLIENT for _ in range(CLIENTS)]

        def client(ci):
            for i in range(REQUESTS_PER_CLIENT):
                results[ci][i] = post(url, {"x": [float(ci), float(i)]})

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                print("FAIL: a load client hung — some request never "
                      "got a terminal response")
                return 1

        flat = [r for row in results for r in row]
        codes = sorted({st for st, _ in flat})
        n_ok = sum(1 for st, _ in flat if st == 200)
        bad = [st for st, _ in flat if st not in (200, 400, 500, 504)]
        if bad:
            print(f"FAIL: unexpected statuses {bad}")
            return 1
        if n_ok == 0:
            print("FAIL: zero non-faulted requests succeeded")
            return 1
        for (st, body), (ci, i) in zip(
                flat, ((c, i) for c in range(CLIENTS)
                       for i in range(REQUESTS_PER_CLIENT))):
            if st == 200 and body["y"] != [ci * 3.0 + 1.0, i * 3.0 + 1.0]:
                print(f"FAIL: wrong payload for ({ci},{i}): {body}")
                return 1

        # -- phase 2: pre-expired deadline is shed before scoring
        st, _ = post(url, {"x": [1.0, 1.0]},
                     headers={"X-Deadline-Ms": "0.01"})
        if st != 504:
            print(f"FAIL: expired-deadline request got {st}, wanted 504")
            return 1

        # -- phase 3: deterministic drain-thread kill mid-flight; the
        # serving retry resubmits against the supervision-restarted
        # pipeline, so the CLIENT still sees 200
        flt.deactivate("compute")  # isolate the kill from random faults
        flt.activate("thread_kill.drain", times=1)
        st, body = post(url, {"x": [2.0, 2.0]})
        if st != 200 or body["y"] != [7.0, 7.0]:
            print(f"FAIL: post-kill request got {st} {body}, wanted "
                  "200 [7.0, 7.0] via retry against restarted pipeline")
            return 1

        conn_req = urllib.request.Request(f"http://{host}/metrics")
        with urllib.request.urlopen(conn_req, timeout=30) as r:
            metrics = r.read().decode()
        checks = {
            "synapseml_faults_injected_total": 1,
            "synapseml_executor_pipeline_restarts_total": 1,
            "synapseml_serving_deadline_shed_total": 1,
            "synapseml_serving_retry_total": 1,
        }
        for name, floor in checks.items():
            got = series_total(metrics, name)
            if got < floor:
                print(f"FAIL: {name} = {got}, wanted >= {floor}")
                return 1

        print(f"chaos smoke ok: {n_ok}/{len(flat)} loaded requests "
              f"succeeded under {spec!r} (codes seen: {codes}), "
              f"restarts="
              f"{series_total(metrics, 'synapseml_executor_pipeline_restarts_total'):.0f}, "
              f"injected="
              f"{series_total(metrics, 'synapseml_faults_injected_total'):.0f}")
    finally:
        cs.stop()
        ex.close(wait=False)

    # -- phase 4: channel kill under open-loop load (loadgen-driven)
    rc = channel_kill_phase()
    if rc:
        return rc
    # -- phase 5: SIGTERM rolling-restart drain on a real subprocess
    return sigterm_phase()


if __name__ == "__main__":
    sys.exit(main())
