#!/usr/bin/env bash
# Hard-timeout smoke for decode serving (runtime/decode.py +
# runtime/kvcache.py, docs/serving.md "Decode serving").
#
# On a FORCED 8-device virtual CPU platform, a real --decode serving
# subprocess takes concurrent mixed prefill/decode traffic under a KV
# capacity tiny enough to force evictions. tools/ci/decode_check.py
# asserts: streamed replies carry rid + traceparent before the first
# token; executor_recompiles_total stays ZERO after warmup (the fixed
# compile geometry); an evicted sequence's recomputed reply is
# BIT-IDENTICAL to its solo reference (digest match); the captured
# traffic replays digest-identical against a fresh replica via
# tools/replay.py --serve (a perturbed record exits 2); and continuous
# batching beats static batching (the policy-inversion tripwire). A
# wedged warmup, starved queue, or eviction livelock HANGS, which the
# timeout turns into a fast exit-124.
#
# Usage: tools/ci/smoke_decode.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8${XLA_FLAGS:+ $XLA_FLAGS}"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"  # bench.py lives at the root
exec timeout -k 10 "${SMOKE_TIMEOUT:-600}" \
  python tools/ci/decode_check.py
