#!/usr/bin/env bash
# Incident capture & deterministic replay proof (tools/ci/replay_check.py,
# docs/observability.md "Incident capture & replay"): a real model-scoring
# serving subprocess captures a poison-isolated 400 and its healthy
# batch-mates under load, then a FRESH interpreter replays the capture
# file offline — bit-identical digests, the poison's 400 reproduced, zero
# post-warmup recompiles (the shared ExecutableStore pays out), and a
# deliberately perturbed record exits 2 with a divergence report.
#
# Hard wall-clock timeout: a wedged warmup/replay hangs rather than
# fails, so it becomes a fast exit-124 instead of a stuck job.
#
# Usage: tools/ci/smoke_replay.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
exec timeout -k 10 "${SMOKE_TIMEOUT:-600}" \
  python tools/ci/replay_check.py
