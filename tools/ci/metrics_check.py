"""Telemetry smoke: serve a real executor-backed pipeline, scrape
``GET /metrics`` MID-RUN twice, and assert the core series are present,
well-formed, and increasing. Driven by tools/ci/smoke_metrics.sh under a
hard timeout (a wedged scrape or pipeline hangs rather than fails).

Exit 0 = every assertion held; any failure prints the offending series
and exits nonzero.
"""
import http.client
import json
import re
import sys

import numpy as np

PROM_LINE = re.compile(
    r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"[+-]?([0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?|inf|nan))$")

# one representative series per instrumented subsystem: executor
# (pipeline stages + dispatch), serving (queue/batching/replies),
# compile cache (registered at import — 0 until a store is configured),
# and the span layer.
CORE_SERIES = [
    "synapseml_compile_cache_store_hits_total",
    "synapseml_compile_cache_store_misses_total",
    "synapseml_serving_requests_total",
    "synapseml_serving_replies_total",
    "synapseml_serving_batch_size",
    "synapseml_serving_queue_wait_seconds",
    "synapseml_serving_queue_depth",
    "synapseml_serving_score_seconds",
    "synapseml_executor_submit_total",
    "synapseml_executor_dispatch_total",
    "synapseml_executor_bucket_total",
    "synapseml_executor_stage_seconds",
    "synapseml_executor_compute_seconds",
    "synapseml_executor_drain_seconds",
    "synapseml_executor_inflight_batches",
    "synapseml_request_stage_seconds",
]

INCREASING = [
    "synapseml_serving_requests_total",
    "synapseml_executor_submit_total",
]


def series_total(text: str, name: str) -> float:
    """Sum every sample of one family (any label set)."""
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def main() -> int:
    from synapseml_tpu.io.serving import ContinuousServer, make_reply
    from synapseml_tpu.runtime.executor import BatchedExecutor

    ex = BatchedExecutor(lambda x: (x * 3.0 + 1.0,), min_bucket=8)

    def pipeline(table):
        feats = np.stack([np.asarray(v["x"], np.float32)
                          for v in table["value"]])
        (out,) = ex(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"y": out[i].tolist()})
        return table.with_column("reply", replies)

    cs = ContinuousServer("metrics_smoke", pipeline, max_batch=16).start()
    try:
        host = cs.url.split("//")[1].rstrip("/")
        conn = http.client.HTTPConnection(host, timeout=30)

        def post():
            conn.request("POST", "/", json.dumps({"x": [1.0, 2.0]}).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 200, (resp.status, body)
            return resp

        def scrape() -> str:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            assert resp.status == 200, resp.status
            ctype = resp.getheader("Content-Type", "")
            assert ctype.startswith("text/plain"), ctype
            return text

        for _ in range(5):
            post()
        first = scrape()  # mid-run: the server keeps serving after this

        bad = [ln for ln in first.rstrip("\n").splitlines()
               if not PROM_LINE.match(ln)]
        if bad:
            print("malformed exposition lines:", *bad[:5], sep="\n  ")
            return 1
        missing = [s for s in CORE_SERIES if s not in first]
        if missing:
            print("missing core series:", *missing, sep="\n  ")
            return 1

        rid = post().getheader("X-Request-Id")
        for _ in range(4):
            post()
        second = scrape()
        for name in INCREASING:
            v1, v2 = series_total(first, name), series_total(second, name)
            if not v2 > v1:
                print(f"series {name} did not increase: {v1} -> {v2}")
                return 1

        # the span surface answers for a real completed request
        conn.request("GET", f"/span/{rid}")
        resp = conn.getresponse()
        span = json.loads(resp.read())
        assert resp.status == 200, resp.status
        stages = set(span["stages"])
        need = {"queue_wait", "batch_form", "stage", "compute", "drain"}
        if not need <= stages:
            print(f"span {rid} missing stages: {sorted(need - stages)}")
            return 1

        print("metrics smoke ok:",
              f"{len(first.splitlines())} exposition lines,",
              "requests="
              f"{series_total(second, 'synapseml_serving_requests_total'):.0f},",
              f"span stages={sorted(stages)}")
        return 0
    finally:
        cs.stop()


if __name__ == "__main__":
    sys.exit(main())
